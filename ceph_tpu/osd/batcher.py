"""Cross-op TPU stripe batcher — the OSD-level encode coalescer.

This is the framework's "batching point" (SURVEY.md §3.1): where the
reference encodes each write's stripes on the submitting thread inside
ECBackend::try_reads_to_commit (reference src/osd/ECBackend.cc:1939,
via ECUtil::encode's per-stripe loop, src/osd/ECUtil.cc:136-148), a
TPU pays per *device call*, not per stripe — so the win is gathering
stripes from MANY in-flight ops (across PGs, one batcher per OSD) into
ONE batched MXU call.

Mechanics:

* ``submit()`` (called under the PG lock from the EC write pipeline)
  enqueues an encode request keyed by codec geometry and wakes the
  collector.  The submitting thread never blocks on the device.
* The collector thread waits ``ec_tpu_queue_window_us`` from the first
  queued request (or until ``ec_tpu_batch_stripes`` stripes are
  pending) for more ops to arrive, then concatenates each geometry
  group to one ``[N, k, chunk]`` array and issues a single
  ``encode_batch_async`` device call — h2d staging, MXU compute and
  parity d2h overlap across consecutive batches exactly like the
  bench's double buffering.
* Parity is split back per request and each continuation runs in
  submission order (per-PG FIFO holds: the PG pipeline admits one
  encode per PG at a time, and one collector drains batches serially).

Locking: ``submit`` takes only the batcher lock; continuations take
the owning PG's lock while the batcher lock is dropped — no ordering
cycle with the op workers (which take PG lock then ``submit``).

Reference anchors: the op queue this rides behind is the sharded work
queue (reference src/osd/OSD.cc:9612 enqueue_op -> op_shardedwq); the
in-order commit contract it must preserve is ECBackend::check_ops
(reference src/osd/ECBackend.cc:2151-2156).
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from . import ecutil
from ..utils import copytrack
from ..utils import faults as faultlib
from ..utils.device_ledger import DeviceLedgerAccum, overlap_stats


class _Req:
    """One queued encode.  ``data`` may be bytes, bytearray,
    memoryview or a uint8 ndarray — the caller hands over ownership
    and must not mutate the buffer until ``cb`` fires."""

    def __init__(self, ec_impl, sinfo: ecutil.StripeInfo, data,
                 cb: Callable[[Dict[int, bytes]], None], tracked=None):
        self.ec_impl = ec_impl
        self.sinfo = sinfo
        self.data = data
        self.cb = cb
        self.nbytes = ecutil.nbytes_of(data)
        self.nstripes = self.nbytes // sinfo.stripe_width
        self.tracked = tracked       # OpTracker handle (stage events)
        self.t_enq = time.monotonic()
        self.done = False            # cb delivered (guards double-fail)

    def as_array(self, k: int) -> np.ndarray:
        """[nstripes, k, chunk] view of the request buffer — no copy."""
        return ecutil.as_stripe_array(self.data, self.nstripes, k,
                                      self.sinfo.chunk_size)


class _DecReq:
    """One queued reconstruction: rebuild ``want - have`` shard chunks
    from the equal-length chunk buffers in ``have``."""

    def __init__(self, ec_impl, sinfo: ecutil.StripeInfo,
                 have: Dict[int, bytes], want,
                 cb: Callable[[Optional[Dict[int, bytes]]], None]):
        self.ec_impl = ec_impl
        self.sinfo = sinfo
        self.have = have
        self.want = frozenset(want)
        self.cb = cb
        self.done = False
        total = ecutil.nbytes_of(next(iter(have.values())))
        self.nstripes = total // sinfo.chunk_size
        self.t_enq = time.monotonic()


class _DeltaReq:
    """One queued parity-delta encode (sub-stripe overwrite RMW):
    ``delta`` holds old XOR new chunk bytes for the DIRTY data
    columns only, laid out ``[nstripes, D, chunk]`` for
    D = len(dirty_cols).  GF(2^8) linearity makes the parity update
    ``new_parity = old_parity XOR M[:,dirty]·Δdata``, so only the
    dirty columns ride the device — the rider's ``cb`` receives
    {parity_shard_index: Δparity chunk bytes} to XOR into the
    stored parity chunks (store-level ``xor_write``)."""

    def __init__(self, ec_impl, sinfo: ecutil.StripeInfo, delta,
                 dirty_cols,
                 cb: Callable[[Optional[Dict[int, bytes]]], None],
                 tracked=None):
        self.ec_impl = ec_impl
        self.sinfo = sinfo
        self.delta = delta
        self.dirty_cols = tuple(dirty_cols)
        self.cb = cb
        self.tracked = tracked
        self.nbytes = ecutil.nbytes_of(delta)
        self.nstripes = self.nbytes // (
            len(self.dirty_cols) * sinfo.chunk_size)
        self.t_enq = time.monotonic()
        self.done = False

    def as_array(self, ncols: int) -> np.ndarray:
        """[nstripes, D, chunk] view of the delta buffer — no copy."""
        return ecutil.as_stripe_array(self.delta, self.nstripes,
                                      ncols, self.sinfo.chunk_size)


class _BatchTwin:
    """Device-free execution twin with the BATCHED codec API: encode
    and decode run as ONE kernel call over a whole [N, k, chunk]
    stripe batch — through the native C++ GF kernels when the
    toolchain is available, numpy otherwise.  This is what a coalesced
    group executes on when the learned crossover routes it off the
    device: the coalescing win (one call for many ops' stripes) is
    preserved even when the device round trip would lose, where the
    reference encodes stripe-by-stripe on the submitting thread
    (reference src/osd/ECUtil.cc:136-148 per-stripe loop).

    Wraps a jerasure-plugin codec of the same geometry (bit-exact by
    the corpus contract) and exposes ``encode_batch`` /
    ``decode_batch`` like the tpu plugin, so ``ecutil.encode/decode``
    take their batched paths."""

    def __init__(self, base):
        self.base = base
        try:
            from ..ops import native as native_mod
            base.core.backend = native_mod.NativeBackend()
        except Exception:
            pass                     # no toolchain: numpy stays

    def __getattr__(self, name):
        return getattr(self.base, name)

    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        return self.base.core.encode_batch(
            np.asarray(data, dtype=np.uint8))

    def decode_batch(self, present, chunk_len: int):
        arrays = {i: np.asarray(c, dtype=np.uint8)
                  for i, c in present.items()}
        return self.base.core.decode_chunks(arrays, chunk_len)


def _geometry_key(ec_impl, sinfo: ecutil.StripeInfo) -> Tuple:
    """Requests may share one device call iff they encode with the
    same coding matrix over the same chunk size.  The matrix is a
    deterministic function of (plugin, technique, k, m, w,
    packetsize), so that tuple + chunk_size is a sound key even
    across codec instances from different PGs of the same pool."""
    return (type(ec_impl).__name__,
            ec_impl.get_data_chunk_count(),
            ec_impl.get_coding_chunk_count(),
            getattr(ec_impl, "technique", ""),
            getattr(ec_impl, "w", 0),
            getattr(ec_impl, "packetsize", 0),
            sinfo.chunk_size)


class EncodeBatcher:
    """Per-OSD encode coalescer (one collector thread).

    The CPU/device crossover and measured CPU rates are CLASS-level:
    the device and the link are machine properties, so every batcher
    in the process (one per OSD in test clusters; one per daemon in a
    real deployment) shares one learned estimate instead of each
    paying its own slow probe."""

    _cpu_bps: Dict[Tuple, float] = {}        # per geometry, shared
    _min_device_bytes: float = 0.0           # learned crossover, shared
    _pinned_min_device_bytes: float = 0.0    # operator pin (breaker
                                             # close resets TO this)
    _dec_min_device_bytes: float = 0.0       # decode-side crossover;
                                             # 0 = not yet learned ->
                                             # seeded from the encode
                                             # EWMA (_dec_min_bytes)
    _delta_min_device_bytes: float = 0.0     # parity-delta crossover;
                                             # 0 = not yet learned ->
                                             # seeded like the decode
                                             # side (_delta_min_bytes)
    _probe_tick: int = 0                     # shared probe cadence
    _warmed: set = set()                     # geometries prewarmed
    _h2d_bps: float = 0.0                    # warm link rate EWMA, shared
    _dev_bps: Dict[Tuple, float] = {}        # steady-state device
                                             # throughput EWMA per
                                             # geometry (compile/outlier
                                             # rejection in the learner)
    # per-mesh-shape learner state (ISSUE 12): the crossover and link
    # EWMA model the AGGREGATE device+ICI bandwidth, so a dp x sp mesh
    # and a single chip must not share one estimate.  _mesh_key is the
    # (dp, sp) shape the CURRENT class-level scalars belong to (None =
    # single chip); _mesh_state stashes the scalars of every other
    # shape seen, swapped by _rekey_mesh when the live mesh changes.
    _mesh_key: Optional[Tuple] = None
    _mesh_state: Dict[Optional[Tuple], dict] = {}
    # shared idle clocks, seeded by the FIRST batcher construction
    # (None until then): seeding at import would treat process
    # lifetime as device idleness, while re-seeding on every
    # construction would reset the idle-reprobe clock for ALL
    # batchers each time a multi-OSD cluster builds another OSD
    _last_device_ts: Optional[float] = None     # last device activity
    _last_idle_probe_ts: Optional[float] = None
    # device circuit breaker — class-level like the crossover it
    # guards: the device is a machine property, so one OSD's string
    # of dispatch failures should route EVERY in-process batcher's
    # traffic to the CPU twin, not just its own
    _breaker_lock = threading.Lock()
    _breaker_failures: int = 0               # consecutive device errors
    _breaker_open: bool = False
    _breaker_opens: int = 0                  # cumulative open transitions
    _breaker_closes: int = 0                 # cumulative re-admissions

    def __init__(self, conf=None, perf=None, perf_coll=None,
                 recorder=None, contention=None):
        def get(k, d):
            if conf is None:
                return d
            try:
                return conf[k]
            except KeyError:
                return d
        # kept for the live-tuning seam: apply_tuning() re-reads the
        # runtime-tunable knobs from here at safe points (collector
        # loop top + OSD tuner tick) instead of latching them forever
        self.conf = conf
        self.max_stripes = get("ec_tpu_batch_stripes", 1024)
        self.window_s = get("ec_tpu_queue_window_us", 200) / 1e6
        # admission-aware coalescing window: the effective window
        # (dyn_window_s) doubles while submits keep arriving at its
        # expiry (queue pressure -> bigger batches clear the device
        # crossover) and halves back toward the base once a window
        # closes with no new joiners (drained queue -> don't tax
        # latency).  tick_flush() remains the hard cut.
        wmax = get("ec_tpu_queue_window_max_us", 0)
        self.window_base_s = self.window_s
        self.window_max_s = (wmax / 1e6) if wmax > 0 \
            else max(self.window_s * 16, 0.02)
        self.dyn_window_s = self.window_s
        self.window_grows = 0        # admission extensions granted
        self.window_cuts = 0         # drain-driven shrinks
        self.last_queue_depth = 0    # requests in the last dispatch
        self.queue_depth_hwm = 0
        # encode-group occupancy (ISSUE 8): the biggest single group
        # dispatched, in requests and stripes — the shard-per-core
        # regression bar is "concurrent cluster writes coalesce into
        # >=k-stripe groups, not per-PG singletons"
        self.group_reqs_hwm = 0
        self.group_stripes_hwm = 0
        self.bytes_copied = 0        # full-payload copies inside the
                                     # batcher (gathers/concats)
        # adaptive CPU/device routing (ec_tpu_fallback_cpu): a device
        # call pays a fixed dispatch+transfer cost that can dwarf the
        # MXU win on small batches — especially over a slow link.  The
        # crossover is LEARNED: batches below the threshold encode on
        # the CPU twin; the threshold doubles when a device call loses
        # to the predicted CPU time and halves when it wins big.
        self.adaptive_cpu = get("ec_tpu_fallback_cpu", True)
        pin = get("ec_tpu_min_device_bytes", 0)
        if pin:
            # operator-pinned crossover: routing is deterministic from
            # the first op instead of riding the prewarm/learning race
            # (probes + big wins can still lower it at runtime).  The
            # pin is remembered separately so a circuit-breaker close
            # restores the OPERATOR's crossover, not whatever CPU bias
            # the learner accumulated while the device was sick.
            # Deliberately PROCESS-global even though the conf is per
            # instance: the crossover models the machine's device+link,
            # so in a multi-OSD process the last-constructed OSD's pin
            # wins (mixed per-OSD pins in one process are unsupported).
            EncodeBatcher._min_device_bytes = float(pin)
            EncodeBatcher._pinned_min_device_bytes = float(pin)
        self.probe_interval = get("ec_tpu_crossover_probe_interval", 16)
        # a device that served ZERO recent traffic gets re-probed
        # aggressively (one group per idle period) — the 1-in-N tick
        # probe alone starves on a lightly loaded OSD where small
        # batches would otherwise pin the CPU bias forever
        self.idle_reprobe_s = get("ec_tpu_device_idle_reprobe_s", 2.0)
        # collection/dispatch of window N+1 overlaps completion of
        # window N: dispatched groups hand off to a completion worker
        # through a bounded FIFO (depth = groups genuinely in flight
        # on the device; the blocking put is the throttle)
        self.inflight_groups = max(1, get("ec_tpu_inflight_groups", 2))
        # multichip mesh shape (ISSUE 12): 0 = auto (use every visible
        # JAX device, dp x sp factored by the backend); >1 forces the
        # device count, ec_tpu_mesh_sp forces the chunk-width axis.
        # The batcher only FORWARDS the shape — the backend owns mesh
        # construction and the sharded dispatch path.
        self.mesh_devices = get("ec_tpu_mesh_devices", 0)
        self.mesh_sp = get("ec_tpu_mesh_sp", 0)
        self._mesh_noted = False     # mesh_build drained to recorder
        # seed the shared idle clocks ONCE (first batcher built, not
        # at import and not per construction — see the class attrs)
        if EncodeBatcher._last_device_ts is None:
            EncodeBatcher._last_device_ts = time.monotonic()
            EncodeBatcher._last_idle_probe_ts = time.monotonic()
        self.crossover_min = get("ec_tpu_crossover_min_bytes", 64 << 10)
        self.device_error_threshold = get(
            "ec_tpu_device_error_threshold", 3)
        self.device_retry_s = get("ec_tpu_device_retry_ms", 2.0) / 1e3
        # device-phase stall threshold: an h2d or compute-fence phase
        # exceeding this flight-records a device_stall (+ rate-limited
        # auto-dump), mirroring the lock_stall path
        self.phase_stall_s = get(
            "ec_tpu_device_phase_stall_ms", 250.0) / 1e3
        self.prewarm_enabled = get("osd_ec_prewarm", True)
        self.cpu_reqs = 0                        # routed to CPU twin
        self.perf = perf
        # dedicated "ec_batcher" counter subsystem: per-stage
        # histograms + routing/transfer/compile counters, dumped via
        # the admin socket's perf dump and scraped by mgr prometheus
        self.bperf = None
        if perf_coll is not None:
            bp = perf_coll.create("ec_batcher")
            if "queue_wait_us" not in bp._types:
                bp.add_histogram(
                    "queue_wait_us",
                    [50, 100, 200, 500, 1000, 2000, 5000, 20000,
                     100000],
                    "per-request wait from submit to dispatch (us)")
                bp.add_histogram(
                    "batch_stripes",
                    [1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024],
                    "stripes per batched device/twin call")
                bp.add_histogram(
                    "dispatch_ms",
                    [0.5, 1, 2, 5, 10, 25, 50, 100, 250, 1000],
                    "fenced dispatch-to-parity latency (ms)")
                bp.add("h2d_bytes",
                       description="data bytes staged to the device")
                bp.add("d2h_bytes",
                       description="parity bytes fetched back")
                bp.add("device_reqs",
                       description="encode requests routed to device")
                bp.add("cpu_reqs",
                       description="encode requests routed to twin")
                bp.add("coalesced_reqs",
                       description="requests that shared a call")
                bp.add("compile_count",
                       description="JIT compiles paid (prewarm)")
                bp.add_time_avg("compile_seconds",
                                "seconds per JIT compile")
                bp.add("bytes_copied",
                       description="payload bytes copied inside the "
                                   "batcher (shard gathers/concats)")
                bp.add("ec_encode_errors",
                       description="encode/continuation failures "
                                   "(each fails its rider ops with "
                                   "EIO rather than hanging them)")
                bp.add("device_errors",
                       description="classified device dispatch/"
                                   "completion failures (post-retry)")
                bp.add("breaker_open",
                       description="circuit-breaker open transitions "
                                   "(device -> CPU twin routing)")
                bp.add("breaker_close",
                       description="circuit-breaker re-admissions "
                                   "(successful probe closed it)")
            self.bperf = bp
        # flight recorder (utils/flight_recorder.py): every routing
        # verdict / breaker transition / staging stall / encode error
        # appends one ring event; None under unit-test stubs
        self.recorder = recorder
        # decode-side device-fault hook (OSD wires this to the SLO
        # engine's recovery-class error feed): called once per
        # classified decode device failure, after the CPU-twin
        # fallback is already queued.  Must not raise.
        self.on_decode_fault = None
        # "ec_device" perf subsystem — the device-side telemetry PR 5
        # shipped without: crossover routing verdicts BY REASON,
        # StagingPool ring occupancy/stall-grows, h2d link EWMA,
        # inflight-group depth, breaker state.  The timer-wheel
        # fire-lag histogram lives here too (filled by the OSD's
        # wheel callback) so one subsystem answers "what did the
        # device machinery do" in perf dump / prometheus.
        self.dperf = None
        if perf_coll is not None:
            dp = perf_coll.create("ec_device")
            if "route_device" not in dp._types:
                for reason, desc in (
                        ("device", "batches over the crossover -> "
                                   "device"),
                        ("pin", "batches under the operator/"
                                "calibration pin -> twin "
                                "(deterministic)"),
                        ("learned", "batches under the LEARNED "
                                    "crossover -> twin"),
                        ("idle_probe", "idle-device re-probes forced "
                                       "to the device"),
                        ("tick_probe", "1-in-N periodic probes "
                                       "forced to the device"),
                        ("breaker_open", "batches the open breaker "
                                         "routed to the twin"),
                        ("breaker_probe", "re-admission probes "
                                          "through the open "
                                          "breaker")):
                    dp.add(f"route_{reason}",
                           description="routing verdicts: " + desc)
                from ..utils.perf import TYPE_U64
                for g, desc in (
                        ("staging_hits", "stagings served from a "
                                         "reused ring slot"),
                        ("staging_allocs", "staging arrays ever "
                                           "allocated"),
                        ("staging_stall_allocs", "ring grows after "
                                                 "an acquire stall"),
                        ("staging_slots", "staging slots live across "
                                          "all shape rings"),
                        ("staging_in_flight", "staging slots checked "
                                              "out right now"),
                        ("h2d_bps", "h2d link bandwidth EWMA "
                                    "(bytes/s, fenced samples)"),
                        ("inflight_groups_now", "encode groups in "
                                                "flight on the "
                                                "device"),
                        ("inflight_groups_hwm", "high-water mark of "
                                                "in-flight encode "
                                                "groups"),
                        ("breaker_open_now", "device circuit breaker "
                                             "state (1=open)")):
                    dp.add(g, TYPE_U64, desc)
                dp.add("breaker_opened",
                       description="breaker open transitions")
                dp.add("breaker_closed",
                       description="breaker close (re-admission) "
                                   "transitions")
                dp.add_histogram(
                    "timer_fire_lag_us",
                    [100, 500, 1000, 5000, 10000, 25000, 50000,
                     100000, 500000],
                    "timer-wheel fire lag vs requested deadline (us)")
            if "dec_route_device" not in dp._types:
                # decode-route verdicts, mirroring route_* for the
                # read/recovery side (registered under their own
                # guard: dperf instances created by older sessions
                # predate these counters)
                for reason, desc in (
                        ("device", "decode batches over the "
                                   "crossover -> device"),
                        ("learned", "decode batches under the "
                                    "LEARNED crossover -> twin"),
                        ("breaker_open", "decode batches the open "
                                         "breaker routed to the "
                                         "twin"),
                        ("breaker_probe", "decode re-admission "
                                          "probes through the open "
                                          "breaker")):
                    dp.add(f"dec_route_{reason}",
                           description="decode routing verdicts: "
                                       + desc)
            if "dec_route_pin" not in dp._types:
                # the full reason ladder for the collect-time decode
                # router (ISSUE 11): decode groups now route BEFORE
                # dispatch like encode groups, so the pin and the
                # probe taxes apply to them too
                for reason, desc in (
                        ("pin", "decode batches under the operator/"
                                "calibration pin -> twin "
                                "(deterministic)"),
                        ("idle_probe", "idle-device decode re-probes "
                                       "forced to the device"),
                        ("tick_probe", "1-in-N periodic decode "
                                       "probes forced to the "
                                       "device")):
                    dp.add(f"dec_route_{reason}",
                           description="decode routing verdicts: "
                                       + desc)
            if "delta_route_device" not in dp._types:
                # parity-delta RMW routing verdicts (own guard: dperf
                # instances created by older sessions predate these).
                # Same reason ladder as encode/decode — the delta
                # matmul rides the same device and crossover machinery
                for reason, desc in (
                        ("device", "delta batches over the "
                                   "crossover -> device"),
                        ("pin", "delta batches under the operator/"
                                "calibration pin -> twin "
                                "(deterministic)"),
                        ("learned", "delta batches under the LEARNED "
                                    "crossover -> twin"),
                        ("idle_probe", "idle-device delta re-probes "
                                       "forced to the device"),
                        ("tick_probe", "1-in-N periodic delta probes "
                                       "forced to the device"),
                        ("breaker_open", "delta batches the open "
                                         "breaker routed to the "
                                         "twin"),
                        ("breaker_probe", "delta re-admission probes "
                                          "through the open "
                                          "breaker")):
                    dp.add(f"delta_route_{reason}",
                           description="parity-delta routing "
                                       "verdicts: " + desc)
            if "staging_host_bytes_now" not in dp._types:
                # memory-accounting + overlap gauges (ISSUE 10),
                # registered under their own guard: dperf instances
                # created by older sessions predate these
                from ..utils.perf import TYPE_U64
                for g, desc in (
                        ("staging_host_bytes_now", "host staging ring "
                                                   "footprint (bytes)"),
                        ("staging_host_bytes_peak", "peak host staging "
                                                    "ring footprint"),
                        ("dev_matrix_bytes_now", "device-resident "
                                                 "coding matrix bytes "
                                                 "(per-geometry cache)"),
                        ("compile_cache_entries", "compiled-executable "
                                                  "cache occupancy"),
                        ("pipeline_overlap_frac", "fraction of window "
                                                  "wall where group "
                                                  "N+1 h2d overlaps "
                                                  "group N compute")):
                    dp.add(g, TYPE_U64, desc)
                dp.add("device_phase_stalls",
                       description="device phases (h2d / compute "
                                   "fence) that exceeded "
                                   "ec_tpu_device_phase_stall_ms")
            if "mesh_dp" not in dp._types:
                # multichip mesh shape gauges (ISSUE 12), own guard:
                # dperf instances created by older sessions predate
                # these
                from ..utils.perf import TYPE_U64
                for g, desc in (
                        ("mesh_dp", "stripe-batch (dp) axis of the "
                                    "active device mesh (0 = single "
                                    "chip)"),
                        ("mesh_sp", "chunk-width (sp) axis of the "
                                    "active device mesh"),
                        ("mesh_devices", "devices in the active "
                                         "encode/decode mesh")):
                    dp.add(g, TYPE_U64, desc)
            self.dperf = dp
        # device-phase ledger accumulator (utils/device_ledger):
        # per-group stage_acquire..deliver stamps harvested from each
        # AsyncBatch at completion, plus the overlap engine over its
        # recent ring.  Dumped via the OSD's dump_device command and
        # bench's device_waterfall block.
        self.ledger_accum = DeviceLedgerAccum(perf_coll)
        self._ledger_completions = 0
        self._last_backend = None    # codec backend seen at completion
        self._route_reason = None    # last verdict's reason code
        self._staging_stalls_seen = 0
        self._inflight_hwm = 0
        # cumulative per-stage attribution (seconds of request time
        # spent in each pipeline stage; consumed by bench.py's
        # time-attribution line).  Collector-thread writes only.
        self.stage_seconds = {"queue_wait": 0.0, "batch_form": 0.0,
                              "h2d": 0.0, "device": 0.0, "d2h": 0.0}
        self.compile_count = 0
        self.compile_seconds = 0.0
        # collector wakeup condition, wait-time instrumented when the
        # OSD supplies its contention sink (utils/locks.py)
        from ..utils.locks import TimedCondition
        self._cond = TimedCondition("batcher_cond", stats=contention)
        self._queues: Dict[Tuple, List] = {}
        self._pending_stripes = 0
        self._first_enqueue = 0.0
        self._flush_now = False      # tick_flush(): cut the window
        self._stop = False
        # introspection (tested + surfaced via perf counters)
        self.calls = 0               # batched encode calls issued
        self.reqs_total = 0          # requests encoded
        self.reqs_coalesced = 0      # requests that shared a call
        self.cpu_calls = 0           # batched encode calls on the twin
        self.dec_calls = 0           # batched decode calls issued
        self.dec_reqs = 0            # decode requests served
        self.dec_coalesced = 0       # decode requests that shared a call
        self.dec_cpu_reqs = 0        # decode requests on the CPU twin
        self.delta_calls = 0         # batched parity-delta calls issued
        self.delta_reqs = 0          # delta requests served
        self.delta_coalesced = 0     # delta requests that shared a call
        self.delta_cpu_reqs = 0      # delta requests on the CPU twin
        self.encode_errors = 0       # encode/continuation failures
        self.device_errors = 0       # classified device failures
        self._cpu_twins: Dict[Tuple, object] = {}  # device-failure path
        self._dec_threads: List[threading.Thread] = []
        # completion worker: joins dispatched groups in FIFO order so
        # the collector can collect/dispatch the NEXT window while
        # this window's parity is still in flight (segment N+1's h2d
        # overlaps segment N's fanout)
        self._completions: "queue.Queue" = queue.Queue(
            maxsize=self.inflight_groups)
        self._comp_thread = threading.Thread(
            target=self._completion_loop, name="ec-batcher-join",
            daemon=True)
        self._comp_thread.start()
        self._thread = threading.Thread(target=self._run,
                                        name="ec-batcher", daemon=True)
        self._thread.start()

    # -- producer side ---------------------------------------------------
    def submit(self, ec_impl, sinfo: ecutil.StripeInfo, data: bytes,
               cb: Callable[[Dict[int, bytes]], None],
               tracked=None) -> None:
        """Queue one aligned extent for encoding; ``cb`` receives the
        full {shard: bytes} chunk map (data + parity) later, from the
        collector thread.  ``tracked`` is an optional OpTracker handle
        that receives batcher stage events.  Codecs without the
        batched async API don't benefit from coalescing — they encode
        inline."""
        if self._stop or not hasattr(ec_impl, "encode_batch_async"):
            cb(ecutil.encode(sinfo, ec_impl, data))
            return
        req = _Req(ec_impl, sinfo, data, cb, tracked)
        if req.nstripes == 0:
            cb({i: b"" for i in range(ec_impl.get_chunk_count())})
            return
        with self._cond:
            if self._stop:
                stopped = True       # raced shutdown: encode inline
            else:
                stopped = False
                if not self._queues:
                    self._first_enqueue = time.monotonic()
                self._queues.setdefault(
                    ("enc",) + _geometry_key(ec_impl, sinfo),
                    []).append(req)
                self._pending_stripes += req.nstripes
                self._cond.notify()
        if stopped:
            cb(ecutil.encode(sinfo, ec_impl, data))

    def submit_decode(self, ec_impl, sinfo: ecutil.StripeInfo,
                      have: Dict[int, bytes], want,
                      cb: Callable[[Optional[Dict[int, bytes]]], None]
                      ) -> None:
        """Queue a batched reconstruction of ``want - have`` shard
        chunks; ``cb`` later receives {missing_shard: bytes} (or None
        on failure) from the collector thread.

        Decode requests coalesce per (geometry, erasure signature):
        recovery after an OSD loss hammers ONE signature for the whole
        rebuild (every object lost the same shard), which makes it the
        best possible coalescing customer — the reference decodes each
        object's recovery window separately on the submitting thread
        (reference src/osd/ECBackend.cc:414-481
        handle_recovery_read_complete)."""
        missing = set(want) - set(have)
        if not missing:
            # everything wanted was read directly (e.g. a stray held
            # the 'missing' shard): passthrough, like ecutil.decode
            cb({s: (have[s] if isinstance(have[s], bytes)
                    else memoryview(have[s]).cast("B"))
                for s in want})
            return
        stopped = self._stop or not hasattr(ec_impl, "decode_batch")
        req = None
        if not stopped:
            req = _DecReq(ec_impl, sinfo, have, want, cb)
            if req.nstripes == 0:
                cb({s: b"" for s in want})
                return
            key = ("dec", _geometry_key(ec_impl, sinfo),
                   tuple(sorted(have)), tuple(sorted(missing)))
            with self._cond:
                if self._stop:
                    stopped = True   # raced shutdown: decode inline
                else:
                    if not self._queues:
                        self._first_enqueue = time.monotonic()
                    self._queues.setdefault(key, []).append(req)
                    self._pending_stripes += req.nstripes
                    self._cond.notify()
        if stopped:
            try:
                dec = ecutil.decode(sinfo, ec_impl, have, set(want))
            except Exception:
                dec = None
            cb(dec)

    def submit_delta(self, ec_impl, sinfo: ecutil.StripeInfo, delta,
                     dirty_cols,
                     cb: Callable[[Optional[Dict[int, bytes]]], None],
                     tracked=None) -> None:
        """Queue a parity-delta encode for a partial-stripe
        overwrite: ``delta`` is old XOR new chunk bytes for the DIRTY
        data columns only ([nstripes, D, chunk] layout); ``cb`` later
        receives {parity_shard_index: Δparity bytes} (or None on
        failure) from the collector thread — the caller XORs each
        Δparity into the stored parity chunk (``xor_write``).

        Delta requests coalesce per (geometry, dirty-column
        signature): a sub-stripe overwrite workload re-hits few
        signatures (a 4 KiB write always dirties one column), so hot
        small-write traffic lands on a handful of prewarmed compiled
        shapes — the same coalescing economics as recovery."""
        cols = tuple(sorted(dirty_cols))
        stopped = self._stop or \
            not hasattr(ec_impl, "delta_encode_batch_async")
        req = None
        if not stopped:
            req = _DeltaReq(ec_impl, sinfo, delta, cols, cb, tracked)
            if req.nstripes == 0:
                k = ec_impl.get_data_chunk_count()
                m = ec_impl.get_coding_chunk_count()
                cb({k + j: b"" for j in range(m)})
                return
            key = ("delta", _geometry_key(ec_impl, sinfo), cols)
            with self._cond:
                if self._stop:
                    stopped = True   # raced shutdown: compute inline
                else:
                    if not self._queues:
                        self._first_enqueue = time.monotonic()
                    self._queues.setdefault(key, []).append(req)
                    self._pending_stripes += req.nstripes
                    self._cond.notify()
        if stopped:
            try:
                out = self._delta_inline(ec_impl, sinfo, delta, cols)
            except Exception:
                out = None
            cb(out)

    def _delta_inline(self, ec_impl, sinfo: ecutil.StripeInfo,
                      delta, cols) -> Dict[int, memoryview]:
        """Synchronous device-free Δparity (shutdown/no-async-API
        fallback for submit_delta)."""
        cs = sinfo.chunk_size
        nstripes = ecutil.nbytes_of(delta) // (len(cols) * cs)
        arr = np.asarray(ecutil.as_stripe_array(
            delta, nstripes, len(cols), cs), dtype=np.uint8)
        if hasattr(ec_impl, "delta_encode_batch"):
            parity = ec_impl.delta_encode_batch(arr, cols)
        else:
            parity = ec_impl.core.delta_parity(arr, cols)
        k = ec_impl.get_data_chunk_count()
        return {k + j: memoryview(
                    np.ascontiguousarray(parity[:, j])).cast("B")
                for j in range(parity.shape[1])}

    def tick_flush(self) -> None:
        """Cut the coalescing window NOW: everything queued dispatches
        as one group set without waiting out ``window_s``.  The crimson
        reactor calls this at the end of each event-loop tick — every
        stripe submitted by ops processed in the tick has already
        joined the queue, so waiting longer buys no extra coalescing,
        only latency (the classic OSD has no such natural barrier and
        must rely on the time window).  No-op when nothing is queued."""
        with self._cond:
            if self._queues and not self._flush_now:
                self._flush_now = True
                self._cond.notify()

    def prewarm(self, ec_impl, sinfo: ecutil.StripeInfo) -> None:
        """Pay the pool geometry's one-time costs at backend-build
        time instead of on the first client op (the reference pays GF
        table setup at plugin load — jerasure_init.cc:37, preloaded at
        global_init.cc:600): measure the CPU twin's rate for the
        crossover router, and compile the device kernels for the
        batch shapes the coalescer dispatches.  Background thread —
        OSD boot is not stalled; a first op racing the warm simply
        shares the in-progress compile (ChainLRU in-progress marker).
        Once per geometry process-wide."""
        if not self.prewarm_enabled or \
                not hasattr(ec_impl, "encode_batch_async"):
            return
        # configure the backend's device mesh BEFORE any learner
        # seeding: the h2d EWMA and crossover thresholds are keyed per
        # mesh shape (_rekey_mesh), so the seed measurements below
        # must accrue to the shape real dispatches will ride.  An
        # explicit ec_tpu_mesh_sp that cannot shard raises HERE (via
        # the backend's strict prewarm_geometry), not mid-dispatch.
        backend = getattr(getattr(ec_impl, "core", None),
                          "backend", None)
        if backend is not None and hasattr(backend, "configure_mesh"):
            backend.configure_mesh(self.mesh_devices, self.mesh_sp)
            self._note_mesh(backend)
        key = _geometry_key(ec_impl, sinfo)
        with self._cond:
            if key in EncodeBatcher._warmed:
                return
            EncodeBatcher._warmed.add(key)

        def work():
            try:
                # the probe must be REPRESENTATIVE: a tiny buffer
                # under-measures the CPU twin (per-stripe call
                # overhead dominates), which makes device round trips
                # look competitive and mis-routes real batches
                nprobe = max(64, min(self.max_stripes, 256))
                probe = _Req(ec_impl, sinfo,
                             b"\0" * (sinfo.stripe_width * nprobe),
                             lambda _c: None)
                self._cpu_rate(key, probe)
                import jax
                if jax.default_backend() == "cpu":
                    return       # cold compile is a device-tunnel
                                 # property; CPU fallback compiles in
                                 # milliseconds on first use
                k = ec_impl.get_data_chunk_count()
                for nb in sorted({max(1, self.max_stripes),
                                  max(1, self.max_stripes // 2)}):
                    if self._stop:
                        return
                    z = np.zeros((nb, k, sinfo.chunk_size),
                                 dtype=np.uint8)
                    if EncodeBatcher._h2d_bps <= 0:
                        # seed the link estimate from a WARM transfer:
                        # the first device_put pays allocator/runtime
                        # warmup that is NOT link cost — timing it
                        # under-states the link by an order of
                        # magnitude and poisons the h2d/device/d2h
                        # split AND the overlap model's bottleneck
                        # leg.  Transfer once cold (discarded), time
                        # the second.  Real batches keep updating the
                        # EWMA afterwards (staging-pool samples).
                        try:
                            jax.block_until_ready(jax.device_put(z))
                            t0 = time.monotonic()
                            jax.block_until_ready(jax.device_put(z))
                            EncodeBatcher._h2d_bps = z.nbytes / max(
                                time.monotonic() - t0, 1e-9)
                        except Exception:
                            pass
                    t0 = time.monotonic()
                    ec_impl.encode_batch_async(z).wait()  # compile
                    dt = time.monotonic() - t0
                    self.compile_count += 1
                    self.compile_seconds += dt
                    if self.bperf is not None:
                        self.bperf.inc("compile_count")
                        self.bperf.tinc("compile_seconds", dt)
                    # SEED the crossover from a second, POST-compile
                    # call (timing the first would fold seconds of
                    # jit into the estimate and misroute a healthy
                    # device to the CPU twin): on a slow device link
                    # the very first client op must already route to
                    # the CPU twin instead of waiting out a doomed
                    # round trip
                    t0 = time.monotonic()
                    ec_impl.encode_batch_async(z).wait()
                    warm_req = _Req(ec_impl, sinfo, z.tobytes(),  # copycheck: ok - one-time warmup calibration buffer
                                    lambda _c: None)
                    self._learn_crossover(
                        [warm_req], time.monotonic() - t0,
                        trust_win=False)
            except Exception:
                pass             # warms are best-effort
        threading.Thread(target=work, name="ec-prewarm",
                         daemon=True).start()

    def stop(self, drain: float = 30.0) -> None:
        """Stop the collector, draining in-flight device work first
        (up to ``drain`` seconds) so no continuation lands after the
        caller unmounts the store.  Idle batchers return instantly."""
        with self._cond:
            self._stop = True
            self._cond.notify()
        deadline = time.monotonic() + max(drain, 0.1)
        self._thread.join(timeout=max(drain, 0.1))
        # the collector queued a sentinel on exit; the completion
        # worker drains every in-flight group behind it, then stops
        self._comp_thread.join(
            timeout=max(0.1, deadline - time.monotonic()))
        for t in self._dec_threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    def _note_copy(self, nbytes: int, site: str) -> None:
        self.bytes_copied += nbytes
        copytrack.note_copy(nbytes, site)
        if self.bperf is not None:
            self.bperf.inc("bytes_copied", nbytes)

    def _shard_views(self, arr: np.ndarray, parity: np.ndarray,
                     k: int, m: int) -> Dict[int, memoryview]:
        """Per-shard chunk buffers as 1-D byte memoryviews.

        The column gathers (arr[:, i] / parity[:, j]) are the ONE
        unavoidable copy on the encode output side — the
        [nstripes, k, chunk] layout interleaves shards, so each
        shard's chunks must be made contiguous exactly once.  The
        views then ride by reference through the sub-write
        transactions, the wire iovecs and the store with no further
        bytes()/tobytes() round trips.  memoryview compares by
        content, so callers that check chunks against reference
        encodes with == still work.
        """
        out: Dict[int, memoryview] = {}
        copied = 0
        for i in range(k):
            src = arr[:, i]
            col = np.ascontiguousarray(src)
            if col is not src:
                copied += col.nbytes
            out[i] = memoryview(col).cast("B")
        for j in range(m):
            src = parity[:, j]
            col = np.ascontiguousarray(src)
            if col is not src:
                copied += col.nbytes
            out[k + j] = memoryview(col).cast("B")
        if copied:
            self._note_copy(copied, "batcher.shard_gather")
        return out

    # -- collector -------------------------------------------------------
    def apply_tuning(self) -> None:
        """Re-read the runtime-tunable knobs from conf and apply them
        to the LIVE pipeline — no restart, bit-exact output (the
        knobs only shape batching/overlap, never data).  Called at
        the top of every collector cycle and from the OSD tuner tick,
        so a ``conf.set(..., source="runtime")`` (operator or
        autotuner) lands within one window:

        * ``ec_tpu_queue_window_max_us`` — coalescing-window ceiling;
          the dynamic window is re-clamped under ``_cond``.
        * ``ec_tpu_inflight_groups`` — the bounded completion FIFO's
          depth; ``queue.Queue`` checks ``maxsize`` under its own
          mutex on every put, so resizing it there (+ waking blocked
          putters) is the safe seam.
        * ``ec_tpu_staging_depth`` — forwarded to the codec backend's
          StagingPool (jax_engine) when one has been seen.
        """
        conf = self.conf
        if conf is None:
            return
        def get(k, d):
            try:
                return conf[k]
            except Exception:
                return d
        wmax = get("ec_tpu_queue_window_max_us", None)
        if wmax is not None:
            new_max = (wmax / 1e6) if wmax > 0 \
                else max(self.window_base_s * 16, 0.02)
            if new_max != self.window_max_s:
                with self._cond:
                    self.window_max_s = new_max
                    self.dyn_window_s = max(
                        min(self.dyn_window_s, new_max),
                        min(self.window_base_s, new_max))
        infl = get("ec_tpu_inflight_groups", None)
        if infl is not None:
            infl = max(1, int(infl))
            if infl != self.inflight_groups:
                self.inflight_groups = infl
                q = self._completions
                with q.mutex:
                    q.maxsize = infl
                    q.not_full.notify_all()
        depth = get("ec_tpu_staging_depth", None)
        backend = self._last_backend
        if depth is not None and backend is not None and \
                hasattr(backend, "configure_staging"):
            try:
                backend.configure_staging(int(depth))
            except Exception:
                pass

    def _run(self) -> None:
        while True:
            grew = False
            self.apply_tuning()
            with self._cond:
                while not self._queues and not self._stop:
                    self._cond.wait()
                if not self._queues and self._stop:
                    break       # sentinel queued below, OUTSIDE _cond
                # linger for the (admission-aware) window so concurrent
                # ops can join, unless the stripe budget is already met
                deadline = self._first_enqueue + self.dyn_window_s
                hard = self._first_enqueue + self.window_max_s
                seen = self._pending_stripes
                while (not self._stop and not self._flush_now
                       and self._pending_stripes < self.max_stripes):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        if self._pending_stripes > seen \
                                and deadline < hard:
                            # submits kept arriving: extend by one base
                            # window (bounded by window_max_s) and widen
                            # the next cycle's opening window
                            grew = True
                            self.window_grows += 1
                            seen = self._pending_stripes
                            self.dyn_window_s = min(
                                self.dyn_window_s * 2,
                                self.window_max_s)
                            deadline = min(
                                time.monotonic() + self.window_base_s,
                                hard)
                            continue
                        break
                    self._cond.wait(remaining)
                if self._flush_now or not grew:
                    # the queue drained inside the window (or the
                    # reactor tick cut it): shrink back toward the base
                    nw = max(self.window_base_s, self.dyn_window_s / 2)
                    if nw < self.dyn_window_s:
                        self.window_cuts += 1
                        self.dyn_window_s = nw
                queues, self._queues = self._queues, {}
                depth = sum(len(v) for v in queues.values())
                self.last_queue_depth = depth
                if depth > self.queue_depth_hwm:
                    self.queue_depth_hwm = depth
                self._pending_stripes = 0
                self._flush_now = False
            # dispatch EVERY group's device call before joining any:
            # h2d staging + MXU compute of group B overlap group A's
            # parity d2h and continuations (same double buffering the
            # bench uses).  Joins then run on the completion worker —
            # the collector immediately loops back to collect the
            # NEXT window, so up to ``inflight_groups`` encode groups
            # genuinely overlap (segment N+1's h2d during segment N's
            # fanout); the bounded queue's blocking put is the
            # throttle.
            groups = []
            for key, reqs in queues.items():
                if len(reqs) > self.group_reqs_hwm:
                    self.group_reqs_hwm = len(reqs)
                gstripes = sum(r.nstripes for r in reqs)
                if gstripes > self.group_stripes_hwm:
                    self.group_stripes_hwm = gstripes
                if key[0] == "dec":
                    # decode groups route + dispatch HERE like encode
                    # groups (ISSUE 11): the async handle rides the
                    # same bounded completion queue, so decode honors
                    # ec_tpu_inflight_groups and pipelines its h2d
                    # under the previous group's compute
                    groups.append((key, reqs,
                                   self._route_dec_group(key, reqs)))
                    continue
                if key[0] == "delta":
                    # parity-delta groups route + dispatch like
                    # decode groups: async handle on the bounded
                    # completion queue, h2d pipelined under the
                    # previous group's compute
                    groups.append((key, reqs,
                                   self._route_delta_group(key,
                                                           reqs)))
                    continue
                to_cpu = self._route_to_cpu(key, reqs)
                if not to_cpu and self._breaker_blocks():
                    to_cpu = True
                self._note_route(key, reqs, to_cpu)
                groups.append((key, reqs, "cpu" if to_cpu
                               else self._dispatch_group(reqs)))
            for key, reqs, handle in groups:
                self._completions.put((key, reqs, handle,
                                       len(groups)))
                if self.dperf is not None:
                    depth = self._completions.qsize()
                    self.dperf.set("inflight_groups_now", depth)
                    if depth > self._inflight_hwm:
                        self._inflight_hwm = depth
                        self.dperf.set("inflight_groups_hwm", depth)
        # shutdown: queue the completion-worker sentinel with _cond
        # RELEASED — _completions is bounded, and a blocking put while
        # holding the cond would deadlock against any continuation
        # that re-enters submit()/flush() (which take _cond)
        self._completions.put(None)   # worker: drain + exit

    def _completion_loop(self) -> None:
        """FIFO join of dispatched groups (continuations preserve
        submission order — the contract ECBackend::check_ops needs).
        A continuation that raises must not kill the worker — that
        would wedge every EC write on the OSD — so each group is
        fault-isolated to its own ops."""
        while True:
            item = self._completions.get()
            if item is None:
                return
            key, reqs, handle, ngroups = item
            try:
                if handle == "dec":
                    self._complete_group_dec(key, reqs)
                elif handle == "dec_cpu":
                    self._complete_group_dec_twin(key, reqs)
                elif isinstance(handle, tuple) and handle \
                        and handle[0] == "decdev":
                    self._complete_group_dec_dev(
                        key, reqs, handle,
                        trust_win=(ngroups == 1))
                elif handle == "delta_cpu":
                    self._complete_group_delta_twin(key, reqs)
                elif isinstance(handle, tuple) and handle \
                        and handle[0] == "deltadev":
                    self._complete_group_delta_dev(
                        key, reqs, handle,
                        trust_win=(ngroups == 1))
                elif handle == "cpu":
                    self._complete_group_cpu(reqs)
                else:
                    # loss-direction learning runs on EVERY group
                    # (raising the threshold is safe even when
                    # sibling completions inflate dev_time — worst
                    # case we conservatively route small batches to
                    # the CPU twin); the win direction (lowering it)
                    # only trusts single-group cycles
                    self._complete_group(reqs, handle, learn=True,
                                         trust_win=(ngroups == 1))
            except Exception:
                # fail every rider op that has not completed yet: a
                # worker-level fault must surface as EIO on the
                # affected ops, never as a hang
                self._cb_error(reqs)

    def _route_to_cpu(self, key: Tuple, reqs: List[_Req]) -> bool:
        """True when the learned crossover says this batch is too
        small to pay the device round trip."""
        if not self.adaptive_cpu or self._min_device_bytes <= 0:
            self._route_reason = "device"
            return False
        total = sum(r.nbytes for r in reqs)
        if total >= self._min_device_bytes:
            self._route_reason = "device"
            return False
        # idle re-probe: a device that served ZERO traffic for a
        # whole idle period gets one group as a probe IMMEDIATELY —
        # a learned CPU bias with no device activity behind it is
        # exactly the misrouting failure mode (every encode on the
        # twin, crossover never challenged), and on a lightly loaded
        # OSD the 1-in-N tick below may take minutes to fire.  Rate
        # limited to one probe per idle period so an actually-slow
        # device is not hammered.
        #
        # A crossover sitting AT (or under) an operator/calibration
        # pin is not learned bias — it is the measured answer for
        # this machine, and the pin's contract is DETERMINISTIC
        # routing (see __init__) — so below-pin groups take the twin
        # with no probe taxes at all; only a threshold the LEARNER
        # pushed above the pin (or learned from scratch) gets
        # challenged by the idle/tick probes below.
        cls = EncodeBatcher
        if 0 < cls._pinned_min_device_bytes and \
                cls._min_device_bytes <= cls._pinned_min_device_bytes:
            self._route_reason = "pin"
            return True
        now = time.monotonic()
        if self.idle_reprobe_s > 0 and \
                now - cls._last_device_ts > self.idle_reprobe_s and \
                now - cls._last_idle_probe_ts > self.idle_reprobe_s:
            cls._last_idle_probe_ts = now
            self._route_reason = "idle_probe"
            return False
        # periodic probe: route an occasional small batch to the
        # device anyway so the threshold can come back down when the
        # link/device recovers.  The tick is class-level like the
        # crossover it refreshes: 13 in-process OSDs share ONE
        # learned threshold, so they should share one probe cadence
        # instead of each paying its own 1-in-N device round trips
        # (per-instance ticks also mean a primary seeing few ops
        # never probes at all)
        EncodeBatcher._probe_tick += 1
        blocked = EncodeBatcher._probe_tick % self.probe_interval != 0
        self._route_reason = "learned" if blocked else "tick_probe"
        return blocked

    def _breaker_blocks(self) -> bool:
        """True when the open circuit breaker routes this encode
        group to the coalesced CPU twin.  Rides the shared probe tick
        so 1-in-``probe_interval`` groups still reach the device as
        re-admission probes — a probe that completes closes the
        breaker (_device_success)."""
        if not EncodeBatcher._breaker_open:
            return False
        EncodeBatcher._probe_tick += 1
        blocked = EncodeBatcher._probe_tick % self.probe_interval != 0
        self._route_reason = "breaker_open" if blocked \
            else "breaker_probe"
        return blocked

    def _note_route(self, key: Tuple, reqs: List[_Req],
                    to_cpu: bool) -> None:
        """Publish one routing verdict: reason-coded counter in the
        ec_device subsystem + one flight-recorder event.  Collector
        thread only — no locking beyond the perf counters' own."""
        reason = self._route_reason or \
            ("learned" if to_cpu else "device")
        self._route_reason = None
        if self.dperf is not None and \
                f"route_{reason}" in self.dperf._types:
            self.dperf.inc(f"route_{reason}")
        rec = self.recorder
        if rec is not None:
            rec.note("route", reason=reason,
                     to="cpu" if to_cpu else "device",
                     bytes=sum(r.nbytes for r in reqs),
                     reqs=len(reqs),
                     crossover=int(EncodeBatcher._min_device_bytes))

    def _device_failure(self, kind: str) -> None:
        """Record one classified device failure (post-retry); opens
        the breaker after ``ec_tpu_device_error_threshold``
        consecutive failures."""
        self.device_errors += 1
        if self.bperf is not None:
            self.bperf.inc("device_errors")
        opened = False
        cls = EncodeBatcher
        with cls._breaker_lock:
            cls._breaker_failures += 1
            if not cls._breaker_open and \
                    cls._breaker_failures >= self.device_error_threshold:
                cls._breaker_open = True
                cls._breaker_opens += 1
                opened = True
        rec = self.recorder
        if rec is not None:
            rec.note("device_error", error=kind,
                     failures=cls._breaker_failures,
                     breaker_opened=opened)
        if kind == "decode":
            hook = self.on_decode_fault
            if hook is not None:
                try:
                    hook()
                except Exception:
                    pass             # telemetry must not kill decode
        if opened:
            if self.bperf is not None:
                self.bperf.inc("breaker_open")
            if self.dperf is not None:
                self.dperf.inc("breaker_opened")
                self.dperf.set("breaker_open_now", 1)
            # breaker-open is an incident: dump the recent routing/
            # error evidence while it is still in the ring
            if rec is not None:
                rec.note("breaker", state="open", cause=kind)
                rec.auto_dump("breaker-open")

    def _device_success(self) -> None:
        """A device call completed: clear the consecutive-failure
        run; if this was a probe through an open breaker, re-admit
        the device."""
        cls = EncodeBatcher
        cls._last_device_ts = time.monotonic()
        if not cls._breaker_failures and not cls._breaker_open:
            return                   # hot path: nothing to clear
        closed = False
        with cls._breaker_lock:
            cls._breaker_failures = 0
            if cls._breaker_open:
                cls._breaker_open = False
                cls._breaker_closes += 1
                closed = True
        if closed:
            # re-admission must come with FRESH routing stats: while
            # the breaker was open every group encoded on the twin
            # and the learner could only accumulate CPU bias, so the
            # crossover snaps back to the operator's pin (or fully
            # unlearned) and the device gets re-tried on its merits
            cls._min_device_bytes = cls._pinned_min_device_bytes
            cls._dec_min_device_bytes = 0.0   # re-seed from encode
            cls._delta_min_device_bytes = 0.0
            cls._dev_bps = {}
            if self.bperf is not None:
                self.bperf.inc("breaker_close")
            if self.dperf is not None:
                self.dperf.inc("breaker_closed")
                self.dperf.set("breaker_open_now", 0)
            if self.recorder is not None:
                self.recorder.note("breaker", state="closed",
                                   crossover=int(
                                       cls._min_device_bytes))

    def _cb_error(self, reqs=None) -> None:
        """Report a continuation/encode failure.  During shutdown the
        op is already dead (teardown races deliver into an unmounting
        OSD — e.g. 'store not mounted'), so stay quiet rather than
        spraying tracebacks over the console and bench output.

        When ``reqs`` is given, every request that has not seen its
        callback yet gets ``cb(None)`` so its write op fails with EIO
        back through the EC backend instead of hanging until the
        client op timeout."""
        if not self._stop:
            import traceback
            traceback.print_exc()
            self.encode_errors += 1
            if self.bperf is not None:
                self.bperf.inc("ec_encode_errors")
            # a client op is about to die with EIO — flight-record
            # the failure and dump the evidence around it (the chaos
            # soak's "client error" incident trigger)
            if self.recorder is not None:
                self.recorder.note("encode_error",
                                   reqs=len(reqs or ()))
                self.recorder.auto_dump("client-encode-error")
        for r in (reqs or ()):
            if r.done:
                continue
            r.done = True
            try:
                r.cb(None)
            except Exception:
                pass                 # op teardown races

    @classmethod
    def reset_learning(cls) -> None:
        """Forget the shared crossover/rates and breaker state
        (tests; ops can call it after a hardware change)."""
        cls._min_device_bytes = 0.0
        cls._pinned_min_device_bytes = 0.0
        cls._dec_min_device_bytes = 0.0
        cls._delta_min_device_bytes = 0.0
        cls._probe_tick = 0
        cls._cpu_bps = {}
        cls._dev_bps = {}
        cls._warmed = set()
        cls._h2d_bps = 0.0
        cls._mesh_state = {}
        cls._mesh_key = None
        cls._last_device_ts = time.monotonic()
        cls._last_idle_probe_ts = time.monotonic()
        cls.reset_breaker()

    @classmethod
    def reset_breaker(cls) -> None:
        """Zero the breaker state/counters WITHOUT forgetting the
        learned crossover (bench runs isolate their breaker stats but
        keep the routing calibration)."""
        with cls._breaker_lock:
            cls._breaker_failures = 0
            cls._breaker_open = False
            cls._breaker_opens = 0
            cls._breaker_closes = 0

    @classmethod
    def _rekey_mesh(cls, key: Optional[Tuple]) -> None:
        """Swap the shared routing/link learner scalars to the state
        belonging to mesh shape ``key`` ((dp, sp), or None for single
        chip).  The h2d EWMA and the crossover thresholds model the
        AGGREGATE device+ICI bandwidth of the active mesh — carrying a
        single-chip estimate into a 4x2 mesh (or back) misroutes every
        batch until the learner recovers.  The outgoing shape's state
        is stashed, so flipping back restores what was learned."""
        if key == cls._mesh_key:
            return
        cls._mesh_state[cls._mesh_key] = {
            "h2d_bps": cls._h2d_bps,
            "min_device_bytes": cls._min_device_bytes,
            "pinned_min_device_bytes": cls._pinned_min_device_bytes,
            "dec_min_device_bytes": cls._dec_min_device_bytes,
            "delta_min_device_bytes": cls._delta_min_device_bytes,
            "dev_bps": dict(cls._dev_bps),
        }
        st = cls._mesh_state.get(key)
        if st is not None:
            cls._h2d_bps = st["h2d_bps"]
            cls._min_device_bytes = st["min_device_bytes"]
            cls._pinned_min_device_bytes = st["pinned_min_device_bytes"]
            cls._dec_min_device_bytes = st["dec_min_device_bytes"]
            cls._delta_min_device_bytes = st.get(
                "delta_min_device_bytes", 0.0)
            cls._dev_bps = dict(st["dev_bps"])
        # first time on this shape: keep the current scalars as the
        # seed (a mesh is at worst as fast as one of its chips)
        cls._mesh_key = key

    def _note_mesh(self, backend) -> None:
        """Fold the backend's active mesh into the batcher's
        telemetry: rekey the learner state to the mesh shape, set the
        mesh_* gauges, and (once) drain the backend's mesh_build
        events into the flight recorder so a misconfigured mesh is
        diagnosable from the admin socket."""
        info = None
        try:
            info = backend.mesh_info()
        except Exception:
            pass
        key = (info["dp"], info["sp"]) if info else None
        EncodeBatcher._rekey_mesh(key)
        dp = self.dperf
        if dp is not None and "mesh_dp" in dp._types:
            dp.set("mesh_dp", info["dp"] if info else 0)
            dp.set("mesh_sp", info["sp"] if info else 0)
            dp.set("mesh_devices", info["n_devices"] if info else 0)
        rec = self.recorder
        if rec is not None and not self._mesh_noted:
            self._mesh_noted = True
            for ev in list(getattr(backend, "mesh_events", ()) or ()):
                rec.note("mesh_build",
                         dp=ev.get("dp"), sp=ev.get("sp"),
                         n_devices=ev.get("n_devices"),
                         device_ids=ev.get("device_ids"))

    def _cpu_rate(self, key: Tuple, req: _Req) -> float:
        """CPU twin throughput for this geometry, measured once on
        real data (bytes/sec); shared process-wide."""
        rate = self._cpu_bps.get(key)
        if rate is None:
            t0 = time.monotonic()
            self._cpu_encode(req)
            dt = max(time.monotonic() - t0, 1e-6)
            rate = req.nbytes / dt
            EncodeBatcher._cpu_bps[key] = rate
        return rate

    def _complete_group_cpu(self, reqs: List[_Req]) -> None:
        """Coalesced device-free encode: the whole group's stripes go
        through ONE batched kernel call on the _BatchTwin (native C++
        when available) — the coalescing win survives CPU routing."""
        t_form = time.monotonic()
        t_wall = time.time()
        self._account_queue_wait(reqs, t_form)
        for r in reqs:
            if r.tracked is not None:
                r.tracked.mark_event("ec:batch_dispatched")
        chunks_list: Optional[List] = None
        try:
            sinfo = reqs[0].sinfo
            k = reqs[0].ec_impl.get_data_chunk_count()
            m = reqs[0].ec_impl.get_coding_chunk_count()
            twin = self.cpu_twin(reqs[0].ec_impl, sinfo)
            arrs = [r.as_array(k) for r in reqs]
            if len(arrs) > 1:
                batch = np.concatenate(arrs, axis=0)
                self._note_copy(batch.nbytes, "batcher.batch_concat")
            else:
                batch = arrs[0]
            parity = twin.encode_batch(batch)
            self.cpu_calls += 1
            # twin encode is pure compute: no transfer legs
            self.stage_seconds["device"] += \
                time.monotonic() - t_form
            # twin groups still fold into the device waterfall: a
            # coarse two-stamp ledger keyed device=-1 (host), so
            # dump_device and the bench attribution account for every
            # group regardless of routing.  No h2d/d2h stamps — the
            # whole interval charges to the compute fence — and the
            # overlap engine ignores negative device ids (a host
            # group has no transfer to hide under compute).
            t_done = time.time()
            self._observe_device_ledger(
                {"stage_acquire": t_wall, "compute_start": t_wall,
                 "compute_done": t_done, "deliver": t_done,
                 "device": -1, "bytes": int(batch.nbytes),
                 "stripes": int(batch.shape[0]), "group": "encode"})
            if self.bperf is not None:
                self.bperf.hinc("batch_stripes", batch.shape[0])
                self.bperf.inc("cpu_reqs", len(reqs))
                if len(reqs) > 1:
                    self.bperf.inc("coalesced_reqs", len(reqs))
            if len(reqs) > 1:
                self.reqs_coalesced += len(reqs)
                if self.perf is not None:
                    self.perf.inc("ec_batch_coalesced", len(reqs))
            chunks_list = []
            off = 0
            for r, arr in zip(reqs, arrs):
                p = parity[off:off + r.nstripes]
                off += r.nstripes
                chunks_list.append(
                    self._shard_views(arr, p, k, m))
        except Exception:
            chunks_list = None
        if chunks_list is None:
            # twin trouble: per-request fallback (still device-free)
            chunks_list = []
            for r in reqs:
                try:
                    chunks = self._cpu_encode(r)
                    self.cpu_calls += 1
                except Exception:
                    self._cb_error()
                    chunks = None
                chunks_list.append(chunks)
        for r, chunks in zip(reqs, chunks_list):
            self.reqs_total += 1
            self.cpu_reqs += 1
            try:
                r.done = True
                r.cb(chunks)
            except Exception:
                self._cb_error()

    def _complete_group_dec(self, key: Tuple,
                            reqs: List[_DecReq]) -> None:
        """One batched reconstruction for every decode request of one
        (geometry, erasure-signature) group.  Routing mirrors the
        encode side: below the learned crossover the batch decodes on
        the _BatchTwin (one native C++ call); above it, on the device
        codec's signature-cached compiled kernel.  Device round trips
        run on their OWN thread — a congested-tunnel decode stalling
        the collector would block every pending encode group behind
        it (the encode path likewise dispatches all groups before
        joining any)."""
        sinfo = reqs[0].sinfo
        total = sum(sum(ecutil.nbytes_of(v) for v in r.have.values())
                    for r in reqs)
        impl = None
        if (self.adaptive_cpu and self._dec_min_bytes() > 0 and
                total < self._dec_min_bytes()) or \
                self._breaker_blocks():
            try:
                impl = self.cpu_twin(reqs[0].ec_impl, sinfo)
            except Exception:
                impl = None
        on_twin = impl is not None
        # publish the verdict (and consume _route_reason so a decode
        # probe through the breaker cannot leak its reason into the
        # next encode group's _note_route)
        reason = self._route_reason
        self._route_reason = None
        if reason is None:
            reason = "learned" if on_twin else "device"
        if self.dperf is not None and \
                f"dec_route_{reason}" in self.dperf._types:
            self.dperf.inc(f"dec_route_{reason}")
        if impl is None:
            impl = reqs[0].ec_impl
        if on_twin:
            self._exec_group_dec(key, reqs, impl, on_twin)
        else:
            t = threading.Thread(
                target=self._exec_group_dec,
                args=(key, reqs, impl, on_twin),
                name="ec-dec-dev", daemon=True)
            # tracked so stop() can honor its drain contract (no
            # continuation after the caller unmounts the store)
            self._dec_threads = [x for x in self._dec_threads
                                 if x.is_alive()] + [t]
            t.start()

    def _exec_group_dec(self, key: Tuple, reqs: List[_DecReq],
                        impl, on_twin: bool) -> None:
        sinfo = reqs[0].sinfo
        cs = sinfo.chunk_size
        have_ids, missing = key[2], key[3]
        try:
            present = {
                s: (np.concatenate(
                    [ecutil.as_stripe_array(r.have[s], r.nstripes,
                                            1, cs)
                     .reshape(r.nstripes, cs) for r in reqs], axis=0)
                    if len(reqs) > 1 else
                    ecutil.as_stripe_array(
                        reqs[0].have[s], reqs[0].nstripes, 1, cs)
                    .reshape(-1, cs))
                for s in have_ids}
        except Exception:
            present = None           # malformed input, not a device
                                     # fault: per-request fallback
        rec = None
        if present is not None:
            try:
                if not on_twin:
                    faultlib.registry().hit(faultlib.DEVICE_DISPATCH)
                t0 = time.time()
                rec = impl.decode_batch(present, cs)
                # decode_batch is a fenced synchronous call, so the
                # group ledger is coarse: the whole interval charges
                # to the compute fence.  Still keyed and accumulated
                # like encode groups so the read path shows up in
                # the device waterfall; twin-routed groups carry
                # device=-1 (host lane, excluded from overlap).
                t1 = time.time()
                led = {"stage_acquire": t0, "compute_start": t0,
                       "compute_done": t1, "deliver": t1,
                       "group": "decode"}
                if on_twin:
                    led["device"] = -1
                self._observe_device_ledger(led)
                if not on_twin:
                    self._device_success()
            except Exception:
                rec = None
                if not on_twin:
                    self._device_failure("decode")
        if rec is None:
            # group decode trouble: per-request fallback
            for r in reqs:
                try:
                    dec = ecutil.decode(sinfo, r.ec_impl, r.have,
                                        set(r.want))
                except Exception:
                    self._cb_error()
                    dec = None
                self.dec_reqs += 1
                try:
                    r.done = True
                    r.cb(dec)
                except Exception:
                    self._cb_error()
            return
        self.dec_calls += 1
        self.dec_reqs += len(reqs)
        if len(reqs) > 1:
            self.dec_coalesced += len(reqs)
        if on_twin:
            self.dec_cpu_reqs += len(reqs)
        if self.perf is not None:
            self.perf.inc("ec_dec_batch_calls")
            if len(reqs) > 1:
                self.perf.inc("ec_dec_batch_coalesced", len(reqs))
        off = 0
        for r in reqs:
            # reconstructed shards from the batched call; wanted
            # shards that were read directly pass through (same
            # contract as ecutil.decode)
            out = {}
            for s in r.want:
                if s in missing:
                    # row slice of a contiguous [B, cs] batch result:
                    # ascontiguousarray is a no-copy view here, and
                    # the memoryview rides downstream by reference
                    out[s] = memoryview(np.ascontiguousarray(
                        rec[s][off:off + r.nstripes])).cast("B")
                else:
                    h = r.have[s]
                    out[s] = h if isinstance(h, bytes) else \
                        memoryview(h).cast("B")
            off += r.nstripes
            try:
                r.done = True
                r.cb(out)
            except Exception:
                self._cb_error()

    # -- decode device pipeline (ISSUE 11 tentpole) --------------------
    def _dec_min_bytes(self) -> float:
        """The decode-side crossover threshold.  Decode keeps its own
        learned value (recovery moves k survivor chunks IN per erased
        chunk OUT, so its transfer economics differ from encode's
        k-in/m-out), but until decode groups have taught it anything
        it is SEEDED from the encode EWMA — the device and link are
        the same hardware, so encode's measurement beats flying
        blind on the first rebuild window."""
        cls = EncodeBatcher
        if cls._dec_min_device_bytes > 0:
            return cls._dec_min_device_bytes
        return cls._min_device_bytes

    def _route_dec_group(self, key: Tuple, reqs: List[_DecReq]):
        """Collect-time routing + dispatch for one decode group.
        Returns the completion-queue handle:

        * ``("decdev", handles, t_disp, in_bytes)`` — async device
          dispatch in flight (joined by _complete_group_dec_dev);
        * ``"dec_cpu"`` — routed to the CPU twin (verdict already
          published);
        * ``"dec"`` — legacy completion-time path for codecs without
          the async decode API (routing happens there)."""
        impl = reqs[0].ec_impl
        sup = getattr(impl, "decode_async_supported", None)
        if sup is None or not hasattr(impl, "decode_batch_async"):
            return "dec"
        try:
            if not sup():
                return "dec"
        except Exception:
            return "dec"
        to_cpu = self._route_to_cpu_dec(key, reqs)
        if not to_cpu and self._breaker_blocks():
            to_cpu = True
        self._note_route_dec(key, reqs, to_cpu)
        if to_cpu:
            return "dec_cpu"
        handle = self._dispatch_group_dec(key, reqs)
        if handle is None:
            return "dec_cpu"         # dispatch failed: twin serves
        return ("decdev",) + handle

    def _route_to_cpu_dec(self, key: Tuple,
                          reqs: List[_DecReq]) -> bool:
        """_route_to_cpu with the decode-side crossover: same
        pin/idle-probe/tick-probe ladder (shared probe cadence and
        idle clocks — the device is one machine property), judged
        against _dec_min_bytes()."""
        if not self.adaptive_cpu:
            self._route_reason = "device"
            return False
        thr = self._dec_min_bytes()
        if thr <= 0:
            self._route_reason = "device"
            return False
        cs = reqs[0].sinfo.chunk_size
        total = sum(r.nstripes * cs * len(r.have) for r in reqs)
        if total >= thr:
            self._route_reason = "device"
            return False
        cls = EncodeBatcher
        if 0 < cls._pinned_min_device_bytes and \
                thr <= cls._pinned_min_device_bytes:
            self._route_reason = "pin"
            return True
        now = time.monotonic()
        if self.idle_reprobe_s > 0 and \
                now - cls._last_device_ts > self.idle_reprobe_s and \
                now - cls._last_idle_probe_ts > self.idle_reprobe_s:
            cls._last_idle_probe_ts = now
            self._route_reason = "idle_probe"
            return False
        cls._probe_tick += 1
        blocked = cls._probe_tick % self.probe_interval != 0
        self._route_reason = "learned" if blocked else "tick_probe"
        return blocked

    def _note_route_dec(self, key: Tuple, reqs: List[_DecReq],
                        to_cpu: bool) -> None:
        """Publish one decode routing verdict (dec_route_* counter +
        flight-recorder event).  Collector thread only."""
        reason = self._route_reason or \
            ("learned" if to_cpu else "device")
        self._route_reason = None
        if self.dperf is not None and \
                f"dec_route_{reason}" in self.dperf._types:
            self.dperf.inc(f"dec_route_{reason}")
        rec = self.recorder
        if rec is not None:
            cs = reqs[0].sinfo.chunk_size
            rec.note("dec_route", reason=reason,
                     to="cpu" if to_cpu else "device",
                     bytes=sum(r.nstripes * cs * len(r.have)
                               for r in reqs),
                     reqs=len(reqs),
                     crossover=int(self._dec_min_bytes()))

    def _dispatch_group_dec(self, key: Tuple, reqs: List[_DecReq]):
        """Issue the async device decode for one (geometry,
        erasure-signature) group: concat every request's survivor
        chunks into one [B, cs] stack per shard id and dispatch
        tile-by-tile through decode_batch_async (signature-cached
        combined recovery rows, StagingPool staging, full seven-phase
        ledger).  Returns (handles, t_disp, in_bytes) or None on
        dispatch failure."""
        t_form = time.monotonic()
        self._account_queue_wait(reqs, t_form)
        sinfo = reqs[0].sinfo
        cs = sinfo.chunk_size
        have_ids = key[2]
        try:
            present = {
                s: (np.concatenate(
                    [ecutil.as_stripe_array(r.have[s], r.nstripes,
                                            1, cs)
                     .reshape(r.nstripes, cs) for r in reqs], axis=0)
                    if len(reqs) > 1 else
                    ecutil.as_stripe_array(
                        reqs[0].have[s], reqs[0].nstripes, 1, cs)
                    .reshape(-1, cs))
                for s in have_ids}
            if len(reqs) > 1:
                self._note_copy(sum(v.nbytes
                                    for v in present.values()),
                                "batcher.dec_batch_concat")
        except Exception:
            # malformed request payload: NOT a device fault (must not
            # trip the breaker) — the twin path fails the bad rider
            # per-request and still serves its group-mates
            return None
        nstripes = sum(r.nstripes for r in reqs)
        in_bytes = sum(v.nbytes for v in present.values())
        tile = max(1, self.max_stripes)
        handles = None
        delay = self.device_retry_s
        for attempt in range(3):
            try:
                faultlib.registry().hit(faultlib.DEVICE_DISPATCH)
                handles = [
                    reqs[0].ec_impl.decode_batch_async(
                        {s: v[i:i + tile]
                         for s, v in present.items()}, cs)
                    for i in range(0, nstripes, tile)]
                break
            except Exception:
                handles = None
                if attempt < 2 and delay > 0:
                    time.sleep(min(delay, 0.1))
                    delay *= 2
        if handles is None:
            self._device_failure("dispatch")
            return None
        t_disp = time.monotonic()
        EncodeBatcher._last_device_ts = t_disp
        self.stage_seconds["batch_form"] += t_disp - t_form
        if self.bperf is not None:
            self.bperf.hinc("batch_stripes", nstripes)
            self.bperf.inc("h2d_bytes", in_bytes)
        return (handles, t_disp, in_bytes)

    def _complete_group_dec_twin(self, key: Tuple,
                                 reqs: List[_DecReq]) -> None:
        """Execute a decode group the collect-time router already
        sent to the CPU (verdict published there — no re-routing)."""
        impl = None
        try:
            impl = self.cpu_twin(reqs[0].ec_impl, reqs[0].sinfo)
        except Exception:
            impl = None
        on_twin = impl is not None
        if impl is None:
            impl = reqs[0].ec_impl
        self._exec_group_dec(key, reqs, impl, on_twin)

    def _complete_group_dec_dev(self, key: Tuple,
                                reqs: List[_DecReq], handle,
                                trust_win: bool = True) -> None:
        """Join one in-flight device decode group: the decode twin of
        _complete_group.  Harvests the seven-phase ledgers, folds h2d
        samples into the link EWMA, teaches the decode crossover, and
        splits the reconstructed [B, cs] stacks back to each rider's
        callback.  Device trouble falls the WHOLE group back to the
        batched CPU twin — zero client errors."""
        _tag, handles, t_disp, in_bytes = handle
        sinfo = reqs[0].sinfo
        missing = key[3]
        rec = None
        dev_time = None
        out_bytes = 0
        try:
            faultlib.registry().hit(faultlib.DEVICE_COMPLETION)
            parts = [h.wait() for h in handles]
            rec = parts[0] if len(parts) == 1 else {
                e: np.concatenate([p[e] for p in parts], axis=0)
                for e in parts[0]}
            out_bytes = sum(v.nbytes for v in rec.values())
            dev_time = time.monotonic() - t_disp
            self._device_success()
            for h in handles:
                hb = getattr(h, "h2d_bytes", 0)
                hs = getattr(h, "h2d_seconds", 0.0)
                if hb and hs > 0:
                    bps = hb / hs
                    EncodeBatcher._h2d_bps = bps \
                        if EncodeBatcher._h2d_bps <= 0 else (
                            0.7 * EncodeBatcher._h2d_bps + 0.3 * bps)
        except Exception:
            rec = None
            self._device_failure("completion")
        if rec is None:
            self._complete_group_dec_twin(key, reqs)
            return
        if self.adaptive_cpu:
            self._learn_crossover_dec(key, reqs, dev_time, in_bytes,
                                      out_bytes, trust_win=trust_win)
        self.dec_calls += 1
        self.dec_reqs += len(reqs)
        if len(reqs) > 1:
            self.dec_coalesced += len(reqs)
        if self.perf is not None:
            self.perf.inc("ec_dec_batch_calls")
            if len(reqs) > 1:
                self.perf.inc("ec_dec_batch_coalesced", len(reqs))
        # fenced-window stage split, same link-rate model as encode
        h2d_s = d2h_s = 0.0
        if self._h2d_bps > 0:
            h2d_s = min(dev_time, in_bytes / self._h2d_bps)
            d2h_s = min(dev_time - h2d_s, out_bytes / self._h2d_bps)
        self.stage_seconds["h2d"] += h2d_s
        self.stage_seconds["d2h"] += d2h_s
        self.stage_seconds["device"] += max(
            0.0, dev_time - h2d_s - d2h_s)
        if self.bperf is not None:
            self.bperf.hinc("dispatch_ms", dev_time * 1e3)
            self.bperf.inc("d2h_bytes", out_bytes)
            self.bperf.inc("device_reqs", len(reqs))
            if len(reqs) > 1:
                self.bperf.inc("coalesced_reqs", len(reqs))
        for h in handles:
            # a mesh dispatch carries one ledger clone per chip
            # (AsyncBatch.ledgers); single-chip keeps the scalar
            leds = getattr(h, "ledgers", None) or \
                [getattr(h, "ledger", None)]
            for led in leds:
                if led is not None:
                    led["group"] = "decode"
                self._observe_device_ledger(led)
        self._publish_device_telemetry(reqs[0].ec_impl)
        off = 0
        for r in reqs:
            out = {}
            for s in r.want:
                if s in missing:
                    out[s] = memoryview(np.ascontiguousarray(
                        rec[s][off:off + r.nstripes])).cast("B")
                else:
                    hv = r.have[s]
                    out[s] = hv if isinstance(hv, bytes) else \
                        memoryview(hv).cast("B")
            off += r.nstripes
            try:
                r.done = True
                r.cb(out)
            except Exception:
                self._cb_error()

    def _cpu_rate_dec(self, key: Tuple,
                      reqs: List[_DecReq]) -> float:
        """CPU twin DECODE throughput for this geometry (bytes of
        survivor input per second), measured once on real data;
        shared process-wide like _cpu_rate."""
        rk = ("dec", key[1])
        rate = EncodeBatcher._cpu_bps.get(rk)
        if rate is None:
            r = reqs[0]
            cs = r.sinfo.chunk_size
            try:
                twin = self.cpu_twin(r.ec_impl, r.sinfo)
                present = {
                    s: ecutil.as_stripe_array(r.have[s], r.nstripes,
                                              1, cs)
                    .reshape(r.nstripes, cs) for s in r.have}
                t0 = time.monotonic()
                twin.decode_batch(present, cs)
                dt = max(time.monotonic() - t0, 1e-6)
                rate = sum(v.nbytes for v in present.values()) / dt
            except Exception:
                # no twin: fall back to the encode-side measurement
                # (same matmul cost model) rather than guessing
                rate = EncodeBatcher._cpu_bps.get(key[1], 0.0)
            EncodeBatcher._cpu_bps[rk] = rate
        return rate

    def _learn_crossover_dec(self, key: Tuple, reqs: List[_DecReq],
                             dev_time: float, in_bytes: int,
                             out_bytes: int,
                             trust_win: bool = True) -> None:
        """_learn_crossover for decode groups: the same pipelined
        cost model (max of the h2d/compute/d2h legs vs the CPU twin's
        prediction) and compile/outlier rejection, but moving the
        DECODE-side threshold and its own per-geometry device-rate
        EWMA bucket."""
        try:
            cls = EncodeBatcher
            rk = ("dec", key[1])
            cpu_rate = max(self._cpu_rate_dec(key, reqs), 1.0)
            cpu_pred = in_bytes / cpu_rate
            h2d_s = d2h_s = 0.0
            if cls._h2d_bps > 0:
                h2d_s = min(dev_time, in_bytes / cls._h2d_bps)
                d2h_s = min(max(0.0, dev_time - h2d_s),
                            out_bytes / cls._h2d_bps)
            compute_s = max(0.0, dev_time - h2d_s - d2h_s)
            rate = cls._dev_bps.get(rk, 0.0)
            if rate > 0 and compute_s > 5.0 * (in_bytes / rate) \
                    and compute_s > 1e-3:
                return               # compile/stall outlier
            if compute_s > 0:
                bps = in_bytes / compute_s
                cls._dev_bps[rk] = bps if rate <= 0 else (
                    0.7 * rate + 0.3 * bps)
            dev_pipe = max(h2d_s, compute_s, d2h_s) \
                if (h2d_s or d2h_s) else dev_time
            cur = self._dec_min_bytes()
            if dev_pipe > cpu_pred:
                cls._dec_min_device_bytes = max(
                    cur, dev_pipe * cpu_rate / 2, self.crossover_min)
            elif trust_win and dev_pipe < cpu_pred / 2 and cur > 0:
                cls._dec_min_device_bytes = min(cur, in_bytes / 2)
        except Exception:
            pass                     # learning is best-effort

    # -- parity-delta device pipeline (sub-stripe overwrite RMW) -------
    def _delta_min_bytes(self) -> float:
        """The parity-delta crossover threshold.  Delta keeps its own
        learned value (a delta call moves D dirty columns IN per m
        parity columns OUT — different transfer economics from both
        encode and decode), seeded from the encode EWMA until delta
        groups have taught it anything, same as the decode side."""
        cls = EncodeBatcher
        if cls._delta_min_device_bytes > 0:
            return cls._delta_min_device_bytes
        return cls._min_device_bytes

    def _route_delta_group(self, key: Tuple,
                           reqs: List["_DeltaReq"]):
        """Collect-time routing + dispatch for one parity-delta
        group.  Returns the completion-queue handle:

        * ``("deltadev", handles, t_disp, in_bytes)`` — async device
          dispatch in flight (joined by _complete_group_delta_dev);
        * ``"delta_cpu"`` — routed to (or falling back on) the CPU
          twin's delta_parity."""
        impl = reqs[0].ec_impl
        sup = getattr(impl, "delta_async_supported", None)
        if sup is None or \
                not hasattr(impl, "delta_encode_batch_async"):
            return "delta_cpu"
        try:
            if not sup():
                return "delta_cpu"
        except Exception:
            return "delta_cpu"
        to_cpu = self._route_to_cpu_delta(key, reqs)
        if not to_cpu and self._breaker_blocks():
            to_cpu = True
        self._note_route_delta(key, reqs, to_cpu)
        if to_cpu:
            return "delta_cpu"
        handle = self._dispatch_group_delta(key, reqs)
        if handle is None:
            return "delta_cpu"       # dispatch failed: twin serves
        return ("deltadev",) + handle

    def _route_to_cpu_delta(self, key: Tuple,
                            reqs: List["_DeltaReq"]) -> bool:
        """_route_to_cpu with the delta-side crossover: same
        pin/idle-probe/tick-probe ladder (shared probe cadence and
        idle clocks), judged against _delta_min_bytes() over the
        group's dirty-column input bytes."""
        if not self.adaptive_cpu:
            self._route_reason = "device"
            return False
        thr = self._delta_min_bytes()
        if thr <= 0:
            self._route_reason = "device"
            return False
        total = sum(r.nbytes for r in reqs)
        if total >= thr:
            self._route_reason = "device"
            return False
        cls = EncodeBatcher
        if 0 < cls._pinned_min_device_bytes and \
                thr <= cls._pinned_min_device_bytes:
            self._route_reason = "pin"
            return True
        now = time.monotonic()
        if self.idle_reprobe_s > 0 and \
                now - cls._last_device_ts > self.idle_reprobe_s and \
                now - cls._last_idle_probe_ts > self.idle_reprobe_s:
            cls._last_idle_probe_ts = now
            self._route_reason = "idle_probe"
            return False
        cls._probe_tick += 1
        blocked = cls._probe_tick % self.probe_interval != 0
        self._route_reason = "learned" if blocked else "tick_probe"
        return blocked

    def _note_route_delta(self, key: Tuple, reqs: List["_DeltaReq"],
                          to_cpu: bool) -> None:
        """Publish one delta routing verdict (delta_route_* counter
        + flight-recorder event).  Collector thread only."""
        reason = self._route_reason or \
            ("learned" if to_cpu else "device")
        self._route_reason = None
        if self.dperf is not None and \
                f"delta_route_{reason}" in self.dperf._types:
            self.dperf.inc(f"delta_route_{reason}")
        rec = self.recorder
        if rec is not None:
            rec.note("delta_route", reason=reason,
                     to="cpu" if to_cpu else "device",
                     bytes=sum(r.nbytes for r in reqs),
                     reqs=len(reqs),
                     dirty_cols=len(key[2]),
                     crossover=int(self._delta_min_bytes()))

    def _dispatch_group_delta(self, key: Tuple,
                              reqs: List["_DeltaReq"]):
        """Issue the async device delta-matmul for one (geometry,
        dirty-column signature) group: concat every request's
        [nstripes, D, chunk] delta stack and dispatch tile-by-tile
        through delta_encode_batch_async (prewarmed compiled shape,
        StagingPool staging, full seven-phase ledger).  Returns
        (handles, t_disp, in_bytes) or None on dispatch failure."""
        t_form = time.monotonic()
        self._account_queue_wait(reqs, t_form)
        cols = key[2]
        try:
            arrs = [r.as_array(len(cols)) for r in reqs]
            if len(arrs) > 1:
                batch = np.concatenate(arrs, axis=0)
                self._note_copy(batch.nbytes,
                                "batcher.delta_batch_concat")
            else:
                batch = np.asarray(arrs[0])
        except Exception:
            # malformed request payload: NOT a device fault (must not
            # trip the breaker) — the twin path fails the bad rider
            # per-request and still serves its group-mates
            return None
        in_bytes = batch.nbytes
        tile = max(1, self.max_stripes)
        handles = None
        delay = self.device_retry_s
        for attempt in range(3):
            try:
                faultlib.registry().hit(faultlib.DEVICE_DISPATCH)
                handles = [
                    reqs[0].ec_impl.delta_encode_batch_async(
                        batch[i:i + tile], cols)
                    for i in range(0, batch.shape[0], tile)]
                break
            except Exception:
                handles = None
                if attempt < 2 and delay > 0:
                    time.sleep(min(delay, 0.1))
                    delay *= 2
        if handles is None:
            self._device_failure("dispatch")
            return None
        t_disp = time.monotonic()
        EncodeBatcher._last_device_ts = t_disp
        self.stage_seconds["batch_form"] += t_disp - t_form
        if self.bperf is not None:
            self.bperf.hinc("batch_stripes", batch.shape[0])
            self.bperf.inc("h2d_bytes", in_bytes)
        for r in reqs:
            if r.tracked is not None:
                r.tracked.mark_event("ec:delta_dispatched")
        return (handles, t_disp, in_bytes)

    def _complete_group_delta_twin(self, key: Tuple,
                                   reqs: List["_DeltaReq"]) -> None:
        """Coalesced device-free Δparity: the whole group's delta
        stripes go through ONE CodecCore.delta_parity call on the
        CPU twin (native GF kernels when available) — the coalescing
        win survives CPU routing, like _complete_group_cpu."""
        t_form = time.monotonic()
        t_wall = time.time()
        self._account_queue_wait(reqs, t_form)
        cols = key[2]
        k = reqs[0].ec_impl.get_data_chunk_count()
        parity = None
        arrs = None
        try:
            twin = self.cpu_twin(reqs[0].ec_impl, reqs[0].sinfo)
            arrs = [r.as_array(len(cols)) for r in reqs]
            if len(arrs) > 1:
                batch = np.concatenate(arrs, axis=0)
                self._note_copy(batch.nbytes,
                                "batcher.delta_batch_concat")
            else:
                batch = np.asarray(arrs[0])
            parity = twin.core.delta_parity(
                np.asarray(batch, dtype=np.uint8), cols)
        except Exception:
            parity = None
        if parity is None:
            # twin trouble: per-request fallback (still device-free)
            for r in reqs:
                try:
                    out = self._delta_inline(r.ec_impl, r.sinfo,
                                             r.delta, cols)
                except Exception:
                    self._cb_error()
                    out = None
                self.delta_reqs += 1
                self.delta_cpu_reqs += 1
                try:
                    r.done = True
                    r.cb(out)
                except Exception:
                    self._cb_error()
            return
        self.delta_calls += 1
        self.cpu_calls += 1
        self.delta_cpu_reqs += len(reqs)
        self.stage_seconds["device"] += time.monotonic() - t_form
        # twin groups still fold into the device waterfall: coarse
        # two-stamp host-lane ledger, same idiom as the encode twin
        t_done = time.time()
        self._observe_device_ledger(
            {"stage_acquire": t_wall, "compute_start": t_wall,
             "compute_done": t_done, "deliver": t_done,
             "device": -1, "bytes": int(sum(r.nbytes for r in reqs)),
             "stripes": int(sum(r.nstripes for r in reqs)),
             "group": "delta"})
        if self.bperf is not None:
            self.bperf.hinc("batch_stripes",
                            sum(r.nstripes for r in reqs))
            self.bperf.inc("cpu_reqs", len(reqs))
            if len(reqs) > 1:
                self.bperf.inc("coalesced_reqs", len(reqs))
        if len(reqs) > 1:
            self.delta_coalesced += len(reqs)
        self._deliver_delta(reqs, parity, k)

    def _complete_group_delta_dev(self, key: Tuple,
                                  reqs: List["_DeltaReq"], handle,
                                  trust_win: bool = True) -> None:
        """Join one in-flight device delta group: harvest the
        seven-phase ledgers, fold h2d samples into the link EWMA,
        teach the delta crossover, and split the [B, m, chunk]
        Δparity stack back to each rider.  Device trouble falls the
        WHOLE group back to the batched CPU twin — zero client
        errors."""
        _tag, handles, t_disp, in_bytes = handle
        k = reqs[0].ec_impl.get_data_chunk_count()
        parity = None
        dev_time = None
        out_bytes = 0
        try:
            faultlib.registry().hit(faultlib.DEVICE_COMPLETION)
            parts = [np.asarray(h.wait()) for h in handles]
            parity = parts[0] if len(parts) == 1 \
                else np.concatenate(parts, axis=0)
            out_bytes = parity.nbytes
            dev_time = time.monotonic() - t_disp
            self._device_success()
            for h in handles:
                hb = getattr(h, "h2d_bytes", 0)
                hs = getattr(h, "h2d_seconds", 0.0)
                if hb and hs > 0:
                    bps = hb / hs
                    EncodeBatcher._h2d_bps = bps \
                        if EncodeBatcher._h2d_bps <= 0 else (
                            0.7 * EncodeBatcher._h2d_bps + 0.3 * bps)
        except Exception:
            parity = None
            self._device_failure("completion")
        if parity is None:
            self._complete_group_delta_twin(key, reqs)
            return
        if self.adaptive_cpu:
            self._learn_crossover_delta(key, reqs, dev_time,
                                        in_bytes, out_bytes,
                                        trust_win=trust_win)
        self.delta_calls += 1
        if len(reqs) > 1:
            self.delta_coalesced += len(reqs)
        if self.perf is not None:
            self.perf.inc("ec_delta_batch_calls")
            if len(reqs) > 1:
                self.perf.inc("ec_delta_batch_coalesced", len(reqs))
        # fenced-window stage split, same link-rate model as decode
        h2d_s = d2h_s = 0.0
        if self._h2d_bps > 0:
            h2d_s = min(dev_time, in_bytes / self._h2d_bps)
            d2h_s = min(dev_time - h2d_s, out_bytes / self._h2d_bps)
        self.stage_seconds["h2d"] += h2d_s
        self.stage_seconds["d2h"] += d2h_s
        self.stage_seconds["device"] += max(
            0.0, dev_time - h2d_s - d2h_s)
        if self.bperf is not None:
            self.bperf.hinc("dispatch_ms", dev_time * 1e3)
            self.bperf.inc("d2h_bytes", out_bytes)
            self.bperf.inc("device_reqs", len(reqs))
            if len(reqs) > 1:
                self.bperf.inc("coalesced_reqs", len(reqs))
        for h in handles:
            leds = getattr(h, "ledgers", None) or \
                [getattr(h, "ledger", None)]
            for led in leds:
                if led is not None:
                    led["group"] = "delta"
                self._observe_device_ledger(led)
        self._publish_device_telemetry(reqs[0].ec_impl)
        self._deliver_delta(reqs, parity, k)

    def _deliver_delta(self, reqs: List["_DeltaReq"],
                       parity: np.ndarray, k: int) -> None:
        """Split a [B, m, chunk] Δparity stack back per rider and
        fire callbacks with {parity_shard_index: Δparity bytes}.
        The per-parity column gathers are the one unavoidable copy
        (the stack interleaves shards) — the memoryviews then ride
        by reference into the xor_write sub-transactions."""
        m = parity.shape[1]
        off = 0
        copied = 0
        for r in reqs:
            p = parity[off:off + r.nstripes]
            off += r.nstripes
            out = {}
            for j in range(m):
                src = p[:, j]
                col = np.ascontiguousarray(src)
                if col is not src:
                    copied += col.nbytes
                out[k + j] = memoryview(col).cast("B")
            self.delta_reqs += 1
            try:
                r.done = True
                r.cb(out)
            except Exception:
                self._cb_error()
        if copied:
            self._note_copy(copied, "batcher.delta_shard_gather")

    def _cpu_rate_delta(self, key: Tuple,
                        reqs: List["_DeltaReq"]) -> float:
        """CPU twin Δparity throughput for this geometry (bytes of
        dirty-column input per second), measured once on real data;
        shared process-wide like _cpu_rate.  One bucket per geometry
        (not per dirty signature): the GF matmul's bytes/s is nearly
        independent of D — compute and input both scale with D."""
        rk = ("delta", key[1])
        rate = EncodeBatcher._cpu_bps.get(rk)
        if rate is None:
            r = reqs[0]
            cols = key[2]
            try:
                twin = self.cpu_twin(r.ec_impl, r.sinfo)
                arr = np.asarray(r.as_array(len(cols)),
                                 dtype=np.uint8)
                t0 = time.monotonic()
                twin.core.delta_parity(arr, cols)
                dt = max(time.monotonic() - t0, 1e-6)
                rate = r.nbytes / dt
            except Exception:
                # no twin: fall back to the encode-side measurement
                # (same matmul cost model) rather than guessing
                rate = EncodeBatcher._cpu_bps.get(key[1], 0.0)
            EncodeBatcher._cpu_bps[rk] = rate
        return rate

    def _learn_crossover_delta(self, key: Tuple,
                               reqs: List["_DeltaReq"],
                               dev_time: float, in_bytes: int,
                               out_bytes: int,
                               trust_win: bool = True) -> None:
        """_learn_crossover for delta groups: same pipelined cost
        model (max of the h2d/compute/d2h legs vs the CPU twin's
        prediction) and compile/outlier rejection, moving the
        DELTA-side threshold and its own per-geometry device-rate
        EWMA bucket."""
        try:
            cls = EncodeBatcher
            rk = ("delta", key[1])
            cpu_rate = max(self._cpu_rate_delta(key, reqs), 1.0)
            cpu_pred = in_bytes / cpu_rate
            h2d_s = d2h_s = 0.0
            if cls._h2d_bps > 0:
                h2d_s = min(dev_time, in_bytes / cls._h2d_bps)
                d2h_s = min(max(0.0, dev_time - h2d_s),
                            out_bytes / cls._h2d_bps)
            compute_s = max(0.0, dev_time - h2d_s - d2h_s)
            rate = cls._dev_bps.get(rk, 0.0)
            if rate > 0 and compute_s > 5.0 * (in_bytes / rate) \
                    and compute_s > 1e-3:
                return               # compile/stall outlier
            if compute_s > 0:
                bps = in_bytes / compute_s
                cls._dev_bps[rk] = bps if rate <= 0 else (
                    0.7 * rate + 0.3 * bps)
            dev_pipe = max(h2d_s, compute_s, d2h_s) \
                if (h2d_s or d2h_s) else dev_time
            cur = self._delta_min_bytes()
            if dev_pipe > cpu_pred:
                cls._delta_min_device_bytes = max(
                    cur, dev_pipe * cpu_rate / 2, self.crossover_min)
            elif trust_win and dev_pipe < cpu_pred / 2 and cur > 0:
                cls._delta_min_device_bytes = min(cur, in_bytes / 2)
        except Exception:
            pass                     # learning is best-effort

    def _learn_crossover(self, reqs: List[_Req],
                         dev_time: float,
                         trust_win: bool = True) -> None:
        """Compare the device's PIPELINED cost model against the CPU
        twin's predicted time for the same bytes and move the routing
        threshold: lost -> raise it past this batch size; won big ->
        lower it.

        Two properties matter here (both were misrouting bugs):

        * the fenced ``dev_time`` is a SERIAL h2d + MXU + d2h sum,
          but in steady state consecutive batches overlap those legs
          (async dispatch, double-buffered staging) — so the cost the
          router should compare is ``max(h2d, compute, d2h)``, not
          the sum.  Judging the device on the serial number makes a
          device that wins pipelined look like it loses, and 100% of
          traffic lands on the twin.
        * a call that paid jit compile (or any one-off stall) must
          not teach the router: if this call ran far slower than the
          geometry's own steady-state EWMA predicts, it is an
          outlier, not a measurement."""
        try:
            cls = EncodeBatcher
            key = _geometry_key(reqs[0].ec_impl, reqs[0].sinfo)
            total = sum(r.nbytes for r in reqs)
            m_over_k = (reqs[0].ec_impl.get_coding_chunk_count()
                        / max(1, reqs[0].ec_impl.get_data_chunk_count()))
            cpu_rate = max(self._cpu_rate(key, reqs[0]), 1.0)
            cpu_pred = total / cpu_rate
            # split the fenced window into transfer legs (measured
            # warm link rate) and the compute remainder
            h2d_s = d2h_s = 0.0
            if cls._h2d_bps > 0:
                h2d_s = min(dev_time, total / cls._h2d_bps)
                d2h_s = min(max(0.0, dev_time - h2d_s),
                            total * m_over_k / cls._h2d_bps)
            compute_s = max(0.0, dev_time - h2d_s - d2h_s)
            # compile/outlier rejection BEFORE the EWMA absorbs it:
            # against this geometry's steady-state compute rate, a
            # 5x-slower call is a one-off (jit compile, allocator
            # stall, scheduler hiccup), not the device's cost
            rate = cls._dev_bps.get(key, 0.0)
            if rate > 0 and compute_s > 5.0 * (total / rate) \
                    and compute_s > 1e-3:
                return
            if compute_s > 0:
                bps = total / compute_s
                cls._dev_bps[key] = bps if rate <= 0 else (
                    0.7 * rate + 0.3 * bps)
            # the PIPELINED device cost: legs overlap across batches,
            # so the sustained per-batch cost is the slowest leg
            dev_pipe = max(h2d_s, compute_s, d2h_s) \
                if (h2d_s or d2h_s) else dev_time
            if dev_pipe > cpu_pred:
                # the device LOST even with overlap credited: set the
                # crossover where the CPU would have taken as long as
                # this call's bottleneck leg (one losing measurement
                # teaches the whole region below it, not just 2x this
                # batch — bursts must not need a convergence loop)
                cls._min_device_bytes = max(
                    self._min_device_bytes,
                    dev_pipe * cpu_rate / 2, self.crossover_min)
            elif trust_win and dev_pipe < cpu_pred / 2 and \
                    self._min_device_bytes > 0:
                cls._min_device_bytes = min(
                    self._min_device_bytes, total / 2)
        except Exception:
            pass                     # learning is best-effort

    # -- decode-side routing (consumed by ECBackend reads/recovery) ----
    def route_decode(self, nbytes: int) -> bool:
        """prefer_cpu() with the measurement the encode side has had
        since PR 5: one reason-coded ``dec_route_*`` verdict counter
        per call (device / learned / breaker_open) so perf dump and
        prometheus answer WHERE decode traffic actually ran.  True
        means the caller should take the CPU twin."""
        if EncodeBatcher._breaker_open:
            reason, to_cpu = "breaker_open", True
        elif (self.adaptive_cpu and self._dec_min_bytes() > 0
                and nbytes < self._dec_min_bytes()):
            reason, to_cpu = "learned", True
        else:
            reason, to_cpu = "device", False
        if self.dperf is not None and \
                f"dec_route_{reason}" in self.dperf._types:
            self.dperf.inc(f"dec_route_{reason}")
        return to_cpu

    def prefer_cpu(self, nbytes: int) -> bool:
        """Should a ``nbytes``-sized codec call avoid the device?
        Shares the encode path's learned crossover — the fixed
        dispatch/transfer cost is the same either direction."""
        if EncodeBatcher._breaker_open:
            return True              # breaker open: device is sick
        return (self.adaptive_cpu and self._min_device_bytes > 0
                and nbytes < self._min_device_bytes)

    def cpu_twin(self, ec_impl, sinfo: ecutil.StripeInfo):
        """The device-free BATCHED twin for this geometry (cached);
        bit-exact by the corpus contract, executing whole stripe
        batches in one native C++ kernel call (_BatchTwin).  Used by
        encode/decode fallback and by read/recovery decode when
        prefer_cpu() says the device round trip loses."""
        key = _geometry_key(ec_impl, sinfo)
        twin = self._cpu_twins.get(key)
        if twin is None:
            from ..ec import registry as ecreg
            prof = {"k": str(ec_impl.get_data_chunk_count()),
                    "m": str(ec_impl.get_coding_chunk_count()),
                    "technique": getattr(ec_impl, "technique",
                                         "reed_sol_van"),
                    "w": str(getattr(ec_impl, "w", 8))}
            ps = getattr(ec_impl, "packetsize", 0)
            if ps:
                prof["packetsize"] = str(ps)
            twin = _BatchTwin(ecreg.instance().factory("jerasure",
                                                       prof))
            self._cpu_twins[key] = twin
        return twin

    def _cpu_encode(self, req: _Req) -> Dict[int, bytes]:
        """Device-free encode through the CPU twin; jerasure lacks the
        batched device API, so ecutil.encode takes its per-stripe CPU
        loop."""
        twin = self.cpu_twin(req.ec_impl, req.sinfo)
        return ecutil.encode(req.sinfo, twin, req.data)

    def _dispatch_group(self, reqs: List[_Req]):
        """Issue one async device call for every request of one
        geometry; returns (arrs, async_handle) or None on dispatch
        failure (completion falls back to per-request CPU encode).
        On a multi-device host the backend's staged dispatch itself
        lays each group out with a NamedSharding(dp, None, sp) over
        the device mesh (jax_engine._staged_put + parallel/mesh.py
        kernels), so this production path rides every local chip —
        one dispatch is still ONE sharded GF matmul, and the ledger
        fans out per chip (AsyncBatch.ledgers)."""
        t_form = time.monotonic()
        self._account_queue_wait(reqs, t_form)
        try:
            k = reqs[0].ec_impl.get_data_chunk_count()
            arrs = [r.as_array(k) for r in reqs]
            if len(arrs) > 1:
                batch = np.concatenate(arrs, axis=0)
                self._note_copy(batch.nbytes, "batcher.batch_concat")
            else:
                batch = arrs[0]
        except Exception:
            # malformed request payload/geometry: NOT a device fault
            # (must not trip the breaker) — completion falls back to
            # per-request CPU encode, which fails the bad rider with
            # EIO and still serves its group-mates
            return None
        # tile oversized batches at max_stripes: bounds per-call
        # device memory AND caps the largest compiled batch shape
        # at bucket(max_stripes) — the shape prewarm() compiles —
        # so a burst can never hit a never-seen (slow-compiling)
        # shape mid-benchmark.  All tiles dispatch before any
        # wait: h2d/MXU/d2h still overlap tile-to-tile.
        tile = max(1, self.max_stripes)
        handles = None
        delay = self.device_retry_s
        for attempt in range(3):
            try:
                faultlib.registry().hit(faultlib.DEVICE_DISPATCH)
                handles = [
                    reqs[0].ec_impl.encode_batch_async(
                        batch[i:i + tile])
                    for i in range(0, batch.shape[0], tile)]
                break
            except Exception:
                # classified device dispatch failure: transient until
                # proven otherwise — retry with capped backoff before
                # charging the breaker
                handles = None
                if attempt < 2 and delay > 0:
                    time.sleep(min(delay, 0.1))
                    delay *= 2
        if handles is None:
            self._device_failure("dispatch")
            return None
        t_disp = time.monotonic()
        EncodeBatcher._last_device_ts = t_disp
        self.stage_seconds["batch_form"] += t_disp - t_form
        if self.bperf is not None:
            self.bperf.hinc("batch_stripes", batch.shape[0])
            self.bperf.inc("h2d_bytes", batch.nbytes)
        for r in reqs:
            if r.tracked is not None:
                r.tracked.mark_event("ec:batch_dispatched")
        return (arrs, handles, t_disp)

    def _publish_device_telemetry(self, ec_impl) -> None:
        """Refresh the ec_device staging/link gauges from the codec's
        StagingPool after a device completion (completion worker
        only).  A stall-grow since the last look is an incident-grade
        event: it means the ring wedged past STALL_S and the pool
        grew to protect the write path — flight-record it."""
        dp = self.dperf
        rec = self.recorder
        backend = getattr(getattr(ec_impl, "core", None),
                          "backend", None)
        if backend is not None and hasattr(backend, "memory_stats"):
            # remembered so dump_device can report memory accounting
            # even on a daemon with no perf plumbing (unit stubs)
            self._last_backend = backend
        if backend is not None and hasattr(backend, "mesh_info"):
            # keep the mesh gauges / learner keying current even when
            # prewarm was skipped (ec_tpu_prewarm=false paths)
            self._note_mesh(backend)
        if dp is None and rec is None:
            return
        pool = getattr(backend, "staging", None)
        if pool is not None:
            try:
                st = pool.stats()
            except Exception:
                st = None
            if st:
                if dp is not None:
                    dp.set("staging_hits", st["hits"])
                    dp.set("staging_allocs", st["allocs"])
                    dp.set("staging_stall_allocs",
                           st["stall_allocs"])
                    dp.set("staging_slots", st["slots"])
                    dp.set("staging_in_flight", st["in_flight"])
                if st["stall_allocs"] > self._staging_stalls_seen:
                    self._staging_stalls_seen = st["stall_allocs"]
                    if rec is not None:
                        rec.note("staging", event="stall_grow",
                                 stall_allocs=st["stall_allocs"],
                                 slots=st["slots"])
        if dp is not None:
            dp.set("h2d_bps", int(EncodeBatcher._h2d_bps))
            if self._last_backend is not None and \
                    "staging_host_bytes_now" in dp._types:
                try:
                    mem = self._last_backend.memory_stats()
                except Exception:
                    mem = None
                if mem:
                    dp.set("staging_host_bytes_now",
                           mem["staging_host_bytes"])
                    dp.set("staging_host_bytes_peak",
                           mem["staging_host_bytes_peak"])
                    dp.set("dev_matrix_bytes_now",
                           mem["dev_matrix_bytes"])
                    dp.set("compile_cache_entries",
                           mem["compile_cache_entries"])

    def _observe_device_ledger(self, led) -> None:
        """Fold one completed group's device-phase ledger into the
        accumulator; stall-check the h2d and compute-fence phases
        (the two that bound the pipeline), mirroring lock_stall.
        Completion-worker only.  Must not raise."""
        if not led:
            return
        try:
            self.ledger_accum.observe(led)
        except Exception:
            return
        self._ledger_completions += 1
        dp = self.dperf
        if dp is not None and self._ledger_completions % 32 == 0 and \
                "pipeline_overlap_frac" in dp._types:
            # periodic refresh: sorting the 256-deep recent ring on
            # every completion is not free, 1-in-32 is
            try:
                ov = overlap_stats(self.ledger_accum.recent())
                dp.set("pipeline_overlap_frac",
                       ov["pipeline_overlap_frac"])
            except Exception:
                pass
        stall = self.phase_stall_s
        if stall <= 0:
            return
        for phase, a, b in (("h2d", "h2d_start", "h2d_done"),
                            ("fence", "compute_start",
                             "compute_done")):
            ta, tb = led.get(a), led.get(b)
            if ta is None or tb is None or tb - ta < stall:
                continue
            if dp is not None and "device_phase_stalls" in dp._types:
                dp.inc("device_phase_stalls")
            rec = self.recorder
            if rec is not None:
                rec.note("device_stall", phase=phase,
                         ms=round((tb - ta) * 1e3, 3),
                         device=led.get("device", 0),
                         bytes=led.get("bytes", 0))
                rec.auto_dump("device-phase-stall")

    def device_dump(self) -> dict:
        """``dump_device`` admin-command payload: the per-phase
        waterfall (with p50/p99 + overlap verdict), memory
        accounting, and the batcher's coarse stage split."""
        dump = self.ledger_accum.dump()
        mem = None
        backend = self._last_backend
        if backend is not None:
            try:
                mem = backend.memory_stats()
            except Exception:
                mem = None
        mesh = None
        if backend is not None and hasattr(backend, "mesh_info"):
            try:
                mesh = backend.mesh_info()
            except Exception:
                mesh = None
        return {
            "ledger": dump,
            "overlap": dump.get("overlap"),
            "memory": mem,
            "mesh": mesh,
            "stage_seconds": dict(self.stage_seconds),
            "breaker_open": bool(EncodeBatcher._breaker_open),
        }

    def device_trace_block(self) -> dict:
        """Raw recent group ledgers (+ memory snapshot) for the
        unified trace exporter's per-device phase lanes."""
        mem = None
        backend = self._last_backend
        if backend is not None:
            try:
                mem = backend.memory_stats()
            except Exception:
                mem = None
        return {"ledgers": self.ledger_accum.recent(), "memory": mem}

    def _account_queue_wait(self, reqs: List[_Req],
                            now: float) -> None:
        for r in reqs:
            w = max(0.0, now - r.t_enq)
            self.stage_seconds["queue_wait"] += w
            if self.bperf is not None:
                self.bperf.hinc("queue_wait_us", w * 1e6)

    def _complete_group(self, reqs: List[_Req], handle,
                        learn: bool = True,
                        trust_win: bool = True) -> None:
        k = reqs[0].ec_impl.get_data_chunk_count()
        m = reqs[0].ec_impl.get_coding_chunk_count()
        parity = None
        dev_time = None
        if handle is not None:
            arrs, async_tiles, t_dispatch = handle
            try:
                faultlib.registry().hit(faultlib.DEVICE_COMPLETION)
                parts = [t.wait() for t in async_tiles]
                parity = parts[0] if len(parts) == 1 \
                    else np.concatenate(parts, axis=0)
                dev_time = time.monotonic() - t_dispatch
                self._device_success()
                # fold any fenced WARM h2d samples the staging pool
                # took during this batch into the shared link EWMA —
                # real-traffic measurements keep the h2d/device/d2h
                # split and the overlap model honest
                for t in async_tiles:
                    hb = getattr(t, "h2d_bytes", 0)
                    hs = getattr(t, "h2d_seconds", 0.0)
                    if hb and hs > 0:
                        bps = hb / hs
                        EncodeBatcher._h2d_bps = bps \
                            if EncodeBatcher._h2d_bps <= 0 else (
                                0.7 * EncodeBatcher._h2d_bps
                                + 0.3 * bps)
            except Exception:
                # classified completion failure (a dispatched handle
                # cannot be re-waited, so no retry here — the CPU
                # twin serves the group and the breaker learns)
                parity = None
                self._device_failure("completion")
        if parity is None:
            # device trouble: encode each request on a REAL CPU path
            # (a jerasure twin of the same geometry — bit-exact by the
            # corpus contract, and free of the broken device).  A
            # request that still cannot encode gets cb(None) so the
            # write op fails with EIO instead of hanging.
            for r in reqs:
                try:
                    chunks = self._cpu_encode(r)
                except Exception:
                    self._cb_error()
                    chunks = None
                try:
                    r.done = True
                    r.cb(chunks)
                except Exception:
                    self._cb_error()
            return
        if dev_time is not None and self.adaptive_cpu and learn:
            self._learn_crossover(reqs, dev_time,
                                  trust_win=trust_win)
        self.calls += 1
        self.reqs_total += len(reqs)
        nstripes = sum(r.nstripes for r in reqs)
        if len(reqs) > 1:
            self.reqs_coalesced += len(reqs)
        if self.perf is not None:
            self.perf.inc("ec_batch_calls")
            self.perf.inc("ec_batch_stripes", nstripes)
            if len(reqs) > 1:
                self.perf.inc("ec_batch_coalesced", len(reqs))
        if dev_time is not None:
            # split the fenced device window into transfer vs compute
            # using the link rate prewarm measured; without a
            # measurement the whole window is charged to "device"
            in_bytes = sum(r.nbytes for r in reqs)
            out_bytes = parity.nbytes
            h2d_s = d2h_s = 0.0
            if self._h2d_bps > 0:
                h2d_s = min(dev_time, in_bytes / self._h2d_bps)
                d2h_s = min(dev_time - h2d_s,
                            out_bytes / self._h2d_bps)
            self.stage_seconds["h2d"] += h2d_s
            self.stage_seconds["d2h"] += d2h_s
            self.stage_seconds["device"] += max(
                0.0, dev_time - h2d_s - d2h_s)
            if self.bperf is not None:
                self.bperf.hinc("dispatch_ms", dev_time * 1e3)
                self.bperf.inc("d2h_bytes", out_bytes)
                self.bperf.inc("device_reqs", len(reqs))
                if len(reqs) > 1:
                    self.bperf.inc("coalesced_reqs", len(reqs))
            # harvest each tile's device-phase ledger (finalized by
            # AsyncBatch.wait above): feeds the phase accumulator,
            # the overlap engine, and the stall flight recorder.  A
            # mesh dispatch finalizes one clone per chip (.ledgers),
            # so every device gets its own waterfall/trace lane.
            for t in async_tiles:
                for led in (getattr(t, "ledgers", None) or
                            [getattr(t, "ledger", None)]):
                    self._observe_device_ledger(led)
            self._publish_device_telemetry(reqs[0].ec_impl)
        off = 0
        for r, arr in zip(reqs, arrs):
            p = parity[off:off + r.nstripes]
            off += r.nstripes
            out = self._shard_views(arr, p, k, m)
            try:
                r.done = True
                r.cb(out)
            except Exception:
                # a failing continuation affects only its own op
                self._cb_error()
