"""Cross-op TPU stripe batcher — the OSD-level encode coalescer.

This is the framework's "batching point" (SURVEY.md §3.1): where the
reference encodes each write's stripes on the submitting thread inside
ECBackend::try_reads_to_commit (reference src/osd/ECBackend.cc:1939,
via ECUtil::encode's per-stripe loop, src/osd/ECUtil.cc:136-148), a
TPU pays per *device call*, not per stripe — so the win is gathering
stripes from MANY in-flight ops (across PGs, one batcher per OSD) into
ONE batched MXU call.

Mechanics:

* ``submit()`` (called under the PG lock from the EC write pipeline)
  enqueues an encode request keyed by codec geometry and wakes the
  collector.  The submitting thread never blocks on the device.
* The collector thread waits ``ec_tpu_queue_window_us`` from the first
  queued request (or until ``ec_tpu_batch_stripes`` stripes are
  pending) for more ops to arrive, then concatenates each geometry
  group to one ``[N, k, chunk]`` array and issues a single
  ``encode_batch_async`` device call — h2d staging, MXU compute and
  parity d2h overlap across consecutive batches exactly like the
  bench's double buffering.
* Parity is split back per request and each continuation runs in
  submission order (per-PG FIFO holds: the PG pipeline admits one
  encode per PG at a time, and one collector drains batches serially).

Locking: ``submit`` takes only the batcher lock; continuations take
the owning PG's lock while the batcher lock is dropped — no ordering
cycle with the op workers (which take PG lock then ``submit``).

Reference anchors: the op queue this rides behind is the sharded work
queue (reference src/osd/OSD.cc:9612 enqueue_op -> op_shardedwq); the
in-order commit contract it must preserve is ECBackend::check_ops
(reference src/osd/ECBackend.cc:2151-2156).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from . import ecutil


class _Req:
    def __init__(self, ec_impl, sinfo: ecutil.StripeInfo, data: bytes,
                 cb: Callable[[Dict[int, bytes]], None]):
        self.ec_impl = ec_impl
        self.sinfo = sinfo
        self.data = data
        self.cb = cb
        self.nstripes = len(data) // sinfo.stripe_width


def _geometry_key(ec_impl, sinfo: ecutil.StripeInfo) -> Tuple:
    """Requests may share one device call iff they encode with the
    same coding matrix over the same chunk size.  The matrix is a
    deterministic function of (plugin, technique, k, m, w,
    packetsize), so that tuple + chunk_size is a sound key even
    across codec instances from different PGs of the same pool."""
    return (type(ec_impl).__name__,
            ec_impl.get_data_chunk_count(),
            ec_impl.get_coding_chunk_count(),
            getattr(ec_impl, "technique", ""),
            getattr(ec_impl, "w", 0),
            getattr(ec_impl, "packetsize", 0),
            sinfo.chunk_size)


class EncodeBatcher:
    """Per-OSD encode coalescer (one collector thread)."""

    def __init__(self, conf=None, perf=None):
        def get(k, d):
            if conf is None:
                return d
            try:
                return conf[k]
            except KeyError:
                return d
        self.max_stripes = get("ec_tpu_batch_stripes", 1024)
        self.window_s = get("ec_tpu_queue_window_us", 200) / 1e6
        self.perf = perf
        self._cond = threading.Condition()
        self._queues: Dict[Tuple, List[_Req]] = {}
        self._pending_stripes = 0
        self._first_enqueue = 0.0
        self._stop = False
        # introspection (tested + surfaced via perf counters)
        self.calls = 0               # device calls issued
        self.reqs_total = 0          # requests encoded
        self.reqs_coalesced = 0      # requests that shared a call
        self._cpu_twins: Dict[Tuple, object] = {}  # device-failure path
        self._thread = threading.Thread(target=self._run,
                                        name="ec-batcher", daemon=True)
        self._thread.start()

    # -- producer side ---------------------------------------------------
    def submit(self, ec_impl, sinfo: ecutil.StripeInfo, data: bytes,
               cb: Callable[[Dict[int, bytes]], None]) -> None:
        """Queue one aligned extent for encoding; ``cb`` receives the
        full {shard: bytes} chunk map (data + parity) later, from the
        collector thread.  Codecs without the batched async API don't
        benefit from coalescing — they encode inline."""
        if self._stop or not hasattr(ec_impl, "encode_batch_async"):
            cb(ecutil.encode(sinfo, ec_impl, data))
            return
        req = _Req(ec_impl, sinfo, data, cb)
        if req.nstripes == 0:
            cb({i: b"" for i in range(ec_impl.get_chunk_count())})
            return
        with self._cond:
            if self._stop:
                stopped = True       # raced shutdown: encode inline
            else:
                stopped = False
                if not self._queues:
                    self._first_enqueue = time.monotonic()
                self._queues.setdefault(_geometry_key(ec_impl, sinfo),
                                        []).append(req)
                self._pending_stripes += req.nstripes
                self._cond.notify()
        if stopped:
            cb(ecutil.encode(sinfo, ec_impl, data))

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()
        self._thread.join(timeout=5)

    # -- collector -------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queues and not self._stop:
                    self._cond.wait()
                if not self._queues and self._stop:
                    return
                # linger for the window so concurrent ops can join,
                # unless the stripe budget is already met
                deadline = self._first_enqueue + self.window_s
                while (not self._stop
                       and self._pending_stripes < self.max_stripes
                       and (remaining := deadline - time.monotonic())
                       > 0):
                    self._cond.wait(remaining)
                queues, self._queues = self._queues, {}
                self._pending_stripes = 0
            # dispatch EVERY group's device call before joining any:
            # h2d staging + MXU compute of group B overlap group A's
            # parity d2h and continuations (same double buffering the
            # bench uses).  A continuation that raises must not kill
            # the collector — that would wedge every EC write on the
            # OSD — so each step is fault-isolated to its own ops.
            groups = []
            for key, reqs in queues.items():
                groups.append((reqs, self._dispatch_group(reqs)))
            for reqs, handle in groups:
                try:
                    self._complete_group(reqs, handle)
                except Exception:
                    import traceback
                    traceback.print_exc()

    def _cpu_encode(self, req: _Req) -> Dict[int, bytes]:
        """Device-free encode through a CPU twin codec of the same
        geometry (cached); jerasure lacks the batched device API, so
        ecutil.encode takes its per-stripe CPU loop."""
        impl = req.ec_impl
        key = _geometry_key(impl, req.sinfo)
        twin = self._cpu_twins.get(key)
        if twin is None:
            from ..ec import registry as ecreg
            prof = {"k": str(impl.get_data_chunk_count()),
                    "m": str(impl.get_coding_chunk_count()),
                    "technique": getattr(impl, "technique",
                                         "reed_sol_van"),
                    "w": str(getattr(impl, "w", 8))}
            ps = getattr(impl, "packetsize", 0)
            if ps:
                prof["packetsize"] = str(ps)
            twin = ecreg.instance().factory("jerasure", prof)
            self._cpu_twins[key] = twin
        return ecutil.encode(req.sinfo, twin, req.data)

    def _dispatch_group(self, reqs: List[_Req]):
        """Issue one async device call for every request of one
        geometry; returns (arrs, async_handle) or None on dispatch
        failure (completion falls back to per-request CPU encode)."""
        try:
            sinfo = reqs[0].sinfo
            k = reqs[0].ec_impl.get_data_chunk_count()
            arrs = [np.frombuffer(r.data, dtype=np.uint8).reshape(
                r.nstripes, k, sinfo.chunk_size) for r in reqs]
            batch = np.concatenate(arrs, axis=0) \
                if len(arrs) > 1 else arrs[0]
            return arrs, reqs[0].ec_impl.encode_batch_async(batch)
        except Exception:
            return None

    def _complete_group(self, reqs: List[_Req], handle) -> None:
        k = reqs[0].ec_impl.get_data_chunk_count()
        m = reqs[0].ec_impl.get_coding_chunk_count()
        parity = None
        if handle is not None:
            arrs, async_batch = handle
            try:
                parity = async_batch.wait()
            except Exception:
                parity = None
        if parity is None:
            # device trouble: encode each request on a REAL CPU path
            # (a jerasure twin of the same geometry — bit-exact by the
            # corpus contract, and free of the broken device).  A
            # request that still cannot encode gets cb(None) so the
            # write op fails with EIO instead of hanging.
            for r in reqs:
                try:
                    chunks = self._cpu_encode(r)
                except Exception:
                    import traceback
                    traceback.print_exc()
                    chunks = None
                try:
                    r.cb(chunks)
                except Exception:
                    import traceback
                    traceback.print_exc()
            return
        self.calls += 1
        self.reqs_total += len(reqs)
        nstripes = sum(r.nstripes for r in reqs)
        if len(reqs) > 1:
            self.reqs_coalesced += len(reqs)
        if self.perf is not None:
            self.perf.inc("ec_batch_calls")
            self.perf.inc("ec_batch_stripes", nstripes)
            if len(reqs) > 1:
                self.perf.inc("ec_batch_coalesced", len(reqs))
        off = 0
        for r, arr in zip(reqs, arrs):
            p = parity[off:off + r.nstripes]
            off += r.nstripes
            out: Dict[int, bytes] = {}
            for i in range(k):
                out[i] = arr[:, i].tobytes()
            for j in range(m):
                out[k + j] = np.ascontiguousarray(p[:, j]).tobytes()
            try:
                r.cb(out)
            except Exception:
                # a failing continuation affects only its own op
                import traceback
                traceback.print_exc()
