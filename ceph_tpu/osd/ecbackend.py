"""Erasure-coded PG backend.

Python-native equivalent of the reference's ECBackend (reference
src/osd/ECBackend.{h,cc}, 2.6k LoC), the engine behind every EC pool:

* **writes** run the reference's pipeline
  ``waiting_state -> waiting_reads -> waiting_commit`` driven by
  ``check_ops()`` (reference ECBackend.cc:2151-2156): a mutation whose
  stripes are partially overwritten first gathers RMW reads
  (``try_state_to_reads``, :1865), then encodes and fans out per-shard
  sub-writes (``try_reads_to_commit``, :1939) — the **encode happens
  here**, and is where this framework diverges TPU-first: the whole
  aligned extent goes to the OSD's cross-op batcher (osd/batcher.py)
  as ONE ``[nstripes, k, chunk]`` array, where it coalesces with
  concurrent ops from other PGs into a single MXU device call instead
  of the reference's per-stripe CPU loop (ECUtil.cc:136-148);
* **reads** reconstruct from the minimum shard set
  (``objects_read_and_reconstruct`` -> ECSubRead fan-out ->
  batched decode; reference ECBackend.cc:2345,1594,2287);
* **recovery** reads k surviving shards, decodes the missing shards'
  chunks in one batch and pushes with MOSDPGPush (reference
  continue_recovery_op FSM IDLE->READING->WRITING, ECBackend.cc:
  570-736); when the primary itself lacks the object its metadata is
  first fetched from a surviving peer (the reference's pull path);
* per-shard cumulative-CRC ``HashInfo`` xattrs maintained on appends
  and consumed by deep scrub (reference ECBackend.cc:2475).

Pools without ``ec_overwrites`` reject non-append writes, omap and
truncate exactly like the reference (allows_ecoverwrites,
osd/osd_types.h:1600; omap ENOTSUP per
doc/dev/osd_internals/erasure_coding/ecbackend.rst) — enforced by the
PG before submit.

Writes serialize through a strictly FIFO per-PG pipeline, exactly like
the reference's in-order 3-queue state machine (ECBackend.cc:2151):
sub-writes — and with them PG-log entries — always apply in submission
order, which keeps every shard's log monotonic.  Overlapping RMW ops
pipeline deeper than one: an in-flight extent overlay (the reference
ExtentCache analog, ECBackend.cc:1891-1920; see ``_overlay`` below)
lets a later op's RMW reads see earlier ops' not-yet-committed bytes,
so multiple writes to one object proceed concurrently without
read-your-own-write hazards.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..msg.messages import (MOSDECSubOpRead, MOSDECSubOpReadReply,
                            MOSDECSubOpWrite, MOSDECSubOpWriteReply,
                            MOSDPGPush, MOSDPGPushReply, PushOp)
from ..store.objectstore import GHObject, Transaction
from ..utils import copytrack
from ..utils import faults as faultlib
from . import ecutil
from .backend import OI_ATTR, Mutation, ObjectInfo, PGBackend, PGHost
from .pglog import Eversion, LogEntry


class _HostCrcWindow(Exception):
    """Scrub-window routing verdict: the batched bitmatrix apply
    would lose to the native per-chunk host CRC kernel here (no
    accelerator, no syndrome bands to fold) — take the host loop."""


class _WriteOp:
    """One in-flight client write (reference ECBackend::Op).

    Pipeline states: PENDING (queued, not started) -> RMW (started,
    gathering reads / encoding) -> ENCODED (chunks ready, awaiting
    its turn to send) -> SENT (sub-writes out) -> DONE.  Barrier ops
    (anything beyond plain data writes) start only at the pipeline
    head and block everything behind them."""

    PENDING, RMW, ENCODED, SENT, DONE = range(5)

    def __init__(self, tid: int, oid: str, mutation: Mutation,
                 at_version: Eversion, log_entries: List[LogEntry],
                 on_all_commit: Callable[[int], None]):
        self.tid = tid
        self.oid = oid
        self.mutation = mutation
        self.at_version = at_version
        self.log_entries = log_entries
        self.on_all_commit = on_all_commit
        self.to_read: Optional[Tuple[int, int]] = None   # aligned extent
        self.read_data: bytes = b""
        self.obj_info = None             # fetched once in _start_rmw
        # shard -> outstanding sub-write commits.  One count per shard
        # for ordinary ops; segs_total counts for segmented ops (one
        # sub-write per segment, replies decrement)
        self.pending_commits: Dict[int, int] = {}
        self.state = self.PENDING
        # pipelined segmented fanout (large aligned writes): encode of
        # segment N+1 overlaps the sub-write fanout of segment N.
        # Metadata (log entries, OI, hinfo finalisation) rides ONLY
        # the final segment's transaction, so a crash mid-op leaves
        # the partial data invisible (object size never advanced).
        self.segs_total = 1
        self.segs_sent = 0
        self.seg_ready: Dict[int, Dict[int, bytes]] = {}
        self.seg_bufs: List = []
        self.seg_astart = 0              # whole-op aligned bounds
        self.seg_hi = 0
        self.seg_width = 0               # logical bytes per segment
        self.seg_chunk_off0 = 0
        self.seg_is_append = False
        self.seg_hinfo = None            # running HashInfo across segs
        self.barrier = True
        self.alive = True                # False after on_change()
        self.tracked = False             # registered in extent overlay
        self.encoded: Optional[Tuple] = None  # (astart, hi, chunks)
        self.committed_size = 0          # store-visible size at start
        self.projected_base = 0          # + earlier in-flight writes
        self.seq = 0                     # submission order (overlay)
        self.poisoned = 0                # errno: earlier same-obj op
                                         # failed after we may have
                                         # absorbed its bytes
        # sub-write deadline state (osd_ec_subwrite_timeout_ms):
        # acked_segs dedups commit replies per (shard, seg) so a
        # deadline re-request whose original ack was merely slow can't
        # double-decrement pending_commits; sent_subwrites retains the
        # wire fields of every remote sub-write (only while the
        # timeout is armed) so a laggard can be re-requested verbatim
        self.acked_segs: Dict[int, Set[int]] = {}
        self.sent_subwrites: Dict[Tuple[int, int], Tuple] = {}
        self.deadline_timer = None
        # parity-delta RMW (sub-stripe overwrite): read plan while the
        # dirty columns' old chunks are in flight, then the lowered
        # txn plan (cols, new dirty-column bytes, chunk_off, Δparity)
        self.delta_pending: Optional[Tuple] = None
        self.delta_txn: Optional[Tuple] = None


class _ReadOp:
    """One in-flight reconstructing read (reference ECBackend::ReadOp).
    ``ranges`` optionally narrows a shard's read to sub-chunk byte
    runs (CLAY repair); a shard's received payload is the in-order
    concatenation of its runs."""

    def __init__(self, tid: int, oid: str, chunk_off: int,
                 chunk_len: int, want_shards: Dict[int, int],
                 cb: Callable[[Dict[int, bytes], Dict[int, int]], None],
                 tried: Optional[Set[int]] = None,
                 ranges: Optional[Dict[int, List[Tuple[int, int]]]]
                 = None, need: Optional[int] = None):
        self.tid = tid
        self.oid = oid
        self.chunk_off = chunk_off
        self.chunk_len = chunk_len
        self.want_shards = want_shards       # shard -> osd
        self.ranges = ranges or {}           # shard -> [(off, len)]
        self.received: Dict[int, bytes] = {}
        self.errors: Dict[int, int] = {}
        self.tried: Set[int] = tried or set(want_shards)
        self.cb = cb                         # (shard->bytes, shard->err)
        # fast_read (reference ECBackend.cc:1043,2173 fast_read /
        # send_all_remaining_reads): when set, the op completes as soon
        # as ``need`` shards answered successfully — the remaining
        # (slow/dead) shards' replies are dropped as stragglers
        self.need = need


class _RecoveryOp:
    """reference ECBackend::RecoveryOp FSM state."""

    def __init__(self, oid: str, version: Eversion,
                 missing_on: List[Tuple[int, int]],
                 cb: Callable[[int], None]):
        self.oid = oid
        self.version = version
        self.missing_on = missing_on         # [(shard, osd)]
        self.cb = cb
        self.pending_pushes: Set[int] = set()


class ECBackend(PGBackend):
    def __init__(self, host: PGHost, ec_impl, stripe_width: int,
                 allows_overwrites: bool = False):
        super().__init__(host)
        self.ec_impl = ec_impl
        self.k = ec_impl.get_data_chunk_count()
        self.m = ec_impl.get_coding_chunk_count()
        self.sinfo = ecutil.StripeInfo(self.k, stripe_width)
        self.allows_overwrites = allows_overwrites
        # pipelined commit fanout: writes larger than this are encoded
        # and fanned out segment-by-segment (0 disables)
        try:
            seg = host.conf["osd_ec_pipeline_segment_bytes"]
        except (AttributeError, KeyError, TypeError):
            seg = 2 << 20
        self.seg_bytes = 0
        if seg:
            # stripe-align the segment so every segment encodes whole
            # stripes
            self.seg_bytes = max(stripe_width,
                                 seg - seg % stripe_width)
        # parity-delta RMW (sub-stripe overwrites): GF(2^8) linearity
        # gives new_parity = old_parity ^ M[:, dirty]·(new ^ old), so
        # a small overwrite of committed stripes reads back ONLY the
        # dirty data columns, device-computes the Δparity once on the
        # primary (osd/batcher.py submit_delta), and ships parity
        # shards an xor_write the store applies against the committed
        # parity.  Clean data shards carry metadata only.
        # (no allows_overwrites gate here: the PG rejects partial
        # overwrites on non-overwrite pools long before submit, and
        # the flag may flip after this backend was built)
        try:
            dn = host.conf["osd_ec_delta_rmw"]
        except (AttributeError, KeyError, TypeError):
            dn = True
        self.delta_rmw = bool(dn)
        # dirty-column fraction above which the full re-encode wins
        # (most of the stripe comes back anyway, and one plain encode
        # beats read+delta at that point)
        try:
            frac = host.conf["osd_ec_delta_rmw_max_dirty"]
        except (AttributeError, KeyError, TypeError):
            frac = 0.5
        self.delta_max_dirty = float(frac)
        self.delta_rmw_ops = 0           # ops lowered to Δparity
        self.delta_rmw_fallbacks = 0     # eligible, but a dirty-shard
                                         # read failed -> full path
        self.rmw_full_ops = 0            # read-back ops on full path
        self.delta_dirty_census: Dict[int, int] = {}   # D -> op count
        # write pipeline queues (reference ECBackend.cc:2151)
        self.waiting_commit: Dict[int, _WriteOp] = {}
        self.in_flight_reads: Dict[int, _ReadOp] = {}
        self.attr_fetches: Dict[int, Tuple] = {}    # tid -> (rec,)
        self.recovery_ops: Dict[str, _RecoveryOp] = {}
        # write pipeline: encodes run CONCURRENTLY (depth > 1), but
        # sub-write fan-out happens strictly in submission order so
        # every shard's log stays monotonic (reference check_ops
        # ordering contract, ECBackend.cc:2151); the extent overlay
        # below plays the reference ExtentCache's role for RMW reads
        # of in-flight bytes
        self._pipeline: deque = deque()
        # oid -> {"ops": n, "writes": [(off, bytes)...] in submission
        # order, "size": projected logical size} for STARTED plain
        # writes (reference ExtentCache pins)
        self._pending_objs: Dict[str, Dict] = {}
        self.max_pipeline_depth = 0      # queued depth high-water
        self.max_concurrent_ops = 0      # simultaneously EXECUTING
        # total bytes requested through _start_read (observability +
        # the CLAY repair-bandwidth test)
        self.read_bytes_total = 0
        self.subchunk_repairs = 0        # CLAY repairs taken
        self.repair_read_bytes = 0       # bytes those repairs read
        self.repair_whole_bytes = 0      # what whole-chunk would read
        # sub-write deadlines (osd_ec_subwrite_timeout_ms; 0 disables):
        # the primary re-requests a laggard shard's sub-write once,
        # then reports the peer to the monitor like a failed heartbeat
        try:
            tmo = host.conf["osd_ec_subwrite_timeout_ms"]
        except (AttributeError, KeyError, TypeError):
            tmo = 0.0
        self.subwrite_timeout_s = (tmo or 0.0) / 1000.0
        self.subwrite_timeouts = 0       # deadlines that expired
        self.subwrite_retries = 0        # sub-writes re-requested
        self.subwrite_peer_reports = 0   # laggards reported to the mon
        # shard-side dedup of re-requested sub-writes, keyed
        # (from_osd, tid, seg): True once committed (a duplicate
        # re-acks — the original ack was lost), False while the first
        # apply is still in flight (its ack is coming; stay silent)
        self._recent_subwrites: Dict[Tuple[int, int, int], bool] = {}
        # pay the pool geometry's one-time costs (device kernel
        # compile + the crossover router's CPU-rate probe) NOW, in the
        # background, instead of on the first client op — the
        # reference pays GF table setup at plugin load
        # (jerasure_init.cc:37, preloaded at global_init.cc:600)
        batcher = getattr(host, "encode_batcher", None)
        if batcher is not None:
            try:
                batcher.prewarm(ec_impl, self.sinfo)
            except Exception:
                pass

    #: geometry keys whose activation prewarm already ran (the work is
    #: per-process; activation happens per PG)
    _activation_warmed: Set[tuple] = set()

    def prewarm_geometry(self) -> None:
        """Make the pool's (k, m, stripe) device executables and
        staging buffers hot BEFORE the first client write — invoked
        from PG activation (pg.py _activate).  Construction-time
        ``batcher.prewarm`` covers the crossover probe and cold
        compile; this adds the persistent staging rings
        (jax_engine StagingPool) for the batch shapes the coalescer
        dispatches, via the codec's prewarm_geometry.  Background
        thread, idempotent per geometry process-wide."""
        batcher = getattr(self.host, "encode_batcher", None)
        if batcher is not None:
            try:
                batcher.prewarm(self.ec_impl, self.sinfo)
            except Exception:
                pass
        warm = getattr(self.ec_impl, "prewarm_geometry", None)
        if warm is None:
            return
        key = (type(self.ec_impl).__name__, self.k, self.m,
               self.sinfo.chunk_size)
        if key in ECBackend._activation_warmed:
            return
        ECBackend._activation_warmed.add(key)
        ms = max(1, getattr(batcher, "max_stripes", 1) or 1)
        batches = tuple(sorted({ms, max(1, ms // 2)}))
        chunk = self.sinfo.chunk_size

        warm_dec = getattr(self.ec_impl, "prewarm_decode", None)

        def work():
            try:
                warm(chunk, batches=batches)
            except Exception:
                pass             # warms are best-effort
            if warm_dec is not None:
                # decode-side activation warm (ISSUE 11): the common
                # single-erasure recovery signatures (combined
                # recovery rows + staging ring + one compiled decode
                # executable), so the first rebuild window after an
                # OSD loss pays no compile/alloc tax.  The decode
                # crossover itself needs no warm — it seeds from the
                # encode EWMA the batcher.prewarm above measures
                # (EncodeBatcher._dec_min_bytes).
                try:
                    warm_dec(chunk, batches=batches)
                except Exception:
                    pass

        threading.Thread(target=work, name="ec-activate-prewarm",
                         daemon=True).start()

    # ------------------------------------------------------------------
    # write path (reference submit_transaction -> start_rmw -> check_ops)
    # ------------------------------------------------------------------
    def submit_transaction(self, oid: str, mutation: Mutation,
                           at_version: Eversion,
                           log_entries: List[LogEntry],
                           on_all_commit: Callable[[int], None]) -> None:
        op = _WriteOp(self.new_tid(), oid, mutation, at_version,
                      log_entries, on_all_commit)
        # plain data writes pipeline (depth > 1); anything that
        # touches object lifecycle or metadata beyond the write is a
        # BARRIER: it waits for the pipeline and blocks what follows
        # (the reference pins such ops through the cache too; this
        # split keeps the overlay algebra to pure byte extents)
        mut = mutation
        op.barrier = not (mut.writes and mut.truncate is None
                          and not mut.delete and not mut.create
                          and mut.clone_to is None
                          and mut.rollback_from is None
                          and not mut.aux_remove
                          and mut.snapdir_set is None)
        self._op_seq = getattr(self, "_op_seq", 0) + 1
        op.seq = self._op_seq
        self._pipeline.append(op)
        self.max_pipeline_depth = max(self.max_pipeline_depth,
                                      len(self._pipeline))
        self._admit_ops()

    def _admit_ops(self) -> None:
        """Start every op that may legally run: the consecutive run
        of non-barrier ops at the head, or a barrier exactly at the
        head (reference check_ops admission)."""
        for op in list(self._pipeline):
            if op.barrier:
                if op.state == op.PENDING \
                        and self._pipeline[0] is op:
                    op.state = op.RMW
                    self._start_rmw(op)
                break                # nothing may pass a barrier
            if op.state == op.PENDING:
                op.state = op.RMW
                self._track_pending(op)
                self._start_rmw(op)
        running = sum(1 for o in self._pipeline
                      if o.state in (o.RMW, o.ENCODED, o.SENT))
        self.max_concurrent_ops = max(self.max_concurrent_ops,
                                      running)

    # -- extent overlay (reference ExtentCache) ------------------------
    def _track_pending(self, op: _WriteOp) -> None:
        st = self._pending_objs.setdefault(
            op.oid, {"ops": 0, "writes": [], "size": 0})
        st["ops"] += 1
        op.tracked = True
        # snapshot the projection BEFORE this op's own writes land
        op.projected_base = max(st["size"], 0)
        for off, data in op.mutation.writes:
            st["writes"].append((op.seq, off, data))
            st["size"] = max(st["size"], off + len(data))

    def _untrack_pending(self, op: _WriteOp,
                         failed: bool = False) -> None:
        if not op.tracked:
            return
        op.tracked = False
        st = self._pending_objs.get(op.oid)
        if st is None:
            return
        st["ops"] -= 1
        if failed:
            # a FAILED op's bytes must never reach another op's
            # encode; any later op that may already have absorbed
            # them gets poisoned by the caller
            st["writes"] = [w for w in st["writes"]
                            if w[0] != op.seq]
        if st["ops"] <= 0:
            # no in-flight writes left: committed state has absorbed
            # every overlay byte — drop the object's cache.
            # (Successful ops' entries stay until then: a concurrent
            # reader's shard data may still predate them.)
            del self._pending_objs[op.oid]

    def _overlay(self, oid: str, buf: bytearray, astart: int,
                 before_seq: int) -> None:
        """Apply in-flight writes SUBMITTED BEFORE ``before_seq``
        intersecting [astart, astart+len(buf)), in submission order —
        the ExtentCache read: projected bytes come from memory, never
        from shards whose application state is in flux.  Later ops'
        bytes must not leak backwards in time."""
        st = self._pending_objs.get(oid)
        if st is None:
            return
        aend = astart + len(buf)
        for seq, off, data in st["writes"]:
            if seq >= before_seq:
                continue
            lo = max(off, astart)
            hi = min(off + len(data), aend)
            if lo < hi:
                buf[lo - astart:hi - astart] = \
                    data[lo - off:hi - off]

    def _overlay_covers(self, oid: str, lo: int, hi: int,
                        committed_end: int, before_seq: int) -> bool:
        """True when [lo,hi) needs no shard read: every byte is either
        beyond the committed size (zeros + overlay) or covered by an
        in-flight write."""
        if lo >= committed_end:
            return True
        st = self._pending_objs.get(oid)
        if st is None:
            return False
        spans = sorted((off, off + len(d))
                       for seq, off, d in st["writes"]
                       if seq < before_seq)
        pos = lo
        end = min(hi, committed_end)
        for s, e in spans:
            if s > pos:
                return False
            pos = max(pos, e)
            if pos >= end:
                return True
        return pos >= end

    def _fail_op(self, op: _WriteOp, err: int) -> None:
        """Fail an op mid-pipeline.  Its overlay bytes are withdrawn,
        and any LATER in-flight op on the same object that may already
        have absorbed them into its encode fails too (the client is
        told; nothing lands silently)."""
        self.waiting_commit.pop(op.tid, None)
        self._cancel_deadline(op)
        op.on_all_commit(err)
        self._untrack_pending(op, failed=True)
        for o in self._pipeline:
            if o.seq > op.seq and o.oid == op.oid \
                    and o.state != o.DONE and not o.poisoned:
                o.poisoned = err
                self._untrack_pending(o, failed=True)
        self._complete_op(op)

    def _start_rmw(self, op: _WriteOp) -> None:
        """Compute the WritePlan (reference get_write_plan,
        ECTransaction.h:40): which existing stripes must be read back
        before this mutation can be encoded.  For pipelined ops the
        logical size projects over the in-flight writes (the overlay
        below plays ExtentCache), so sizes/appends stay correct even
        though earlier ops have not committed yet."""
        info = self.get_object_info(op.oid)
        mut = op.mutation
        if mut.create and info is not None:
            op.on_all_commit(-17)        # -EEXIST: exclusive create
            self._complete_op(op)
            return
        op.obj_info = info = info or ObjectInfo()
        op.committed_size = info.size
        if op.tracked:
            # logical size as of this op's admission: committed state
            # plus every earlier in-flight write
            info.size = max(info.size, op.projected_base)
        if mut.delete or not mut.writes:
            self._reads_to_commit(op)
            return
        lo = min(off for off, _ in mut.writes)
        hi = max(off + len(d) for off, d in mut.writes)
        astart, alen = self.sinfo.offset_len_to_stripe_bounds(lo, hi - lo)
        # existing bytes inside the affected aligned range that the new
        # data does not fully cover must be read back (RMW); bytes the
        # accompanying truncate will discard don't count (writefull)
        existing_end = min(info.size, astart + alen)
        if mut.truncate is not None:
            # the truncate applies BEFORE the writes (pg.py projects
            # sizes the same way): bytes at/above it are discarded and
            # must not be read back — including bytes BELOW the write
            # start, which become zeros, not resurrected stale data
            existing_end = min(existing_end, mut.truncate)
        if existing_end <= astart or \
                self._fully_covers(mut.writes, astart, existing_end) \
                or self._overlay_covers(op.oid, astart, existing_end,
                                        op.committed_size,
                                        op.seq + 1):
            # nothing to read from shards: gaps are zeros/overlay —
            # the ExtentCache fast path (reference ECBackend.cc:
            # 1891-1920: in-flight extents served from cache)
            self._reads_to_commit(op)
            return
        if self._try_delta_rmw(op, lo, hi, astart, alen):
            return
        self.rmw_full_ops += 1
        op.to_read = (astart, existing_end - astart)
        if mut.tracked_op is not None:
            mut.tracked_op.mark_event("ec:rmw_read")
        self.objects_read(
            op.oid, astart, min(existing_end, op.committed_size)
            - astart,
            lambda res, data: self._rmw_read_done(op, res, data),
            trace=(mut.trace_id, mut.parent_span_id))

    @staticmethod
    def _fully_covers(writes: List[Tuple[int, bytes]], lo: int,
                      hi: int) -> bool:
        """True if [lo,hi) is entirely covered by the write extents."""
        if hi <= lo:
            return True
        spans = sorted((off, off + len(d)) for off, d in writes)
        pos = lo
        for s, e in spans:
            if s > pos:
                return False
            pos = max(pos, e)
            if pos >= hi:
                return True
        return pos >= hi

    # -- parity-delta RMW (sub-stripe overwrite) -----------------------
    def _try_delta_rmw(self, op: _WriteOp, lo: int, hi: int,
                       astart: int, alen: int) -> bool:
        """Sub-stripe overwrite fast path.  Eligible when the mutation
        is a plain tracked write entirely inside committed stripes, no
        earlier in-flight write overlaps the extent (those bytes are
        not on shards yet — the overlay algebra stays on the full
        path), the dirty-column fraction is small enough, and every
        dirty column's shard is up (the old bytes are read verbatim,
        never reconstructed — reconstruction is the full path's job).
        Returns True when the delta read plan was started."""
        mut = op.mutation
        if not self.delta_rmw or not op.tracked:
            return False                 # barriers keep the full path
        if hi > op.committed_size:
            return False                 # extends the object: stripes
                                         # beyond committed aren't on
                                         # shards yet
        batcher = getattr(self.host, "encode_batcher", None)
        if batcher is None or \
                not hasattr(self.ec_impl, "delta_encode_batch_async"):
            return False
        st = self._pending_objs.get(op.oid)
        if st is not None:
            for seq, off, data in st["writes"]:
                if seq < op.seq and off < astart + alen \
                        and off + len(data) > astart:
                    return False
        cols = self._dirty_columns(mut.writes, astart, alen)
        if not cols or len(cols) > self.k * self.delta_max_dirty:
            return False                 # dirty majority: re-encode
        acting = {s: o for s, o in self.host.acting_shards()
                  if o is not None}
        if any(c not in acting for c in cols):
            return False
        chunk_off = \
            self.sinfo.aligned_logical_offset_to_chunk_offset(astart)
        chunk_len = self.sinfo \
            .aligned_logical_offset_to_chunk_offset(astart + alen) \
            - chunk_off
        op.delta_pending = (astart, alen, hi, cols, chunk_off,
                            chunk_len)
        self.delta_rmw_ops += 1
        self.delta_dirty_census[len(cols)] = \
            self.delta_dirty_census.get(len(cols), 0) + 1
        if mut.tracked_op is not None:
            mut.tracked_op.mark_event("ec:rmw_delta_read")
        self._start_read(
            op.oid, chunk_off, chunk_len,
            {c: acting[c] for c in cols},
            lambda received, errors:
                self._delta_read_done(op, received, errors),
            trace=(mut.trace_id, mut.parent_span_id))
        return True

    def _dirty_columns(self, writes: List[Tuple[int, bytes]],
                       astart: int, alen: int) -> Tuple[int, ...]:
        """Data columns (chunk indices) any write byte lands in,
        across every stripe row of the aligned extent."""
        W = self.sinfo.stripe_width
        cs = self.sinfo.chunk_size
        cols: Set[int] = set()
        for off, data in writes:
            w_lo = max(off, astart)
            w_hi = min(off + len(data), astart + alen)
            if w_lo >= w_hi:
                continue
            for r in range((w_lo - astart) // W,
                           (w_hi - astart + W - 1) // W):
                s0 = astart + r * W
                l = max(w_lo, s0)
                h = min(w_hi, s0 + W)
                cols.update(range((l - s0) // cs,
                                  (h - s0 + cs - 1) // cs))
                if len(cols) >= self.k:
                    return tuple(range(self.k))
        return tuple(sorted(cols))

    def _delta_read_done(self, op: _WriteOp,
                         received: Dict[int, bytes],
                         errors: Dict[int, int]) -> None:
        """Old dirty-column chunks arrived: build the XOR delta in
        column space and hand it to the batcher's delta lane (ONE
        GF delta-matmul per coalesced batch on the device)."""
        if not op.alive:
            return
        astart, alen, hi, cols, chunk_off, chunk_len = op.delta_pending
        batcher = getattr(self.host, "encode_batcher", None)
        if batcher is None or errors or \
                any(len(received.get(c, b"")) != chunk_len
                    for c in cols):
            # a dirty shard couldn't serve its old chunk verbatim:
            # reconstruct through the ordinary full-stripe read-back
            # instead — correctness never rides the fast path
            self._delta_fallback(op)
            return
        import numpy as np
        cs = self.sinfo.chunk_size
        W = self.sinfo.stripe_width
        nrows = alen // W
        old = np.stack(
            [np.frombuffer(received[c], dtype=np.uint8)
             .reshape(nrows, cs) for c in cols], axis=1)
        new = old.copy()
        copytrack.note_copy(old.nbytes, "ecbackend.delta_stage")
        colidx = {c: i for i, c in enumerate(cols)}
        for off, data in op.mutation.writes:
            w_lo = max(off, astart)
            w_hi = min(off + len(data), astart + alen)
            if w_lo >= w_hi:
                continue
            src = np.frombuffer(data, dtype=np.uint8)
            for r in range((w_lo - astart) // W,
                           (w_hi - astart + W - 1) // W):
                s0 = astart + r * W
                for c in cols:
                    c0 = s0 + c * cs
                    l = max(w_lo, c0)
                    h = min(w_hi, c0 + cs)
                    if l < h:
                        new[r, colidx[c], l - c0:h - c0] = \
                            src[l - off:h - off]
        delta = old
        delta ^= new                     # in place: old is dead after
        new_cols = {
            c: memoryview(np.ascontiguousarray(new[:, i])).cast("B")
            for i, c in enumerate(cols)}
        op.delta_pending = (astart, hi, cols, new_cols, chunk_off)
        if op.mutation.tracked_op is not None:
            op.mutation.tracked_op.mark_event("ec:encode_queued")
        batcher.submit_delta(
            self.ec_impl, self.sinfo, delta, cols,
            lambda dp: self._delta_encode_done(op, dp),
            tracked=op.mutation.tracked_op)

    def _delta_fallback(self, op: _WriteOp) -> None:
        """Delta read failed (dirty shard down/short mid-flight): take
        the ordinary reconstructing read-back, which decodes the
        extent from any k shards."""
        astart, alen = op.delta_pending[0], op.delta_pending[1]
        op.delta_pending = None
        self.delta_rmw_fallbacks += 1
        self.rmw_full_ops += 1
        mut = op.mutation
        info = op.obj_info or ObjectInfo()
        existing_end = min(info.size, astart + alen)
        op.to_read = (astart, existing_end - astart)
        if mut.tracked_op is not None:
            mut.tracked_op.mark_event("ec:rmw_read")
        self.objects_read(
            op.oid, astart,
            min(existing_end, op.committed_size) - astart,
            lambda res, data: self._rmw_read_done(op, res, data),
            trace=(mut.trace_id, mut.parent_span_id))

    def _delta_encode_done(self, op: _WriteOp,
                           dparity: Optional[Dict[int, bytes]]) -> None:
        """Continuation from the batcher's collector thread with the
        Δparity chunk map {k+j: bytes}: re-enter under the PG lock and
        queue for the ORDERED send (same contract as _encode_done)."""
        lock = getattr(self.host, "lock", None)
        if lock is None:
            import contextlib
            lock = contextlib.nullcontext()
        with lock:
            if not op.alive:
                return
            if op.mutation.tracked_op is not None:
                op.mutation.tracked_op.mark_event("ec:encoded")
            if dparity is None:          # delta failed even inline: EIO
                self._fail_op(op, -5)
                return
            op.delta_txn = op.delta_pending + (dparity,)
            op.delta_pending = None
            op.state = op.ENCODED
            self._flush_ready()

    def _rmw_read_done(self, op: _WriteOp, res: int,
                       data: bytes) -> None:
        if not op.alive:
            return                   # interval change dropped the op
        if res < 0:
            # RMW source unreadable (shards down mid-pipeline): fail
            # the op (and dependents); clients resend after re-peer
            self._fail_op(op, res)
            return
        op.read_data = data
        self._reads_to_commit(op)

    def _reads_to_commit(self, op: _WriteOp) -> None:
        """Encode + fan out per-shard sub-writes (reference
        try_reads_to_commit, ECBackend.cc:1939-2101).

        The encode does NOT run inline here: writes with data hand
        their stripe-aligned buffer to the OSD's cross-op batcher
        (osd/batcher.py), which coalesces stripes from concurrent ops
        across PGs into one device call and calls back into
        _encode_done.  Codec or host without batching support encodes
        synchronously on this thread instead."""
        mut = op.mutation
        if mut.delete or not mut.writes:
            self._commit_fanout(op, self._generate_transactions(op))
            return
        lo = min(off for off, _ in mut.writes)
        hi = max(off + len(d) for off, d in mut.writes)
        astart, alen = self.sinfo.offset_len_to_stripe_bounds(
            lo, hi - lo)
        if len(mut.writes) == 1 and not op.read_data \
                and lo == astart and hi - astart == alen:
            # aligned full-cover write (the deployed whole-object
            # path): the client payload IS the stripe-aligned extent —
            # hand it to the encoder by reference, zero copies.  Any
            # overlay bytes are fully shadowed by this op's own data.
            payload = mut.writes[0][1]
        else:
            buf = bytearray(alen)        # zero padding to stripe bounds
            if op.read_data:
                buf[0:len(op.read_data)] = op.read_data
            if op.tracked:
                # in-flight bytes of EARLIER ops shadow whatever the
                # shards returned (they may predate those uncommitted
                # writes); own writes applied below
                self._overlay(op.oid, buf, astart, op.seq)
            for off, data in mut.writes:
                buf[off - astart:off - astart + len(data)] = data
            copytrack.note_copy(alen, "ecbackend.rmw_gather")
            payload = buf
        batcher = getattr(self.host, "encode_batcher", None)
        if batcher is not None and \
                hasattr(self.ec_impl, "encode_batch_async"):
            if mut.tracked_op is not None:
                mut.tracked_op.mark_event("ec:encode_queued")
            if self.seg_bytes and not op.barrier \
                    and alen > self.seg_bytes:
                self._start_segmented(op, astart, hi, payload,
                                      batcher)
                return
            batcher.submit(
                self.ec_impl, self.sinfo, payload,
                lambda chunks: self._encode_done(op, astart, hi,
                                                 chunks),
                tracked=mut.tracked_op)
        else:
            if mut.tracked_op is not None:
                mut.tracked_op.mark_event("ec:encode_queued")
            chunks = ecutil.encode(self.sinfo, self.ec_impl, payload)
            if mut.tracked_op is not None:
                mut.tracked_op.mark_event("ec:encoded")
            self._encoded_to_commit(op, astart, hi, chunks)

    def _encode_done(self, op: _WriteOp, astart: int, hi: int,
                     chunks: Dict[int, bytes]) -> None:
        """Continuation from the batcher's collector thread: re-enter
        the PG under its lock, unless an interval change dropped the
        op mid-encode."""
        lock = getattr(self.host, "lock", None)
        if lock is None:
            import contextlib
            lock = contextlib.nullcontext()
        with lock:
            if not op.alive:
                return               # on_change() cleared the pipeline
            if op.mutation.tracked_op is not None:
                op.mutation.tracked_op.mark_event("ec:encoded")
            if chunks is None:       # encode failed even on CPU: EIO
                self._fail_op(op, -5)
                return
            self._encoded_to_commit(op, astart, hi, chunks)

    def _encoded_to_commit(self, op: _WriteOp, astart: int, hi: int,
                           chunks: Dict[int, bytes]) -> None:
        """Encode finished: queue for the ORDERED send.  Concurrent
        encodes may finish out of order; sub-writes must not (shard
        logs are monotonic — reference check_ops ordering)."""
        op.encoded = (astart, hi, chunks)
        op.state = op.ENCODED
        self._flush_ready()

    def _flush_ready(self) -> None:
        """Send, in submission order, every encoded op not yet sent;
        stop at the first op still encoding.  Poisoned ops (an earlier
        same-object op failed under them) error out instead of
        sending.  Segmented ops send their encoded segment prefix and
        — until the final (metadata-carrying) segment is out — block
        everything behind them, keeping shard logs monotonic."""
        for op in list(self._pipeline):
            if op.state in (op.SENT, op.DONE):
                continue
            if op.state != op.ENCODED:
                break
            if op.poisoned:
                # a partially-sent segmented op stops here: its data
                # sub-writes may have landed, but without the final
                # segment's metadata they are invisible
                self.waiting_commit.pop(op.tid, None)
                self._cancel_deadline(op)
                op.on_all_commit(op.poisoned)
                op.state = op.DONE
                continue
            if op.segs_total > 1:
                self._send_ready_segments(op)
                if op.state == op.DONE:
                    continue
                if op.state != op.SENT:
                    break            # mid-op: later ops must wait
                continue
            op.state = op.SENT
            if op.delta_txn is not None:
                txns = self._generate_transactions(
                    op, delta_plan=op.delta_txn)
            elif op.encoded is not None:
                astart, hi, chunks = op.encoded
                txns = self._generate_transactions(
                    op, write_plan=(astart, hi, chunks))
            else:
                txns = self._generate_transactions(op)
            self._commit_fanout(op, txns)
        while self._pipeline and \
                self._pipeline[0].state == _WriteOp.DONE:
            self._untrack_pending(self._pipeline.popleft())

    def _complete_op(self, op: _WriteOp) -> None:
        """An op finished (committed everywhere, or failed early):
        mark DONE and retire the completed prefix of the pipeline."""
        op.state = op.DONE
        while self._pipeline and self._pipeline[0].state == op.DONE:
            done = self._pipeline.popleft()
            self._untrack_pending(done)
        self._admit_ops()
        self._flush_ready()

    def _commit_fanout(self, op: _WriteOp,
                       shard_txns: Dict[int, Transaction]) -> None:
        wire_entries = [e.to_dict() for e in op.log_entries]
        self._register_commits(op, 1)
        tracked = op.mutation.tracked_op
        if tracked is not None:
            tracked.mark_event("ec:sub_write_sent")
        self._fanout_txns(op, shard_txns, wire_entries)

    def _register_commits(self, op: _WriteOp, per_shard: int) -> None:
        """Populate pending_commits for the WHOLE acting set before
        any send: a fast commit reply must not find a half-filled map
        and declare the op done early.  ``per_shard`` is the number of
        sub-writes each shard will receive (segments)."""
        op.pending_commits = {
            shard: per_shard for shard, osd in
            self.host.acting_shards() if osd is not None}
        self.waiting_commit[op.tid] = op
        if self.subwrite_timeout_s > 0:
            self._arm_subwrite_deadline(op, attempt=1,
                                        delay=self.subwrite_timeout_s)

    def _fanout_txns(self, op: _WriteOp,
                     shard_txns: Dict[int, Transaction],
                     wire_entries: List[dict], seg: int = 0) -> None:
        """Send one sub-write per shard.  Remote shards get the
        transaction as encode_parts() fragments — the messenger ships
        them as scatter-gather iovecs, so encoded chunk views never
        round-trip through one big bytes.  The primary's own shard
        gets the Transaction OBJECT (no encode at all).  ``seg`` is
        the pipeline segment index, carried on the wire so deadline
        re-requests dedup per (from, tid, seg)."""
        local_txn: Optional[Transaction] = None
        for shard, osd in [(s, o) for s, o in
                           self.host.acting_shards() if o is not None]:
            txn = shard_txns.get(shard) or Transaction()
            if osd == self.host.whoami:
                local_txn = txn
                continue
            parts = txn.encode_parts()
            sub = MOSDECSubOpWrite(
                pgid=self.host.pgid_str, shard=shard,
                from_osd=self.host.whoami, tid=op.tid,
                epoch=self.host.epoch, txn=parts,
                log_entries=wire_entries,
                at_version=op.at_version,
                trace_id=op.mutation.trace_id,
                parent_span_id=op.mutation.parent_span_id, seg=seg)
            sub.stamp_hop("client_send")
            self.host.send_shard(osd, sub)
            if self.subwrite_timeout_s > 0:
                # retained ONLY while a deadline is armed: parts are
                # views over op.encoded's chunks, so this adds no copy
                op.sent_subwrites[(shard, seg)] = (parts, wire_entries)
        if local_txn is not None:
            # the primary's own shard goes through the same sub-write
            # handler, local call (reference ECBackend.cc:2086-2092);
            # it bypasses handle_message, so its child span is cut here
            span = self.host.trace_span(
                "ec_sub_write", op.mutation.trace_id,
                op.mutation.parent_span_id)
            if span is not None:
                span.tag("shard", self.host.own_shard).tag(
                    "pgid", self.host.pgid_str).finish()
            tid = op.tid
            cmsg = op.mutation.client_msg

            def _local_committed(t=tid, s=seg, m=cmsg):
                if m is not None:
                    # first segment's commit wins: from here the op is
                    # waiting on the ack set, not the local store
                    m.stamp_hop("store_apply")
                self._sub_write_committed(t, self.host.own_shard, s)
            self._apply_sub_write(
                self.host.own_shard, local_txn, wire_entries,
                _local_committed)

    # -- pipelined segmented fanout ------------------------------------
    def _start_segmented(self, op: _WriteOp, astart: int, hi: int,
                         payload, batcher) -> None:
        """Cut a large aligned write into stripe-aligned segments and
        pipeline encode against fanout: segment N's sub-writes go out
        while the batcher encodes segment N+1 (the next segment is
        submitted from N's encode continuation, so the collector
        thread works while this PG thread fans out).  Only the final
        segment carries log entries, OI and the finalised hinfo —
        partial data is invisible until it lands."""
        mv = memoryview(payload)
        seg = self.seg_bytes
        op.seg_bufs = [mv[i:i + seg]
                       for i in range(0, len(mv), seg)]
        op.segs_total = len(op.seg_bufs)
        op.seg_astart = astart
        op.seg_hi = hi
        op.seg_width = seg
        op.seg_chunk_off0 = \
            self.sinfo.aligned_logical_offset_to_chunk_offset(astart)
        info = op.obj_info or ObjectInfo()
        op.seg_is_append = op.mutation.append_only_at(info.size) and \
            astart >= self.sinfo.logical_to_prev_stripe_offset(
                info.size)
        self._submit_segment(op, 0, batcher)

    def _submit_segment(self, op: _WriteOp, idx: int,
                        batcher) -> None:
        batcher.submit(
            self.ec_impl, self.sinfo, op.seg_bufs[idx],
            lambda chunks, i=idx: self._seg_encode_done(op, i, chunks),
            tracked=op.mutation.tracked_op)

    def _seg_encode_done(self, op: _WriteOp, idx: int,
                         chunks: Optional[Dict[int, bytes]]) -> None:
        """Continuation from the batcher's collector thread for one
        segment: re-enter the PG under its lock, queue the segment for
        the ordered send, and start the NEXT segment's encode — that
        encode then overlaps this segment's fanout."""
        lock = getattr(self.host, "lock", None)
        if lock is None:
            import contextlib
            lock = contextlib.nullcontext()
        with lock:
            if not op.alive:
                return
            if chunks is None:       # encode failed even on CPU: EIO
                self.waiting_commit.pop(op.tid, None)
                self._fail_op(op, -5)
                return
            op.seg_ready[idx] = chunks
            if idx == 0:
                op.state = op.ENCODED
            if idx + 1 < op.segs_total:
                batcher = getattr(self.host, "encode_batcher", None)
                if batcher is not None:
                    self._submit_segment(op, idx + 1, batcher)
            if idx + 1 == op.segs_total \
                    and op.mutation.tracked_op is not None:
                op.mutation.tracked_op.mark_event("ec:encoded")
            self._flush_ready()

    def _send_ready_segments(self, op: _WriteOp) -> None:
        """Fan out, in order, every segment whose encode has finished.
        The final segment reuses _generate_transactions (full
        metadata); intermediate segments carry data + running hinfo
        only."""
        while op.segs_sent in op.seg_ready:
            idx = op.segs_sent
            chunks = op.seg_ready.pop(idx)
            if idx == 0:
                self._register_commits(op, op.segs_total)
                if op.mutation.tracked_op is not None:
                    op.mutation.tracked_op.mark_event(
                        "ec:sub_write_sent")
            seg_chunk_off = op.seg_chunk_off0 + \
                idx * (op.seg_width // self.k)
            op.seg_hinfo = self._update_hinfo(
                op.oid, chunks, seg_chunk_off, op.seg_is_append,
                hinfo=op.seg_hinfo)
            if idx == op.segs_total - 1:
                txns = self._generate_transactions(
                    op, write_plan=(op.seg_astart, op.seg_hi, chunks),
                    hinfo=op.seg_hinfo, chunk_off=seg_chunk_off)
                wire_entries = [e.to_dict() for e in op.log_entries]
            else:
                txns = self._segment_txns(op, seg_chunk_off, chunks)
                wire_entries = []
            self._fanout_txns(op, txns, wire_entries, seg=idx)
            op.segs_sent += 1
        if op.segs_sent >= op.segs_total:
            op.state = op.SENT

    def _segment_txns(self, op: _WriteOp, chunk_off: int,
                      chunks: Dict[int, bytes]
                      ) -> Dict[int, Transaction]:
        """Per-shard transactions for a NON-final segment: chunk data
        + the running hinfo, nothing else — no OI, no log entries, no
        truncate.  A crash after this lands leaves the bytes invisible
        (object size unchanged) — same consistency the reference gets
        from atomic whole-op transactions."""
        henc = op.seg_hinfo.encode()
        txns: Dict[int, Transaction] = {}
        for shard, osd in self.host.acting_shards():
            if osd is None:
                continue
            txn = Transaction()
            obj = GHObject(op.oid, shard)
            coll = self.host.coll_of(shard)
            txn.touch(coll, obj)
            txn.write(coll, obj, chunk_off, chunks[shard])
            txn.setattr(coll, obj, ecutil.HINFO_KEY, henc)
            txns[shard] = txn
        return txns

    def _generate_transactions(self, op: _WriteOp,
                               write_plan: Optional[Tuple] = None,
                               hinfo: Optional[ecutil.HashInfo] = None,
                               chunk_off: Optional[int] = None,
                               delta_plan: Optional[Tuple] = None
                               ) -> Dict[int, Transaction]:
        """Lower the logical mutation to per-shard store transactions
        (reference ECTransaction::generate_transactions ->
        encode_and_write, ECTransaction.cc:97,28).  ``write_plan`` is
        (astart, hi, chunks) with the already-encoded chunk map from
        the batcher when the mutation carries data.  For the FINAL
        segment of a pipelined op, ``hinfo`` is the caller-maintained
        running HashInfo (already folded through every segment) and
        ``chunk_off`` the final segment's shard offset, while
        write_plan keeps the whole-op bounds so sizes stay right.
        ``delta_plan`` is (astart, hi, cols, new_cols, chunk_off,
        dparity) for a parity-delta RMW: dirty data shards get their
        new column bytes as a plain write, parity shards get an
        ``xor_write`` the store XORs into the committed parity chunk
        (WAL-backed stores replay it crash-safe), clean data shards
        carry metadata only.  The wire format does not change — the
        sub-write is a normal MOSDECSubOpWrite whose transaction
        happens to hold xor_write ops."""
        mut, oid = op.mutation, op.oid
        txns: Dict[int, Transaction] = {
            shard: Transaction()
            for shard, osd in self.host.acting_shards()
            if osd is not None}

        def for_all(fn):
            for shard, txn in txns.items():
                fn(shard, txn, GHObject(oid, shard),
                   self.host.coll_of(shard))

        from .snaps import SS_ATTR
        if mut.clone_to is not None:
            # snapshot COW: clone every shard's chunk object — the
            # store's COW copies bytes; NO re-encode happens (the
            # parity of unchanged data is unchanged).  This is the EC
            # snapshot win on TPU: snapshots cost zero device work.
            def _clone(s, t, o, c):
                cobj = GHObject(mut.clone_to, s)
                t.clone(c, o, cobj)
                t.rmattr(c, cobj, SS_ATTR)   # clones carry no SnapSet
                if mut.clone_attrs:
                    t.setattrs(c, cobj, mut.clone_attrs)
            for_all(_clone)
        for aux in mut.aux_remove:
            for_all(lambda s, t, o, c, a=aux:
                    t.remove(c, GHObject(a, s)))

        if mut.delete:
            for_all(lambda s, t, o, c: t.remove(c, o))
            if mut.snapdir_set is not None:
                sd_oid, ss, sd_oi = mut.snapdir_set

                def _snapdir(s, t, o, c):
                    sd = GHObject(sd_oid, s)
                    t.touch(c, sd)
                    t.setattr(c, sd, SS_ATTR, ss)
                    t.setattr(c, sd, OI_ATTR, sd_oi)
                for_all(_snapdir)
            return txns

        info = op.obj_info or ObjectInfo()
        new_size = info.size
        if mut.rollback_from is not None:
            # head becomes the clone's content, shard by shard
            def _rollback(s, t, o, c):
                t.remove(c, o)
                t.clone(c, GHObject(mut.rollback_from, s), o)
            for_all(_rollback)
            new_size = mut.rollback_size
        for_all(lambda s, t, o, c: t.touch(c, o))
        if mut.snapset is not None:
            for_all(lambda s, t, o, c:
                    t.setattr(c, o, SS_ATTR, mut.snapset))

        if mut.truncate is not None:
            # logical truncate: shards trim to the per-shard size; any
            # stale bytes inside the final partial stripe stay hidden
            # behind ObjectInfo.size (reads trim, RMW re-encodes whole
            # stripes from the logical content).  The truncate op is
            # emitted BEFORE any accompanying write — the store
            # applies ops in order, and the truncate logically
            # precedes the writes (pg.py projects sizes the same
            # way), so it must never chop bytes the write just put
            # past it.  The writes branch below folds the write end
            # into new_size.
            new_size = mut.truncate
            shard_sz = self.sinfo.object_size_to_shard_size(new_size)
            for_all(lambda s, t, o, c: t.truncate(c, o, shard_sz))
            if not mut.writes:
                # pure truncate invalidates cumulative CRCs (the
                # write path below refreshes/clears them otherwise)
                cleared = ecutil.HashInfo(self.k + self.m).encode()
                for_all(lambda s, t, o, c:
                        t.setattr(c, o, ecutil.HINFO_KEY, cleared))

        if mut.writes and delta_plan is not None:
            # ★ parity-delta RMW: the device computed only
            # M[:, dirty]·Δdata — parity shards apply it with a store
            # XOR, clean data shards move no data at all
            astart, hi, cols, new_cols, dchunk_off, dparity = \
                delta_plan
            new_size = max(new_size, hi)
            dhinfo = self._update_hinfo(oid, {}, dchunk_off, False)
            henc = dhinfo.encode()       # overwrite: CRCs unknowable
            for shard, txn in txns.items():
                obj = GHObject(oid, shard)
                coll = self.host.coll_of(shard)
                if shard in new_cols:
                    txn.write(coll, obj, dchunk_off, new_cols[shard])
                elif shard in dparity:
                    txn.xor_write(coll, obj, dchunk_off,
                                  dparity[shard])
                txn.setattr(coll, obj, ecutil.HINFO_KEY, henc)
        elif mut.writes:
            assert write_plan is not None, \
                "writes with data must arrive pre-encoded"
            # ★ the batched encode already happened: one [nstripes, k,
            # chunk] device call in the OSD batcher, shared with
            # concurrent ops from other PGs
            astart, hi, chunks = write_plan
            # when a truncate rides along it applied first: the final
            # size is the write end over the truncated base, never the
            # pre-truncate size
            new_size = max(new_size if mut.truncate is not None
                           else info.size, hi)
            if chunk_off is None:
                chunk_off = self.sinfo \
                    .aligned_logical_offset_to_chunk_offset(astart)
            if hinfo is None:
                is_append = mut.append_only_at(info.size) and \
                    astart >= \
                    self.sinfo.logical_to_prev_stripe_offset(info.size)
                hinfo = self._update_hinfo(oid, chunks, chunk_off,
                                           is_append)
            henc = hinfo.encode()
            for shard, txn in txns.items():
                obj = GHObject(oid, shard)
                coll = self.host.coll_of(shard)
                txn.write(coll, obj, chunk_off, chunks[shard])
                txn.setattr(coll, obj, ecutil.HINFO_KEY, henc)

        oi = ObjectInfo(size=new_size, version=op.at_version).encode()
        for_all(lambda s, t, o, c: t.setattr(c, o, OI_ATTR, oi))
        for name, value in mut.attrs.items():
            if value is None:
                for_all(lambda s, t, o, c, n=name:
                        t.rmattr(c, o, "u_" + n))
            else:
                for_all(lambda s, t, o, c, n=name, v=value:
                        t.setattr(c, o, "u_" + n, v))
        return txns

    def _update_hinfo(self, oid: str, chunks: Dict[int, bytes],
                      chunk_off: int, is_append: bool,
                      hinfo: Optional[ecutil.HashInfo] = None
                      ) -> ecutil.HashInfo:
        """Cumulative CRCs stay valid only for pure appends; any
        overwrite clears them (the reference drops hinfo on
        ec_overwrites pools).  Pass ``hinfo`` to fold a further
        segment into a running HashInfo without re-reading the
        store (pipelined segmented writes)."""
        if hinfo is None:
            obj = GHObject(oid, self.host.own_shard)
            try:
                hinfo = ecutil.HashInfo.decode(self.host.store.getattr(
                    self.host.coll, obj, ecutil.HINFO_KEY))
            except (FileNotFoundError, KeyError, ValueError):
                pass            # absent or corrupt: rebuilt below
        if hinfo is None or len(hinfo.crcs) != self.k + self.m:
            hinfo = ecutil.HashInfo(self.k + self.m)
        if is_append and hinfo.total_chunk_size == chunk_off:
            hinfo.append(chunk_off, chunks)
        else:
            hinfo.clear()               # overwrite: CRCs unknowable
        return hinfo

    def _apply_sub_write(self, shard: int, txn: Transaction,
                         wire_entries: List[dict],
                         on_commit: Callable[[], None]) -> None:
        """Shard-side sub-write application (reference handle_sub_write,
        ECBackend.cc:915-989): log entries + data in one transaction."""
        self.host.prepare_log_txn(txn, wire_entries)
        txn.register_on_commit(
            lambda: self.host.on_local_commit(on_commit))
        self.host.store.queue_transactions([txn], op="client_write")

    def _sub_write_committed(self, tid: int, shard: int,
                             seg: int = 0) -> None:
        op = self.waiting_commit.get(tid)
        if op is None:
            return
        acked = op.acked_segs.setdefault(shard, set())
        if seg in acked:
            return      # duplicate ack from a deadline re-request
        acked.add(seg)
        op.sent_subwrites.pop((shard, seg), None)
        left = op.pending_commits.get(shard, 0)
        if left <= 1:
            op.pending_commits.pop(shard, None)
        else:
            # segmented op: one reply per segment per shard (replies
            # ride ordered channels, so counting is sufficient)
            op.pending_commits[shard] = left - 1
        if not op.pending_commits:
            del self.waiting_commit[tid]
            self._cancel_deadline(op)
            if op.mutation.tracked_op is not None:
                op.mutation.tracked_op.mark_event(
                    "ec:all_shards_committed")
            # ordered sends over ordered channels make completions
            # arrive in submission order; clients observe per-object
            # commit order
            op.on_all_commit(0)
            self._complete_op(op)

    # -- sub-write deadlines (osd_ec_subwrite_timeout_ms) --------------
    def _arm_subwrite_deadline(self, op: _WriteOp, attempt: int,
                               delay: float) -> None:
        call_later = getattr(self.host, "call_later", None)
        if call_later is None:
            return           # host without timers (unit-test stubs)
        tid = op.tid
        op.deadline_timer = call_later(
            delay, lambda: self._subwrite_deadline(tid, attempt))

    def _cancel_deadline(self, op: _WriteOp) -> None:
        timer, op.deadline_timer = op.deadline_timer, None
        op.sent_subwrites.clear()
        if timer is not None:
            try:
                timer.cancel()
            except Exception:
                pass

    def _subwrite_deadline(self, tid: int, attempt: int) -> None:
        """The per-op sub-write deadline expired (fires on a timer
        thread / the reactor; re-enters the PG under its lock).  First
        expiry re-requests every outstanding sub-write from the
        laggard shards — a FRESH message with the retained fields, so
        the messenger's seq dedup can't swallow it when only the ACK
        was lost — and re-arms at double the timeout.  Second expiry
        reports the laggard peers to the monitor like a failed
        heartbeat; the resulting map change re-peers the PG and the
        client resends."""
        lock = getattr(self.host, "lock", None)
        if lock is None:
            import contextlib
            lock = contextlib.nullcontext()
        with lock:
            op = self.waiting_commit.get(tid)
            if op is None or not op.alive or op.deadline_timer is None:
                return
            op.deadline_timer = None
            self.subwrite_timeouts += 1
            perf = getattr(self.host, "osd_perf", None)
            if perf is not None:
                perf.inc("ec_subwrite_timeouts")
            acting = {s: o for s, o in self.host.acting_shards()}
            laggards = set(op.pending_commits)
            recorder = getattr(self.host, "flight_recorder", None)
            if recorder is not None:
                recorder.note("subwrite_timeout", tid=tid,
                              attempt=attempt,
                              pg=getattr(self.host, "pgid_str", "?"),
                              laggards=sorted(laggards))
                recorder.auto_dump("subwrite-timeout")
            if attempt == 1:
                resent = 0
                for (shard, seg), (parts, entries) in sorted(
                        op.sent_subwrites.items()):
                    if shard not in laggards or \
                            seg in op.acked_segs.get(shard, ()):
                        continue
                    osd = acting.get(shard)
                    if osd is None or osd == self.host.whoami:
                        continue
                    self.host.send_shard(osd, MOSDECSubOpWrite(
                        pgid=self.host.pgid_str, shard=shard,
                        from_osd=self.host.whoami, tid=tid,
                        epoch=self.host.epoch, txn=parts,
                        log_entries=entries,
                        at_version=op.at_version,
                        trace_id=op.mutation.trace_id,
                        parent_span_id=op.mutation.parent_span_id,
                        seg=seg))
                    resent += 1
                self.subwrite_retries += resent
                if perf is not None and resent:
                    perf.inc("ec_subwrite_retries", resent)
                self._arm_subwrite_deadline(
                    op, attempt=2, delay=2 * self.subwrite_timeout_s)
                return
            reported: Set[int] = set()
            for shard in laggards:
                osd = acting.get(shard)
                if osd is None or osd == self.host.whoami \
                        or osd in reported:
                    continue
                reported.add(osd)
                report = getattr(self.host, "report_laggard", None)
                if report is not None:
                    report(osd, 3 * self.subwrite_timeout_s)
            self.subwrite_peer_reports += len(reported)
            if perf is not None and reported:
                perf.inc("ec_subwrite_peer_reports", len(reported))

    # ------------------------------------------------------------------
    # read path (reference objects_read_and_reconstruct)
    # ------------------------------------------------------------------
    def objects_read(self, oid: str, offset: int, length: int,
                     cb: Callable[[int, bytes], None],
                     trace: Tuple[int, int] = (0, 0),
                     hop_msg=None) -> None:
        info = self.get_object_info(oid)
        if info is None:
            cb(-2, b"")                  # -ENOENT
            return
        if offset >= info.size or length == 0:
            cb(0, b"")
            return
        length = min(length, info.size - offset)
        astart, alen = self.sinfo.offset_len_to_stripe_bounds(
            offset, length)
        chunk_off = \
            self.sinfo.aligned_logical_offset_to_chunk_offset(astart)
        chunk_len = self.sinfo.aligned_logical_offset_to_chunk_offset(
            astart + alen) - chunk_off

        # fast_read pools fan the read to EVERY available shard and
        # reconstruct from the first k answers, trading bandwidth for
        # tail latency (reference ECBackend.cc:1043 fast_read,
        # osd_pool_default_ec_fast_read)
        fast = bool(getattr(getattr(self.host, "pool", None),
                            "fast_read", False))
        need = None
        if fast:
            shards = {s: o for s, o in self.host.acting_shards()
                      if o is not None}
            if len(shards) < self.k:
                shards = None
            else:
                need = self.k
        else:
            shards = self._min_read_shards(set(range(self.k)))
        if shards is None:
            cb(-5, b"")                  # -EIO: not enough shards up
            return
        min_needed = need if need is not None else len(shards)

        def reads_done(received: Dict[int, bytes],
                       errors: Dict[int, int]) -> None:
            if errors or len(received) < min_needed:
                cb(-5, b"")
                return
            degraded = any(i not in received for i in range(self.k))
            batcher = getattr(self.host, "encode_batcher", None)
            if degraded and batcher is not None and \
                    hasattr(self.ec_impl, "decode_batch"):
                # client-facing reconstruction rides the OSD's
                # cross-op decode batcher (ISSUE 11): concurrent
                # degraded reads of one erasure signature share one
                # batched device dispatch (full seven-phase ledger),
                # and the batcher owns routing, breaker, and the
                # CPU-twin fallback.  The continuation arrives on the
                # batcher's worker thread, so it re-enters under the
                # PG lock — same contract as recovery's
                # decode_done_async.
                if hop_msg is not None:
                    hop_msg.stamp_hop("decode_dispatch")

                def decode_done(dec) -> None:
                    lock = getattr(self.host, "lock", None)
                    if lock is None:
                        import contextlib
                        lock = contextlib.nullcontext()
                    with lock:
                        if dec is None:
                            cb(-5, b"")
                            return
                        try:
                            if hop_msg is not None:
                                hop_msg.stamp_hop("decode_complete")
                            import numpy as np
                            cs = self.sinfo.chunk_size
                            total = len(dec[0])
                            nst = total // cs if cs else 0
                            shards = np.stack(
                                [np.frombuffer(dec[i], dtype=np.uint8)
                                 .reshape(nst, cs)
                                 for i in range(self.k)], axis=1)
                            data = shards.reshape(
                                nst * self.sinfo.stripe_width
                            ).tobytes()  # copycheck: ok - shard interleave -> client payload
                        except Exception:
                            cb(-5, b"")
                            return
                        lo = offset - astart
                        cb(0, data[lo:lo + length])

                batcher.submit_decode(self.ec_impl, self.sinfo,
                                      received, set(range(self.k)),
                                      decode_done)
                return
            try:
                # client-facing decode window rides the op's ledger:
                # degraded reads reconstruct here, healthy reads
                # concat — either way the interval is the decode leg
                if hop_msg is not None:
                    hop_msg.stamp_hop("decode_dispatch")
                nbytes = sum(len(v) for v in received.values())
                impl = self._decode_impl(nbytes)
                t0 = time.time()
                data = ecutil.decode_concat(self.sinfo, impl, received)
                if hop_msg is not None:
                    hop_msg.stamp_hop("decode_complete")
                # a degraded read that reconstructed on the DEVICE
                # (routing kept the tpu impl, not the twin, and a data
                # shard was actually missing) is a device group like
                # any batched decode: fold a coarse two-stamp ledger
                # into the batcher's accumulator so dump_device and
                # the overlap engine see client-path reconstruction
                # alongside the batcher's own traffic
                if impl is self.ec_impl and \
                        hasattr(impl, "encode_batch_async"):
                    try:
                        k = impl.get_data_chunk_count()
                        if any(i not in received for i in range(k)):
                            obs = getattr(
                                getattr(self.host, "encode_batcher",
                                        None),
                                "_observe_device_ledger", None)
                            if obs is not None:
                                t1 = time.time()
                                obs({"stage_acquire": t0,
                                     "compute_start": t0,
                                     "compute_done": t1,
                                     "deliver": t1, "bytes": nbytes,
                                     "group": "decode"})
                    except Exception:
                        pass
            except Exception:
                cb(-5, b"")
                return
            lo = offset - astart
            cb(0, data[lo:lo + length])

        if hop_msg is not None:
            hop_msg.stamp_hop("read_queued")
        self._start_read(oid, chunk_off, chunk_len, shards, reads_done,
                         need=need, trace=trace)

    def _decode_impl(self, nbytes: int):
        """Decode through the CPU twin when the OSD batcher's learned
        crossover says a device round trip of this size loses (same
        economics as the encode side; bit-exact either way).  Every
        verdict is counted (``dec_route_*``) so the decode routing is
        as auditable as the encode side's."""
        batcher = getattr(self.host, "encode_batcher", None)
        if batcher is not None and \
                hasattr(self.ec_impl, "encode_batch_async"):
            if batcher.route_decode(nbytes):
                try:
                    return batcher.cpu_twin(self.ec_impl, self.sinfo)
                except Exception:
                    pass
        return self.ec_impl

    def _min_read_shards(self, want: Set[int],
                         exclude: Optional[Set[int]] = None,
                         oid: Optional[str] = None
                         ) -> Optional[Dict[int, int]]:
        """Choose the minimum shard set for reconstruction (reference
        get_min_avail_to_read_shards, ECBackend.cc:1594): the codec's
        minimum_to_decode picks data shards when whole, parity fills
        holes; LRC/SHEC/CLAY codecs pick their cheaper local sets.

        Post-split, a chunk position whose acting holder lacks the
        object may still be served by a stray (the parent's former
        shard holder) — with ``oid`` given, strays fill such holes
        (the reference reads from past-interval members the same
        way)."""
        avail = {shard: osd for shard, osd in self.host.acting_shards()
                 if osd is not None
                 and not (exclude and shard in exclude)}
        if oid is not None:
            for shard, osd in self.host.extra_recovery_sources(oid):
                if shard >= 0 and shard not in avail:
                    avail[shard] = osd
        try:
            need = self.ec_impl.minimum_to_decode(want, set(avail))
        except IOError:
            return None
        return {shard: avail[shard] for shard in need}

    def _start_read(self, oid: str, chunk_off: int, chunk_len: int,
                    shards: Dict[int, int],
                    cb: Callable[[Dict[int, bytes], Dict[int, int]],
                                 None],
                    tried: Optional[Set[int]] = None,
                    ranges: Optional[Dict[int, List[Tuple[int, int]]]]
                    = None, need: Optional[int] = None,
                    trace: Tuple[int, int] = (0, 0),
                    for_recovery: bool = False) -> None:
        rop = _ReadOp(self.new_tid(), oid, chunk_off, chunk_len,
                      dict(shards), cb, tried, ranges, need)
        rop.trace = trace
        rop.for_recovery = for_recovery
        self.in_flight_reads[rop.tid] = rop
        for shard, osd in shards.items():
            extents = rop.ranges.get(shard,
                                     [(chunk_off, chunk_len)])
            self.read_bytes_total += sum(ln for _, ln in extents)
            if osd == self.host.whoami:
                parts: List[bytes] = []
                err = 0
                for off, length in extents:
                    data, err = self._local_chunk_read(
                        oid, shard, off, length)
                    if err < 0:
                        break
                    parts.append(data)
                if err != 0:
                    piece = b""
                elif len(parts) == 1:
                    piece = parts[0]     # common case: no join copy
                else:
                    piece = b"".join(parts)  # copycheck: ok - multi-extent read reassembly
                self._read_piece(rop, shard, piece, err)
            else:
                sub = MOSDECSubOpRead(
                    pgid=self.host.pgid_str, shard=shard,
                    from_osd=self.host.whoami, tid=rop.tid,
                    epoch=self.host.epoch,
                    reads=[(oid, off, length)
                           for off, length in extents],
                    for_recovery=for_recovery,
                    trace_id=trace[0], parent_span_id=trace[1])
                # sub-read round trip opens its own ledger (mirrors
                # the sub-write path); the reply closes it at this
                # primary into the read/recovery accumulator
                sub.stamp_hop("client_send")
                self.host.send_shard(osd, sub)

    def _local_chunk_read(self, oid: str, shard: int, off: int,
                          length: int) -> Tuple[bytes, int]:
        try:
            data = self.host.store.read(
                self.host.coll_of(shard), GHObject(oid, shard), off,
                length)
        except FileNotFoundError:
            return b"", -2
        except OSError:
            # store-level csum mismatch (BlockStore EIO): treat like
            # corruption — the read retries over other shards and
            # reconstruction replaces the bytes
            return b"", -5
        if len(data) < length:
            # shards are never legitimately short (every write pads to
            # stripe bounds): a short read means truncation/corruption,
            # so error out and let reconstruction use parity instead
            return b"", -5
        if off == 0:
            # whole-shard read: verify bytes against the HashInfo CRC
            # so bit-rot surfaces as EIO and the read retries over
            # other shards (reference handle_sub_read hinfo check,
            # ECBackend.cc:1002-1048)
            try:
                hinfo = ecutil.HashInfo.decode(self.host.store.getattr(
                    self.host.coll_of(shard), GHObject(oid, shard),
                    ecutil.HINFO_KEY))
            except (FileNotFoundError, KeyError, ValueError):
                hinfo = None
            if hinfo is not None and \
                    hinfo.total_chunk_size == len(data) and \
                    ecutil.chunk_crc(data) != hinfo.crcs[shard]:
                return b"", -5
        return data, 0

    def _read_piece(self, rop: _ReadOp, shard: int, data: bytes,
                    err: int) -> None:
        if rop.tid not in self.in_flight_reads:
            return
        if err < 0:
            rop.errors[shard] = err
        else:
            rop.received[shard] = data
        if rop.need is not None and len(rop.received) >= rop.need:
            # fast_read: enough shards to reconstruct — don't wait for
            # stragglers (their late replies hit the tid-gone guard)
            del self.in_flight_reads[rop.tid]
            rop.cb(rop.received, {})
            return
        if len(rop.received) + len(rop.errors) < len(rop.want_shards):
            return
        del self.in_flight_reads[rop.tid]
        if rop.errors:
            # retry over shards not yet tried (reference
            # send_all_remaining_reads on error, ECBackend.cc:2400)
            retry = self._min_read_shards(set(range(self.k)),
                                          exclude=rop.tried)
            # allow reusing successfully-read shards from this attempt
            if retry is None:
                reuse = {s: o for s, o in self.host.acting_shards()
                         if o is not None
                         and (s in rop.received
                              or s not in rop.tried)}
                try:
                    need = self.ec_impl.minimum_to_decode(
                        set(range(self.k)), set(reuse))
                    retry = {s: reuse[s] for s in need}
                except IOError:
                    retry = None
            if retry is not None:
                self._start_read(rop.oid, rop.chunk_off, rop.chunk_len,
                                 retry, rop.cb,
                                 tried=rop.tried | set(retry),
                                 trace=getattr(rop, "trace", (0, 0)),
                                 for_recovery=getattr(
                                     rop, "for_recovery", False))
                return
        rop.cb(rop.received, rop.errors)

    # ------------------------------------------------------------------
    # recovery (reference continue_recovery_op FSM)
    # ------------------------------------------------------------------
    def recover_object(self, oid: str, version: Eversion,
                       missing_on: List[Tuple[int, int]],
                       cb: Callable[[int], None]) -> None:
        if oid in self.recovery_ops:
            cb(-16)                      # -EBUSY
            return
        rec = _RecoveryOp(oid, version, missing_on, cb)
        self.recovery_ops[oid] = rec
        info = self.get_object_info(oid)
        if info is not None:
            obj = GHObject(oid, self.host.own_shard)
            try:
                attrs = self.host.store.getattrs(self.host.coll, obj)
            except FileNotFoundError:
                attrs = {}
            self._recover_with_info(rec, info, attrs)
            return
        # primary's own shard lacks the object: fetch metadata from a
        # surviving peer first (the reference's pull path); post-split
        # strays count as surviving holders — including our own
        # physically-held source shard (mispositioned after an EC
        # split), which we can read locally
        missing_shards = {s for s, _ in missing_on}
        for s, o in self.host.extra_recovery_sources(oid):
            if o == self.host.whoami and s >= 0:
                try:
                    attrs = self.host.store.getattrs(
                        self.host.coll_of(s), GHObject(oid, s))
                except FileNotFoundError:
                    continue
                if OI_ATTR in attrs:
                    self._recover_with_info(
                        rec, ObjectInfo.decode(attrs[OI_ATTR]), attrs)
                    return
        peers = [(s, o) for s, o in self.host.acting_shards()
                 if o is not None and o != self.host.whoami
                 and s not in missing_shards]
        for s, o in self.host.extra_recovery_sources(oid):
            if s >= 0 and o != self.host.whoami and \
                    all(o != po for _, po in peers):
                peers.append((s, o))
        if not peers:
            del self.recovery_ops[oid]
            cb(-5)
            return
        shard, osd = peers[0]
        tid = self.new_tid()
        self.attr_fetches[tid] = (rec,)
        # attrs_to_read carries object names (reference ECSubRead
        # attrs_to_read is a set of hobjects)
        fetch = MOSDECSubOpRead(
            pgid=self.host.pgid_str, shard=shard,
            from_osd=self.host.whoami, tid=tid, epoch=self.host.epoch,
            reads=[], attrs_to_read=[oid], for_recovery=True)
        fetch.stamp_hop("client_send")
        self.host.send_shard(osd, fetch)

    def _attr_fetch_done(self, rec: _RecoveryOp,
                         attrs: Dict[str, bytes]) -> None:
        if rec.oid not in self.recovery_ops:
            return
        if OI_ATTR not in attrs:
            del self.recovery_ops[rec.oid]
            rec.cb(-2)
            return
        self._recover_with_info(rec, ObjectInfo.decode(attrs[OI_ATTR]),
                                attrs)

    def _recover_with_info(self, rec: _RecoveryOp, info: ObjectInfo,
                           attrs: Dict[str, bytes]) -> None:
        """READING state: gather k shards, decode missing (reference
        handle_recovery_read_complete, ECBackend.cc:414-481)."""
        shard_len = self.sinfo.object_size_to_shard_size(info.size)
        missing_shards = {s for s, _ in rec.missing_on}
        if shard_len == 0:
            self._push_recovered(
                rec, attrs, {s: b"" for s in missing_shards})
            return
        if self._try_subchunk_repair(rec, attrs, shard_len,
                                     missing_shards):
            return
        self._recover_whole(rec, attrs, shard_len, missing_shards)

    def _recover_whole(self, rec: _RecoveryOp,
                       attrs: Dict[str, bytes], shard_len: int,
                       missing_shards: Set[int]) -> None:
        """Generic recovery: stream chunk windows from the minimum
        shard set and batch-decode the missing ones.  The window is
        osd_recovery_chunk_size logical bytes (reference
        get_recovery_chunk_size, ECBackend.h:206) so one huge object
        can't hold k shards' worth of its bytes in memory at once."""
        oid = rec.oid
        shards = self._min_read_shards(set(missing_shards),
                                       exclude=missing_shards,
                                       oid=oid)
        if shards is None:
            self.recovery_ops.pop(oid, None)
            rec.cb(-5)
            return
        try:
            logical = self.host.conf["osd_recovery_chunk_size"]
        except (AttributeError, KeyError):
            logical = 8 << 20
        win = max(self.sinfo.chunk_size,
                  self.sinfo.object_size_to_shard_size(logical))
        win -= win % self.sinfo.chunk_size
        pieces: Dict[int, List[bytes]] = {s: [] for s in missing_shards}
        state = {"off": 0}

        def read_next() -> None:
            length = min(win, shard_len - state["off"])
            self._start_read(oid, state["off"], length, shards,
                             reads_done, for_recovery=True)

        def reads_done(received: Dict[int, bytes],
                       errors: Dict[int, int]) -> None:
            if rec.oid not in self.recovery_ops:
                return
            if errors or len(received) < len(shards):
                self.recovery_ops.pop(oid, None)
                rec.cb(-5)
                return
            # the decode window gets its own two-stamp ledger
            # (decode_dispatch -> decode_complete) charged into the
            # recovery waterfall when the decode lands
            state["dec_t0"] = time.time()
            # recovery decodes ride the OSD's cross-op batcher: every
            # object of a rebuild lost the SAME shard (one erasure
            # signature), so concurrent recovery ops coalesce into one
            # batched decode call (VERDICT r4 Next #3; the reference
            # decodes per recovery window on the submitting thread,
            # reference ECBackend.cc:414-481)
            batcher = getattr(self.host, "encode_batcher", None)
            if batcher is not None and \
                    hasattr(self.ec_impl, "decode_batch"):
                batcher.submit_decode(
                    self.ec_impl, self.sinfo, received,
                    set(missing_shards),
                    lambda dec: decode_done_async(dec))
                return
            try:
                nbytes = sum(len(v) for v in received.values())
                dec = ecutil.decode(self.sinfo,
                                    self._decode_impl(nbytes),
                                    received, set(missing_shards))
            except Exception:
                dec = None
            decoded(dec)

        def decode_done_async(dec) -> None:
            """Continuation from the batcher's collector thread:
            re-enter the PG under its lock (same contract as
            _encode_done)."""
            lock = getattr(self.host, "lock", None)
            if lock is None:
                import contextlib
                lock = contextlib.nullcontext()
            with lock:
                if rec.oid not in self.recovery_ops:
                    return
                decoded(dec)

        def decoded(dec) -> None:
            t0 = state.pop("dec_t0", None)
            if t0 is not None:
                _obs = getattr(self.host, "observe_hops", None)
                if _obs is not None:
                    _obs({"decode_dispatch": t0,
                          "decode_complete": time.time()},
                         kind="recovery")
            if dec is None:
                self.recovery_ops.pop(oid, None)
                rec.cb(-5)
                return
            for s in missing_shards:
                pieces[s].append(dec[s])
            state["off"] += win
            if state["off"] >= shard_len:
                # single-window objects skip the join copy entirely;
                # multi-window recovery gathers once
                self._push_recovered(
                    rec, attrs,
                    {s: (pieces[s][0] if len(pieces[s]) == 1
                         else b"".join(pieces[s]))  # copycheck: ok - multi-window recovery gather
                     for s in missing_shards})
            else:
                read_next()

        read_next()

    def _try_subchunk_repair(self, rec: _RecoveryOp,
                             attrs: Dict[str, bytes], shard_len: int,
                             missing_shards: Set[int]) -> bool:
        """CLAY MSR single-shard repair: read only the repair
        sub-chunks (q^(t-1) of q^t planes) from each of d helpers
        instead of whole chunks from k — the repair-bandwidth saving
        that makes CLAY MSR (reference ECBackend.cc:1594
        get_min_avail_to_read_shards consulting the plugin +
        ErasureCodeClay::get_repair_subchunks, :334-392)."""
        impl = self.ec_impl
        if len(missing_shards) != 1:
            return False
        sub_no = getattr(impl, "get_sub_chunk_count", lambda: 1)()
        if sub_no <= 1 or shard_len % sub_no:
            return False
        avail_map = {s: o for s, o in self.host.acting_shards()
                     if o is not None and s not in missing_shards}
        want = set(missing_shards)
        try:
            if not impl.is_repair(want, set(avail_map)):
                return False
            minimum = impl.minimum_to_repair(want, set(avail_map))
        except Exception:
            return False
        sc = shard_len // sub_no
        ranges = {c: [(off * sc, cnt * sc) for off, cnt in runs]
                  for c, runs in minimum.items()}
        shards = {c: avail_map[c] for c in minimum}
        oid = rec.oid

        def reads_done(received: Dict[int, bytes],
                       errors: Dict[int, int]) -> None:
            if rec.oid not in self.recovery_ops:
                return
            dec = None
            if not errors and len(received) == len(shards):
                try:
                    dec = impl.decode(want, received, shard_len)
                except Exception:
                    dec = None
            if dec is None:
                # a helper failed or repair math balked: fall back to
                # the whole-chunk path rather than failing the object
                self._recover_whole(rec, attrs, shard_len,
                                    missing_shards)
                return
            # stats record SUCCESSFUL repairs only — a fallback would
            # otherwise report savings that did not happen
            self.subchunk_repairs += 1
            self.repair_read_bytes += sum(
                ln for runs in ranges.values() for _, ln in runs)
            self.repair_whole_bytes += self.k * shard_len
            self._push_recovered(rec, attrs, dec)

        self._start_read(oid, 0, shard_len, shards, reads_done,
                         ranges=ranges, for_recovery=True)
        return True

    def _push_recovered(self, rec: _RecoveryOp, attrs: Dict[str, bytes],
                        dec: Dict[int, bytes]) -> None:
        """WRITING state: push decoded chunks + attrs to missing shards
        (reference ECBackend.cc:634+)."""
        for shard, osd in rec.missing_on:
            rec.pending_pushes.add(shard)
        for shard, osd in rec.missing_on:
            push = PushOp(oid=rec.oid, data_offset=0,
                          data=dec.get(shard, b""),
                          attrs=dict(attrs), complete=True,
                          version=rec.version)
            if osd == self.host.whoami:
                self._apply_push(shard, push,
                                 lambda s=shard: self._push_acked(
                                     rec.oid, s))
            else:
                pmsg = MOSDPGPush(
                    pgid=self.host.pgid_str, shard=shard,
                    from_osd=self.host.whoami, epoch=self.host.epoch,
                    pushes=[push])
                pmsg.stamp_hop("client_send")
                self.host.send_shard(osd, pmsg)

    def _apply_push(self, shard: int, push: PushOp,
                    on_commit: Callable[[], None]) -> None:
        """Shard-side recovery write (reference handle_recovery_push)."""
        coll = self.host.coll_of(shard)
        obj = GHObject(push.oid, shard)
        # late answers from abandoned recovery rounds must not roll a
        # shard back (strictly-newer check: equal-version pushes are
        # scrub repairs and must apply)
        info = self.get_object_info(push.oid, shard=shard)
        if info is not None and \
                tuple(info.version) > tuple(push.version):
            on_commit()
            return
        txn = Transaction()
        # remove-then-recreate: a stale local copy must not leak attrs
        # the authoritative copy no longer has
        txn.remove(coll, obj)
        txn.touch(coll, obj)
        if push.data:
            txn.write(coll, obj, push.data_offset, push.data)
        if push.attrs:
            txn.setattrs(coll, obj, push.attrs)

        def committed() -> None:
            self.host.note_object_recovered(push.oid, push.version)
            on_commit()
        txn.register_on_commit(
            lambda: self.host.on_local_commit(committed))
        self.host.store.queue_transactions([txn], op="recovery_push")

    def _push_acked(self, oid: str, shard: int) -> None:
        rec = self.recovery_ops.get(oid)
        if rec is None:
            return
        rec.pending_pushes.discard(shard)
        if not rec.pending_pushes:
            del self.recovery_ops[oid]
            rec.cb(0)

    # ------------------------------------------------------------------
    # message dispatch (both roles)
    # ------------------------------------------------------------------
    def handle_message(self, msg) -> bool:
        if isinstance(msg, MOSDECSubOpWrite):
            span = self.host.trace_span(
                "ec_sub_write", msg.trace_id,
                getattr(msg, "parent_span_id", 0))
            if span is not None:
                # child span per shard sub-write, parented under the
                # primary's osd_op span (reference ECBackend.cc:
                # 2063-2068 blkin spans)
                span.tag("shard", msg.shard).tag(
                    "pgid", msg.pgid).finish()
            seg = getattr(msg, "seg", 0)
            key = (msg.from_osd, msg.tid, seg)
            done = self._recent_subwrites.get(key)
            if done is not None:
                # deadline re-request of a sub-write we already have:
                # committed → re-ack (the original ack was lost);
                # still applying → stay silent, its ack is coming.
                # Either way NEVER re-apply (log entries must not
                # append twice).
                if done:
                    reack = MOSDECSubOpWriteReply(
                        pgid=self.host.pgid_str, shard=msg.shard,
                        from_osd=self.host.whoami, tid=msg.tid,
                        epoch=self.host.epoch, seg=seg)
                    if msg.hops:
                        reack.hops = dict(msg.hops)
                    reack.stamp_hop("commit_sent")
                    self.host.send_shard(msg.from_osd, reack)
                return True
            self._recent_subwrites[key] = False
            while len(self._recent_subwrites) > 512:
                self._recent_subwrites.pop(
                    next(iter(self._recent_subwrites)))
            txn = Transaction.decode(msg.txn)

            def _committed(m=msg, k=key, s=seg):
                self._recent_subwrites[k] = True
                m.stamp_hop("store_apply")
                reply = MOSDECSubOpWriteReply(
                    pgid=self.host.pgid_str, shard=m.shard,
                    from_osd=self.host.whoami, tid=m.tid,
                    epoch=self.host.epoch, seg=s)
                # ledger rides the round trip back to the primary
                if m.hops:
                    reply.hops = dict(m.hops)
                reply.stamp_hop("commit_sent")
                self.host.send_shard(m.from_osd, reply)
            self._apply_sub_write(msg.shard, txn, msg.log_entries,
                                  _committed)
            return True
        if isinstance(msg, MOSDECSubOpWriteReply):
            if faultlib.registry().check_drop(
                    faultlib.EC_SUBWRITE_ACK):
                return True  # ack lost: the deadline re-requests
            # sub-op waterfall closes at the primary: charge the
            # round trip into this OSD's hops view
            msg.stamp_hop("client_complete")
            _obs = getattr(self.host, "observe_hops", None)
            if _obs is not None:
                _obs(msg.hops)
            self._sub_write_committed(msg.tid, msg.shard,
                                      getattr(msg, "seg", 0))
            return True
        if isinstance(msg, MOSDECSubOpRead):
            span = self.host.trace_span(
                "ec_sub_read", getattr(msg, "trace_id", 0),
                getattr(msg, "parent_span_id", 0))
            if span is not None:
                span.tag("shard", msg.shard).tag(
                    "pgid", msg.pgid).finish()
            self._handle_sub_read(msg)
            return True
        if isinstance(msg, MOSDECSubOpReadReply):
            # sub-read waterfall closes at the primary, split by WHY
            # the read ran (client-facing reconstruction vs recovery)
            if msg.tid in self.attr_fetches:
                msg.stamp_hop("client_complete")
                _obs = getattr(self.host, "observe_hops", None)
                if _obs is not None:
                    _obs(msg.hops, kind="recovery")
                (rec,) = self.attr_fetches.pop(msg.tid)
                attrs = dict(msg.attrs[0][1]) if msg.attrs else {}
                self._attr_fetch_done(rec, attrs)
                return True
            rop = self.in_flight_reads.get(msg.tid)
            if rop is None:
                return True
            msg.stamp_hop("client_complete")
            _obs = getattr(self.host, "observe_hops", None)
            if _obs is not None:
                _obs(msg.hops,
                     kind="recovery" if getattr(rop, "for_recovery",
                                                False) else "read")
            if msg.errors:
                self._read_piece(rop, msg.shard, b"",
                                 msg.errors[0][1])
            elif msg.buffers:
                # multi-extent replies (CLAY sub-chunk repair runs)
                # concatenate in request order into one payload;
                # single-extent replies pass through copy-free
                if len(msg.buffers) == 1:
                    self._read_piece(rop, msg.shard,
                                     msg.buffers[0][2], 0)
                else:
                    self._read_piece(
                        rop, msg.shard,
                        b"".join(  # copycheck: ok - multi-buffer read-reply reassembly
                            b for _, _, b in msg.buffers), 0)
            return True
        if isinstance(msg, MOSDPGPush):
            def _push_done(p, m=msg):
                # recovery write landed: ledger rides the ack back to
                # the primary (same shape as the sub-write round trip)
                m.stamp_hop("store_apply")
                ack = MOSDPGPushReply(
                    pgid=self.host.pgid_str, shard=m.shard,
                    from_osd=self.host.whoami,
                    epoch=self.host.epoch, oids=[p.oid])
                if m.hops:
                    ack.hops = dict(m.hops)
                ack.stamp_hop("commit_sent")
                self.host.send_shard(m.from_osd, ack)
            for push in msg.pushes:
                self._apply_push(msg.shard, push,
                                 lambda p=push: _push_done(p))
            return True
        if isinstance(msg, MOSDPGPushReply):
            msg.stamp_hop("client_complete")
            _obs = getattr(self.host, "observe_hops", None)
            if _obs is not None:
                _obs(msg.hops, kind="recovery")
            for oid in msg.oids:
                self._push_acked(oid, msg.shard)
            return True
        return False

    def _handle_sub_read(self, msg: MOSDECSubOpRead) -> None:
        """Shard-side chunk read (reference handle_sub_read,
        ECBackend.cc:991)."""
        reply = MOSDECSubOpReadReply(
            pgid=self.host.pgid_str, shard=msg.shard,
            from_osd=self.host.whoami, tid=msg.tid,
            epoch=self.host.epoch)
        for oid, off, length in msg.reads:
            data, err = self._local_chunk_read(oid, msg.shard, off,
                                               length)
            if err < 0:
                reply.errors.append((oid, err))
            else:
                reply.buffers.append((oid, off, data))
        for oid in msg.attrs_to_read:
            try:
                attrs = self.host.store.getattrs(
                    self.host.coll_of(msg.shard), GHObject(oid, msg.shard))
                reply.attrs.append((oid, attrs))
            except FileNotFoundError:
                reply.errors.append((oid, -2))
        # local chunk service complete: the interval since pg_locked is
        # the shard's read work, and the ledger rides the reply home
        msg.stamp_hop("shard_read")
        if msg.hops:
            reply.hops = dict(msg.hops)
        reply.stamp_hop("commit_sent")
        self.host.send_shard(msg.from_osd, reply)

    def inflight_writes(self) -> int:
        return len(self._pipeline)

    def build_scrub_map(self, deep: bool) -> Dict[str, dict]:
        """Per-shard-object snapshot (reference ECBackend::be_deep_scrub,
        ECBackend.cc:2475-2579): under deep, recompute this shard's CRC
        from stored bytes and compare against the HashInfo xattr — no
        decode on scrub.  ``hinfo_ok`` is None when the CRC is
        unknowable (overwritten object cleared its cumulative CRCs).

        Deep CRCs batch per scrub window (ISSUE 11): CRC32C is a
        GF(2)-affine map, so a whole window of objects checksums as
        ONE bitmatrix matmul through the codec backend
        (ops/crclinear) instead of a per-chunk CPU loop.  With
        ``osd_deep_scrub_syndrome`` the same apply also emits GF
        syndrome CRC partials — XORed across shards by the primary,
        zero iff the whole code word is consistent — a distributed
        whole-stripe check the reference's per-shard CRC compare
        cannot see."""
        out: Dict[str, dict] = {}
        store = self.host.store
        shard = self.host.own_shard
        coll = self.host.coll
        pending = []                 # (entry, data, hinfo) for deep
        for obj in store.collection_list(coll):
            if obj.oid.startswith("_pgmeta"):
                continue
            try:
                st = store.stat(coll, obj)
                entry: Dict[str, object] = {"size": st.size,
                                            "shard": shard}
                info = self.get_object_info(obj.oid)
                entry["oi_version"] = list(info.version) if info else None
                if info is not None:
                    entry["expect_size"] = \
                        self.sinfo.object_size_to_shard_size(info.size)
                hinfo = None
                try:
                    hinfo = ecutil.HashInfo.decode(store.getattr(
                        coll, obj, ecutil.HINFO_KEY))
                except (FileNotFoundError, KeyError, ValueError):
                    pass
                if deep:
                    data = store.read(coll, obj)
                    pending.append((entry, data, hinfo))
            except OSError:
                # missing OR store-csum EIO: both scrub as read_error
                # and repair via recovery
                entry = {"error": "read_error", "shard": shard}
            out[obj.oid] = entry
        if pending:
            self._scrub_fill_crcs(pending)
            for entry, data, hinfo in pending:
                if hinfo is not None and \
                        hinfo.total_chunk_size == len(data):
                    entry["stored_crc"] = hinfo.crcs[shard]
                    entry["hinfo_ok"] = \
                        hinfo.crcs[shard] == entry["data_crc"]
                else:
                    entry["hinfo_ok"] = None        # CRC unknowable
        return out

    def _scrub_fill_crcs(self, pending) -> None:
        """Fill ``data_crc`` (and, when osd_deep_scrub_syndrome is
        on, ``syndrome_partials``) for every pending deep-scrub
        entry, one batched linear-CRC apply per
        ``ec_tpu_scrub_window_bytes`` window.  Any window trouble
        falls that window back to the per-chunk CPU loop — scrub
        must never fail an object on device grounds."""
        def conf(key, dflt):
            try:
                return self.host.conf[key]
            except (AttributeError, KeyError, TypeError):
                return dflt
        wbytes = max(1 << 20, int(conf("ec_tpu_scrub_window_bytes",
                                       16 << 20)))
        shard = self.host.own_shard
        from ..ops import crclinear
        lin = crclinear.shared()
        backend = getattr(getattr(self.ec_impl, "core", None),
                          "backend", None)
        if backend is not None and \
                not hasattr(backend, "apply_bitmatrix_bytes"):
            backend = None
        scales = None
        if conf("osd_deep_scrub_syndrome", False):
            cm = getattr(getattr(self.ec_impl, "core", None),
                         "coding_matrix", None)
            if cm is not None and getattr(self.ec_impl, "w", 0) == 8:
                if shard < self.k:
                    scales = [int(cm[e][shard])
                              for e in range(self.m)]
                else:
                    scales = [1 if e == shard - self.k else 0
                              for e in range(self.m)]
        # the batched bitmatrix CRC only beats the native per-chunk
        # host kernel when an accelerator executes the apply OR the
        # GF syndrome bands must fold into the same matmul; on a
        # plain-CPU box with syndrome off, the pre-existing host
        # loop is strictly faster, so route there
        accel = False
        try:
            import jax
            accel = jax.default_backend() != "cpu"
        except Exception:
            pass
        _obs = getattr(self.host, "observe_hops", None)
        import numpy as np
        i = 0
        while i < len(pending):
            t0 = time.time()
            j, acc = i, 0
            while j < len(pending) and \
                    (j == i or acc + len(pending[j][1]) <= wbytes):
                acc += len(pending[j][1])
                j += 1
            window = pending[i:j]
            chunks = [p[1] for p in window]
            lens = [len(c) for c in chunks]
            try:
                if scales is None and not (accel and
                                           backend is not None):
                    raise _HostCrcWindow
                if scales is not None:
                    # distinct nonzero syndrome scales share the data
                    # band's apply: bands = (1, *scales) in one matmul
                    nz = sorted({s for s in scales if s})
                    Lmax = max(lens) if lens else 0
                    stack = np.zeros((len(chunks), Lmax),
                                     dtype=np.uint8)
                    for idx, c in enumerate(chunks):
                        if lens[idx]:
                            stack[idx, Lmax - lens[idx]:] = \
                                np.frombuffer(c, dtype=np.uint8)
                    parts = lin._apply_window(
                        stack, (1,) + tuple(nz), backend=backend)
                    zero = np.array([lin.zero_crc(n) for n in lens],
                                    dtype=np.uint32)
                    crcs = parts[0] ^ zero
                    for idx, (entry, _d, _h) in enumerate(window):
                        entry["data_crc"] = int(crcs[idx])
                        entry["syndrome_partials"] = [
                            int(parts[1 + nz.index(s)][idx])
                            if s else 0 for s in scales]
                else:
                    crcs = lin.crc_batch(chunks, backend=backend)
                    for idx, (entry, _d, _h) in enumerate(window):
                        entry["data_crc"] = int(crcs[idx])
                self.scrub_device_windows = getattr(
                    self, "scrub_device_windows", 0) + 1
            except Exception:
                for entry, data, _h in window:
                    entry["data_crc"] = ecutil.chunk_crc(data)
            self.scrub_windows = getattr(self, "scrub_windows", 0) + 1
            self.scrub_crc_bytes = getattr(
                self, "scrub_crc_bytes", 0) + sum(lens)
            if _obs is not None:
                # one scrub_window hop per batched window: the scrub
                # waterfall attributes checksum time per window, not
                # per object
                _obs({"pg_locked": t0, "scrub_window": time.time()},
                     kind="recovery")
            i = j

    def on_change(self) -> None:
        """New interval: drop every in-flight op (reference on_change);
        clients resend against the new acting set."""
        for op in self._pipeline:
            op.alive = False         # late encode callbacks must drop
        for op in self.waiting_commit.values():
            self._cancel_deadline(op)
        self._pending_objs.clear()
        self.waiting_commit.clear()
        self.in_flight_reads.clear()
        self.attr_fetches.clear()
        self.recovery_ops.clear()
        self._pipeline.clear()
