"""Stripe algebra + batched stripe codec for the EC backend.

Python-native equivalent of the reference's ECUtil (reference
src/osd/ECUtil.{h,cc}):

* ``StripeInfo`` — the reference's ``stripe_info_t`` (ECUtil.h:27-81):
  stripe_width = k * chunk_size and the offset algebra between logical
  object extents and per-shard chunk extents;
* ``encode`` / ``decode`` — the reference's per-stripe loops
  (ECUtil.cc:120-159 encode, :9-118 decode), re-designed TPU-first:
  instead of calling the codec once per stripe_width block, the whole
  aligned extent is reshaped to a ``[nstripes, k, chunk]`` array and
  encoded in ONE batched device call (the plugin's ``encode_batch``;
  SURVEY.md §3.1 "HOT LOOP" / §5 "batch the stripe loop into one
  [batch, k, chunk] device call").  Codecs without the batched API
  (jerasure/isa/lrc/shec/clay CPU plugins) fall back to the reference's
  per-stripe loop;
* ``HashInfo`` — per-shard cumulative CRC xattr (reference ECUtil.h:
  161-245, key ``hinfo_key``) used by append writes and deep scrub
  (reference ECBackend.cc:2475 compares chunk CRCs, no decode).
"""
from __future__ import annotations

import json
from ..utils.crc import crc32c
from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

import numpy as np

HINFO_KEY = "hinfo_key"  # reference ECUtil.h ECUtil::get_hinfo_key()


def nbytes_of(data) -> int:
    """Byte length of any bytes-like (bytes, bytearray, memoryview,
    uint8 ndarray) — the write path now threads views and arrays, not
    just bytes."""
    if isinstance(data, (bytes, bytearray)):
        return len(data)
    if isinstance(data, np.ndarray):
        return data.nbytes
    return memoryview(data).nbytes


def as_stripe_array(data, nstripes: int, k: int,
                    chunk_size: int) -> np.ndarray:
    """View ``data`` as a [nstripes, k, chunk] uint8 array without
    copying (buffer-protocol objects and ndarrays alike)."""
    if isinstance(data, np.ndarray):
        arr = data if data.dtype == np.uint8 \
            else data.view(np.uint8)
        return arr.reshape(nstripes, k, chunk_size)
    return np.frombuffer(data, dtype=np.uint8).reshape(
        nstripes, k, chunk_size)


class StripeInfo:
    """reference ECUtil::stripe_info_t (ECUtil.h:27)."""

    def __init__(self, k: int, stripe_width: int):
        assert stripe_width % k == 0, \
            f"stripe_width {stripe_width} not a multiple of k {k}"
        self.k = k
        self.stripe_width = stripe_width
        self.chunk_size = stripe_width // k

    # -- offset algebra (reference ECUtil.h:44-81) ------------------------
    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return ((offset + self.stripe_width - 1) //
                self.stripe_width) * self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        return ((offset + self.stripe_width - 1) //
                self.stripe_width) * self.stripe_width

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def chunk_aligned_logical_offset_to_chunk_offset(
            self, offset: int) -> int:
        return self.logical_to_prev_chunk_offset(offset)

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def offset_len_to_stripe_bounds(
            self, offset: int, length: int) -> Tuple[int, int]:
        """Logical extent -> enclosing stripe-aligned extent
        (reference offset_len_to_stripe_bounds)."""
        start = self.logical_to_prev_stripe_offset(offset)
        end = self.logical_to_next_stripe_offset(offset + length)
        return start, end - start

    def object_size_to_shard_size(self, size: int) -> int:
        """Logical (stripe-padded) object size -> per-shard object size."""
        return self.logical_to_next_chunk_offset(size)


# ---------------------------------------------------------------------------
# batched stripe encode / decode
# ---------------------------------------------------------------------------

def encode(sinfo: StripeInfo, ec_impl, data: bytes,
           want: Optional[Set[int]] = None) -> Dict[int, bytes]:
    """Encode a stripe-aligned extent into per-shard chunk buffers.

    Reference ECUtil::encode (ECUtil.cc:120-159) loops stripe-by-stripe
    calling ec_impl->encode per stripe_width block; here the whole
    extent becomes one [nstripes, k, chunk] batch and a single device
    call when the codec has ``encode_batch`` (the tpu plugin), else the
    per-stripe loop runs on the CPU codec.

    Returns {shard_id: chunk_bytes} of len nstripes*chunk_size each.
    """
    k = ec_impl.get_data_chunk_count()
    m = ec_impl.get_coding_chunk_count()
    nb = nbytes_of(data)
    assert nb % sinfo.stripe_width == 0, \
        f"len {nb} not stripe aligned"
    if want is None:
        want = set(range(k + m))
    nstripes = nb // sinfo.stripe_width
    if nstripes == 0:
        return {i: b"" for i in want}

    arr = as_stripe_array(data, nstripes, k, sinfo.chunk_size)
    if hasattr(ec_impl, "encode_batch"):
        parity = ec_impl.encode_batch(arr)          # [B, m, chunk]
        out: Dict[int, bytes] = {}
        for i in want:
            if i < k:
                out[i] = arr[:, i].tobytes()
            else:
                out[i] = parity[:, i - k].tobytes()
        return out

    # CPU fallback: the reference's sequential per-stripe loop
    chunks: Dict[int, List[bytes]] = {i: [] for i in want}
    for s in range(nstripes):
        encoded = ec_impl.encode(set(range(k + m)),
                                 arr[s].tobytes())
        for i in want:
            chunks[i].append(encoded[i])
    return {i: b"".join(chunks[i]) for i in want}


def decode(sinfo: StripeInfo, ec_impl,
           have: Mapping[int, bytes],
           want: Set[int]) -> Dict[int, bytes]:
    """Reconstruct wanted shard chunks from available ones, batched.

    Reference ECUtil::decode (ECUtil.cc:47-118): per-stripe
    decode_chunks; here all stripes of the extent decode in one batched
    call when the codec supports it (tpu plugin's ``decode_batch``).
    Every buffer in ``have`` must be the same chunk-aligned length.
    """
    if not have:
        raise IOError("no chunks to decode from")
    total = nbytes_of(next(iter(have.values())))
    assert all(nbytes_of(v) == total for v in have.values()), \
        "shard buffers must be equal length"
    assert total % sinfo.chunk_size == 0
    nstripes = total // sinfo.chunk_size
    missing = set(want) - set(have)
    if not missing:
        return {i: bytes(have[i]) for i in want}
    if nstripes == 0:
        return {i: b"" for i in want}

    if hasattr(ec_impl, "decode_batch"):
        present = {i: as_stripe_array(v, nstripes, 1, sinfo.chunk_size)
                   .reshape(nstripes, sinfo.chunk_size)
                   for i, v in have.items()}
        rec = ec_impl.decode_batch(present, sinfo.chunk_size)
        out: Dict[int, bytes] = {}
        for i in want:
            if i in have:
                out[i] = bytes(have[i])
            else:
                out[i] = np.ascontiguousarray(rec[i]).tobytes()
        return out

    # CPU fallback: per-stripe decode
    parts: Dict[int, List[bytes]] = {i: [] for i in want}
    for s in range(nstripes):
        lo, hi = s * sinfo.chunk_size, (s + 1) * sinfo.chunk_size
        stripe_have = {i: v[lo:hi] for i, v in have.items()}
        dec = ec_impl.decode(set(want), stripe_have, sinfo.chunk_size)
        for i in want:
            parts[i].append(dec[i])
    return {i: b"".join(parts[i]) for i in want}


def decode_concat(sinfo: StripeInfo, ec_impl,
                  have: Mapping[int, bytes]) -> bytes:
    """Reconstruct and concatenate the k data shards back into the
    logical byte stream (reference ECUtil::decode concat variant,
    ECUtil.cc:9-45)."""
    k = ec_impl.get_data_chunk_count()
    want = set(range(k))
    dec = decode(sinfo, ec_impl, have, want)
    total = len(next(iter(dec.values())))
    nstripes = total // sinfo.chunk_size if sinfo.chunk_size else 0
    if nstripes == 0:
        return b""
    shards = np.stack([np.frombuffer(dec[i], dtype=np.uint8).reshape(
        nstripes, sinfo.chunk_size) for i in range(k)], axis=1)
    return shards.reshape(nstripes * sinfo.stripe_width).tobytes()


# ---------------------------------------------------------------------------
# HashInfo (reference ECUtil.h:161-245)
# ---------------------------------------------------------------------------

class HashInfo:
    """Cumulative per-shard chunk CRC + total logical chunk size,
    persisted as the ``hinfo_key`` xattr on every shard object.

    Append-only accounting exactly like the reference: each
    append_chunks() call folds the new chunk bytes into each shard's
    running CRC (reference HashInfo::append).  Deep scrub recomputes a
    shard's CRC from stored bytes and compares — no decode needed
    (reference ECBackend.cc:2475-2579).
    """

    def __init__(self, num_chunks: int):
        self.total_chunk_size = 0            # per-shard bytes hashed
        self.crcs: List[int] = [0] * num_chunks

    def append(self, old_size: int, chunks: Mapping[int, bytes]) -> None:
        assert old_size == self.total_chunk_size, \
            f"append at {old_size} != hashed {self.total_chunk_size}"
        size = None
        for i, buf in chunks.items():
            # crc32c reads straight from the buffer — no bytes() copy
            self.crcs[i] = crc32c(buf, self.crcs[i])
            if size is None:
                size = nbytes_of(buf)
            assert size == nbytes_of(buf), "unequal chunk appends"
        if size:
            self.total_chunk_size += size

    def clear(self) -> None:
        self.total_chunk_size = 0
        self.crcs = [0] * len(self.crcs)

    def encode(self) -> bytes:
        return json.dumps({"s": self.total_chunk_size,
                           "c": self.crcs}).encode()

    @classmethod
    def decode(cls, buf: bytes) -> "HashInfo":
        d = json.loads(buf.decode())
        hi = cls(len(d["c"]))
        hi.total_chunk_size = d["s"]
        hi.crcs = list(d["c"])
        return hi


def chunk_crc(data) -> int:
    """CRC of a full shard object, for deep-scrub comparison."""
    return crc32c(data)
