"""OSD daemon — hosts PGs, serves clients, heartbeats peers.

Python-native equivalent of the reference's OSD/OSDService (reference
src/osd/OSD.{h,cc} 10.8k LoC) reduced to the daemon duties the
framework's PG/backend stack needs:

* **boot** (reference OSD::init :3262 + _send_boot): mount the store,
  subscribe to osdmaps, announce ourselves to the monitor (MOSDBoot);
  restart is resume — PGs reload their logs from the store when the
  first map arrives;
* **map handling** (reference handle_osd_map :7753 +
  handle_advance_map): every published epoch advances all hosted PGs;
  PGs are instantiated on demand for any pool whose CRUSH mapping
  places a shard here (reference load_pgs / handle_pg_create);
* **op dispatch** (reference ms_fast_dispatch :7008 -> enqueue_op
  :9612 -> op_shardedwq): client MOSDOps land in a sharded op queue
  (``osd_op_num_shards`` × ``osd_op_num_threads_per_shard`` workers,
  reference common/options.cc:2869-2901) hashed by PG so per-PG order
  holds — **this queue is the TPU plugin's batching point** (SURVEY.md
  §3.1): stripes from many in-flight ops on different PGs gather into
  one device call; backend sub-ops fast-dispatch inline (reference
  fast dispatch bypasses the queue for sub-ops);
* **heartbeats + failure reports** (reference OSD.cc:5079-5632): ping
  every up peer on an interval; a peer silent past
  ``osd_heartbeat_grace`` is reported to the monitor (MOSDFailure),
  which marks it down once enough distinct reporters agree;
* **recovery driving** (reference start_recovery_ops + recovery wq):
  a background thread drains primary PGs' missing sets through their
  backends, ``osd_recovery_max_active`` object recoveries at a time;
* **PG stats** (reference MPGStats tick): primaries report per-PG
  state to the monitor, feeding ``status``/``wait_for_clean``.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..ec import registry as ec_registry
from ..mon.client import MonClient
from ..msg.messages import (MCommand, MCommandReply, MOSDECSubOpRead,
                            MOSDECSubOpReadReply, MOSDECSubOpWrite,
                            MOSDECSubOpWriteReply, MOSDMap, MOSDOp,
                            MOSDPGLog, MOSDPGNotify, MOSDPGPull,
                            MOSDPGPush, MOSDPGPushReply, MOSDPGQuery,
                            MOSDPGRemove,
                            MOSDPing, MOSDRepOp, MOSDRepOpReply,
                            MOSDScrub, MRepScrub, MRepScrubMap)
from ..msg.messenger import Connection, Dispatcher, Messenger
from ..store.objectstore import ObjectStore
from ..utils.config import Config, default_config
from ..utils.lockdep import make_lock
from ..utils.log import Dout
from .osdmap import OSDMap, PGid
from .pg import PG, STATE_ACTIVE, STATE_PEERING

_BACKEND_MSGS = (MOSDECSubOpWrite, MOSDECSubOpWriteReply,
                 MOSDECSubOpRead, MOSDECSubOpReadReply,
                 MOSDRepOp, MOSDRepOpReply, MOSDPGPush,
                 MOSDPGPushReply, MOSDPGPull)
_PEERING_MSGS = (MOSDPGQuery, MOSDPGNotify, MOSDPGLog)


class OSDService:
    """The narrow service surface PGs and backends consume (reference
    OSDService in osd/OSD.h)."""

    def __init__(self, osd: "OSD"):
        self._osd = osd

    @property
    def whoami(self) -> int:
        return self._osd.whoami

    @property
    def conf(self) -> Config:
        return self._osd.conf

    @property
    def store(self) -> ObjectStore:
        return self._osd.store

    @property
    def ec_registry(self):
        return self._osd.ec_registry

    @property
    def encode_batcher(self):
        return self._osd.encode_batcher

    @property
    def tracer(self):
        return self._osd.tracer

    @property
    def perf(self):
        return self._osd.perf

    @property
    def flight_recorder(self):
        return self._osd.flight_recorder

    @property
    def hops(self):
        return self._osd.hops

    @property
    def hops_read(self):
        return self._osd.hops_read

    @property
    def hops_recovery(self):
        return self._osd.hops_recovery

    @property
    def slo(self):
        return self._osd.slo

    @property
    def contention(self):
        return self._osd.contention

    def call_later(self, delay: float, fn):
        """Cancellable one-shot timer (EC sub-write deadlines); the
        crimson OSD substitutes a reactor timer."""
        return self._osd._call_later(delay, fn)

    def report_laggard(self, osd: int, elapsed: float) -> None:
        self._osd.report_laggard(osd, elapsed)

    def get_osdmap(self) -> OSDMap:
        return self._osd.osdmap

    def send_osd(self, osd: int, msg) -> None:
        self._osd.send_osd(osd, msg)

    def pg_activated(self, pg: PG) -> None:
        self._osd.kick_recovery()

    def kick_recovery(self, pg: Optional[PG] = None) -> None:
        self._osd.kick_recovery()

    def objecter_ioctx(self, pool_id: int, bypass_tier: bool = True):
        return self._osd.objecter_ioctx(pool_id, bypass_tier)

    def ensure_pg(self, pgid) -> Optional[PG]:
        """Get-or-create a local PG instance regardless of acting-set
        membership (split children are created on the parent's holders
        even when they are strays there)."""
        return self._osd._ensure_pg(pgid, self._osd.osdmap)

    def forget_pg(self, pgid) -> None:
        """Drop a purged stray PG from the local registry."""
        with self._osd.pg_lock:
            self._osd.pgs.pop(pgid, None)


class OSD(Dispatcher):
    """One object-storage daemon (reference ceph_osd.cc + OSD.cc)."""

    def __init__(self, whoami: int, store: ObjectStore,
                 mon_addr: Tuple[str, int],
                 conf: Optional[Config] = None,
                 addr: Tuple[str, int] = ("127.0.0.1", 0)):
        self.whoami = whoami
        self.store = store
        self.conf = conf or default_config()
        self.log = Dout("osd", f"osd.{whoami} ")
        self.ec_registry = ec_registry.instance()
        self.ec_registry.preload_from_conf(self.conf)
        self.osdmap = OSDMap()
        self.map_lock = make_lock("osd.map")
        self.pgs: Dict[PGid, PG] = {}
        self.pg_lock = make_lock("osd.pgs")
        self.service = OSDService(self)
        self.msgr = self._make_messenger()
        self.my_addr = self.msgr.bind(addr)
        self.msgr.add_dispatcher(self)
        self.monc = MonClient(self.msgr, mon_addr,
                              map_cb=self._on_map_published)
        self._mon_addr = mon_addr
        self._int_client = None          # lazy internal objecter
                                         # (copy_from, cache tiering)
        self._int_client_lock = threading.Lock()
        # sharded op queue (reference op_shardedwq, OSD.h:1287) with
        # mClock-style QoS per shard (reference osd/scheduler/): the
        # client/recovery/scrub classes stop sharing a plain FIFO
        from .scheduler import OpScheduler, qos_from_conf
        self._n_shards = self.conf["osd_op_num_shards"]
        fifo = self.conf["osd_op_queue"] == "fifo"
        qos = {} if fifo else qos_from_conf(self.conf)
        hard = any(lim > 0 for _, _, lim in qos.values())
        self._shard_queues: List[OpScheduler] = [
            OpScheduler(qos, hard_limits=hard, fifo=fifo)
            for _ in range(self._n_shards)]
        # sustained-growth detector for the OP_QUEUE_BACKLOG health
        # check: consecutive ticks the client class got deeper
        self._opq_last_depth = 0
        self._opq_growth_ticks = 0
        self._workers: List[threading.Thread] = []
        self._stop = threading.Event()
        self._recovery_kick = threading.Event()
        # heartbeat state: peer -> last reply time (reference
        # HeartbeatInfo, OSD.h)
        self._hb_last_rx: Dict[int, float] = {}
        self._hb_reported: Dict[int, float] = {}
        self._threads: List[threading.Thread] = []
        # observability (reference l_osd_* counters OSD.cc:9630 +
        # OpTracker dump_historic_ops OSD.cc:2457)
        from ..utils.optracker import OpTracker
        from ..utils.perf import PerfCountersCollection, TYPE_TIME_AVG
        self.perf_coll = PerfCountersCollection()
        self.perf = self.perf_coll.create("osd")
        self.perf.add("op", description="client operations")
        self.perf.add("op_w", description="client writes")
        self.perf.add("op_r", description="client reads")
        self.perf.add("op_in_bytes", description="client bytes written")
        self.perf.add("op_latency", TYPE_TIME_AVG,
                      "client op latency (dequeue to reply)")
        self.perf.add("op_w_latency", TYPE_TIME_AVG,
                      "client write latency")
        self.perf.add("op_r_latency", TYPE_TIME_AVG,
                      "client read latency")
        self.perf.add("subop", description="replica/shard sub-ops")
        self.perf.add("recovery_ops", description="objects recovered")
        self.perf.add("ec_batch_calls",
                      description="batched EC encode device calls")
        self.perf.add("ec_batch_stripes",
                      description="stripes encoded through the batcher")
        self.perf.add("ec_batch_coalesced",
                      description="write ops that shared a device call")
        self.perf.add("ec_dec_batch_calls",
                      description="batched EC decode calls")
        self.perf.add("ec_dec_batch_coalesced",
                      description="decode requests that shared a call")
        self.perf.add("ec_delta_batch_calls",
                      description="batched parity-delta (RMW) device "
                      "calls")
        self.perf.add("ec_delta_batch_coalesced",
                      description="delta requests that shared a call")
        self.perf.add("ec_subwrite_timeouts",
                      description="EC sub-write deadlines expired")
        self.perf.add("ec_subwrite_retries",
                      description="EC sub-writes re-requested from "
                      "laggard shards")
        self.perf.add("ec_subwrite_peer_reports",
                      description="laggard peers reported to the mon")
        # mClock scheduler telemetry (ISSUE 13): per-class queue
        # depth/served/deficit aggregated over this daemon's op-queue
        # shards.  Registered at boot on BOTH backends so the mgr
        # prometheus scrape carries the ceph_op_queue_* families
        # before any traffic; refreshed on every tick and perf dump.
        from ..utils.perf import TYPE_U64
        self.op_queue_perf = self.perf_coll.create("op_queue")
        from .scheduler import DEFAULT_QOS
        for cls_name in DEFAULT_QOS:
            self.op_queue_perf.add(
                f"{cls_name}_queued_now", TYPE_U64,
                f"{cls_name}-class ops queued across shards")
            self.op_queue_perf.add(
                f"{cls_name}_served",
                description=f"{cls_name}-class ops dequeued")
            self.op_queue_perf.add(
                f"{cls_name}_depth_hwm", TYPE_U64,
                f"max {cls_name}-class depth on any one shard")
            self.op_queue_perf.add(
                f"{cls_name}_deficit_now", TYPE_U64,
                f"{cls_name}-class weighted-fair deficit (sum)")
        # process-wide fault injection (utils/faults.py): arm the
        # registry from fault_injection/_seed; idempotent, so an OSD
        # restart mid-run keeps the sites' RNG streams
        from ..utils import faults as faultlib
        faultlib.configure_from(self.conf)
        # per-OSD hashed timer wheel: EC sub-write deadlines, recovery
        # pacing (one thread total; see utils/timer_wheel.py)
        from ..utils.timer_wheel import TimerWheel
        self.timer_wheel = TimerWheel()
        # per-OSD flight recorder: bounded ring of recent routing/
        # batcher/fault events, dumped via dump_flight_recorder and
        # auto-dumped on op timeout / breaker-open / client encode
        # error (utils/flight_recorder.py)
        from ..utils.flight_recorder import FlightRecorder
        self.flight_recorder = FlightRecorder(
            capacity=self.conf["flight_recorder_events"],
            name=f"osd.{whoami}")
        # lock/queue contention telemetry ("contention" subsystem):
        # the PG lock, batcher condition, store mutex and messenger
        # send queues report wait/hold/depth here; stalls over the
        # threshold leave a breadcrumb in the flight recorder
        from ..utils.locks import ContentionStats, TimedLock
        self.contention = ContentionStats(
            perf_coll=self.perf_coll, recorder=self.flight_recorder,
            stall_threshold_s=self.conf["contention_stall_threshold"])
        self.contention.register_queue("msgr_sendq")
        self.msgr.contention = self.contention
        # retrofit the store mutex; a restart on a surviving store
        # finds it already wrapped and just rebinds the sink
        st_lock = getattr(store, "_lock", None)
        if isinstance(st_lock, TimedLock):
            st_lock.bind(self.contention)
        elif st_lock is not None:
            store._lock = TimedLock("store_lock", stats=self.contention,
                                    inner=st_lock)
        # store-transaction ledger (utils/store_ledger.py): every
        # queue_transactions charges its wall to the phase waterfall
        # ("store" perf subsystem, dump_store command); a phase at or
        # over store_phase_stall_ms flight-records a store_stall and
        # rate-limit auto-dumps.  Idempotent across OSD restart on a
        # surviving store — accumulated history stays, the counters
        # rebind into this daemon's collection.
        self.store.attach_observability(
            perf_coll=self.perf_coll, recorder=self.flight_recorder,
            stall_threshold_s=self.conf["store_phase_stall_ms"] / 1e3)
        # cross-daemon hop-ledger accumulators: this OSD's view of
        # sub-op round trips, split by op class so the read/recovery
        # waterfall doesn't smear into the write one ("hops" = write
        # sub-ops, "hops_read" = client-facing shard reads,
        # "hops_recovery" = pushes/pulls + scrub windows; the client
        # owns the end-to-end MOSDOp views)
        from ..utils.hops import HopAccum
        self.hops = HopAccum(perf_coll=self.perf_coll)
        self.hops_read = HopAccum(perf_coll=self.perf_coll,
                                  subsystem="hops_read")
        self.hops_recovery = HopAccum(perf_coll=self.perf_coll,
                                      subsystem="hops_recovery")
        # cross-op TPU stripe coalescer (SURVEY §3.1 batching point)
        from .batcher import EncodeBatcher
        self.encode_batcher = EncodeBatcher(
            self.conf, perf=self.perf, perf_coll=self.perf_coll,
            recorder=self.flight_recorder, contention=self.contention)
        # checksum offload: a deferred-checksum store (BlueStore)
        # folds its apply-batch CRCs through the codec backend's
        # GF-bitmatrix kernel when an accelerator is live; resolved
        # per batch because the batcher only learns its backend on
        # first device dispatch
        if hasattr(self.store, "attach_device_batcher"):
            self.store.attach_device_batcher(
                lambda: getattr(self.encode_batcher,
                                "_last_backend", None))
        # timer-wheel fire lag rides the batcher's ec_device
        # subsystem (one device-machinery surface); tick-scale lag is
        # normal, so only fires a full revolution late (a wedged
        # wheel thread) are flight-recorded
        _dperf = self.encode_batcher.dperf
        _wheel = self.timer_wheel
        _late_s = _wheel.tick_s * _wheel.slots

        def _note_fire_lag(lag, _dp=_dperf, _rec=self.flight_recorder,
                           _late=_late_s):
            if _dp is not None:
                _dp.hinc("timer_fire_lag_us", lag * 1e6)
            if lag > _late:
                _rec.note("timer", event="late_fire",
                          lag_ms=round(lag * 1e3, 3))
        self.timer_wheel.on_fire_lag = _note_fire_lag
        self.op_tracker = OpTracker(
            history_size=self.conf["osd_op_history_size"],
            history_duration=self.conf["osd_op_history_duration"],
            slow_op_warn_threshold=self.conf["osd_op_complaint_time"])
        # per-op critical-path analysis on every retired op: stage
        # budget + bounding-stage census, exported as the "critpath"
        # perf subsystem and the dump_critical_path command
        from ..utils.critpath import CriticalPathAccum
        self.critpath = CriticalPathAccum(perf_coll=self.perf_coll)
        # per-op-class SLO accounting (mgr/slo.py): client classes
        # feed from op retirement, recovery/scrub from their own
        # completion paths; both observers are chained post-reply and
        # must not raise
        from ..mgr.slo import SLOEngine
        self.slo = SLOEngine(conf=self.conf, perf_coll=self.perf_coll)

        def _on_retire(op, _cp=self.critpath.observe,
                       _slo=self.slo.observe_op):
            _cp(op)
            _slo(op)
        self.op_tracker.on_retire = _on_retire
        # decode device faults burn recovery-class budget even though
        # the CPU-twin fallback keeps the op itself successful
        self.encode_batcher.on_decode_fault = \
            lambda: self.slo.note_error("recovery")
        # closed-loop per-OSD tuner (utils/tuner.py, ROADMAP item 5):
        # a guarded hill-climb over the Option-marked tunable batcher
        # knobs, fed by the telemetry ladder (overlap engine, staging
        # stalls, contention stalls, SLO burn) from _maybe_tuner_tick.
        # Built even while osd_tuner_enable is off so the "tuner" perf
        # subsystem and dump_tuner exist on every daemon.
        from ..utils.tuner import Tuner, knobs_from_config
        tuner_knobs = []
        if hasattr(self.conf, "tunables"):
            tuner_knobs = knobs_from_config(
                self.conf,
                # seeds give the 0-means-auto knobs a real first step
                {"ec_tpu_queue_window_max_us": {"seed": 20000},
                 "ec_tpu_inflight_groups": {},
                 "ec_tpu_staging_depth": {},
                 "osd_ec_pipeline_segment_bytes": {"seed": 1 << 20}},
                pinned=self.conf["osd_tuner_pin"])
        self.tuner = Tuner(
            f"osd.{whoami}", tuner_knobs,
            hysteresis=self.conf["osd_tuner_hysteresis"],
            cooldown_ticks=self.conf["osd_tuner_cooldown_ticks"],
            blacklist_ticks=self.conf["osd_tuner_blacklist_ticks"],
            recorder=self.flight_recorder,
            perf_coll=self.perf_coll)
        self._tuner_ticks = 0
        self._tuner_last = (None, 0)     # (monotonic, reqs) objective
        self._tuner_last_overlap = None  # collapse-guard memory
        # live mClock retune seam: the mgr tuner module (or an
        # operator `config set`) changes an osd_mclock_scheduler_*
        # option; the central config rides the next map epoch into
        # this daemon's conf, whose observer pushes the new triples
        # into every RUNNING shard queue (OpScheduler.set_qos) — no
        # restart, no queue drain
        if hasattr(self.conf, "add_observer"):
            def _remclock(_name, _val):
                self._reapply_mclock()
            for _cls in ("client", "recovery", "scrub", "peering"):
                for _part in ("res", "wgt", "lim"):
                    self.conf.add_observer(
                        f"osd_mclock_scheduler_{_cls}_{_part}",
                        _remclock)
        from ..utils.tracer import Tracer
        self.tracer = Tracer(f"osd.{whoami}",
                             enabled=self.conf["osd_tracing"],
                             keep=self.conf["trace_keep_spans"])
        # optional unix-socket command surface (reference AdminSocket,
        # common/admin_socket.cc; the MCommand path stays primary)
        self.admin_socket = None
        sock_tmpl = self.conf["admin_socket"]
        if sock_tmpl:
            from string import Template
            from ..utils.admin_socket import AdminSocket
            path = Template(sock_tmpl).safe_substitute(
                name=f"osd.{whoami}")
            self.admin_socket = AdminSocket(path)
            for prefix in ("perf dump", "dump_traces",
                           "dump_historic_ops",
                           "dump_historic_slow_ops",
                           "dump_blocked_ops", "dump_ops_in_flight",
                           "dump_slow_ops", "dump_flight_recorder",
                           "dump_critical_path", "dump_hops",
                           "dump_slo", "dump_trace",
                           "dump_profile", "dump_device",
                           "dump_op_queue", "dump_tuner",
                           "dump_store",
                           "dump_health", "status",
                           "config get", "config set"):
                self.admin_socket.register(
                    prefix, self._admin_socket_hook)

    def _make_messenger(self) -> Messenger:
        """Messenger factory — the crimson OSD substitutes its
        reactor-driven messenger here."""
        return Messenger(f"osd.{self.whoami}", conf=self.conf)

    # -- sampling profiler lifecycle (utils/sampler.py) ----------------
    # refcounted: the process-wide sampler thread runs while any
    # daemon holds a reference and stops with the last release, so
    # cluster teardown leaves no sampler thread behind
    _sampler_held = False

    def _sampler_retain(self) -> None:
        hz = self.conf["osd_sampler_hz"]
        if hz <= 0 or self._sampler_held:
            return
        from ..utils.sampler import global_sampler
        global_sampler(hz=hz).retain()
        self._sampler_held = True

    def _sampler_release(self) -> None:
        if not self._sampler_held:
            return
        self._sampler_held = False
        from ..utils.sampler import global_sampler
        global_sampler().release()

    # ------------------------------------------------------------------
    # lifecycle (reference OSD::init)
    # ------------------------------------------------------------------
    def start(self) -> None:
        self._sampler_retain()
        self.msgr.start()
        for shard in range(self._n_shards):
            for t in range(self.conf["osd_op_num_threads_per_shard"]):
                w = threading.Thread(
                    target=self._op_worker, args=(shard,),
                    name=f"osd{self.whoami}-op-{shard}.{t}", daemon=True)
                w.start()
                self._workers.append(w)
        for target, name in ((self._recovery_loop, "recovery"),
                             (self._heartbeat_loop, "hb"),
                             (self._tick_loop, "tick")):
            t = threading.Thread(target=target,
                                 name=f"osd{self.whoami}-{name}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        self.monc.subscribe_osdmap()
        self.monc.send_boot(self.whoami, self.my_addr)
        if self.admin_socket is not None:
            self.admin_socket.start()
        self.log.dout(1, f"booted, addr {self.my_addr}")

    def shutdown(self) -> None:
        self._stop.set()
        if self.admin_socket is not None:
            self.admin_socket.stop()
        self.encode_batcher.stop(
            drain=self.conf["osd_batcher_drain_timeout"])
        self.timer_wheel.stop()
        self._recovery_kick.set()
        for q in self._shard_queues:
            q.close()
        if self._int_client is not None:
            try:
                self._int_client.shutdown()
            except Exception:
                pass
        self.msgr.shutdown()
        for t in self._workers + self._threads:
            t.join(timeout=5)
        self._sampler_release()
        try:
            self.store.umount()
        except Exception:
            pass

    # ------------------------------------------------------------------
    # map handling (reference handle_osd_map :7753)
    # ------------------------------------------------------------------
    def _on_map_published(self, wire: dict) -> None:
        newmap = OSDMap.from_wire_dict(wire)
        with self.map_lock:
            if newmap.epoch <= self.osdmap.epoch:
                return
            self.osdmap = newmap
        # central config overrides ride the map (reference
        # ConfigMonitor -> MConfig): apply changes, REVERT removals,
        # observers fire either way
        from ..utils.config import apply_cluster_config_overrides
        self._applied_overrides = apply_cluster_config_overrides(
            self.conf, newmap.cluster_config,
            getattr(self, "_applied_overrides", {}))
        self._advance_pgs(newmap)
        # if the monitor thinks we're down (e.g. spurious failure
        # reports) but we're alive, re-boot (reference OSD re-sends
        # MOSDBoot when marked down while up)
        info = newmap.osds.get(self.whoami)
        if (info is None or not info.up) and not self._stop.is_set():
            self.monc.send_boot(self.whoami, self.my_addr)

    def _maybe_merge_collections(self, osdmap: OSDMap) -> None:
        """PG merge — the inverse of maybe_split (reference OSD
        merge tracking, osd/OSD.cc:329-422 + PG::merge_from): when a
        pool's pg_num SHRANK, collections whose seed is at or past the
        new pg_num fold their objects back into the split parent
        (pg_split_source).  Deterministic on every replica — all
        holders of a child move the same objects into the same parent
        collections (sorted order, so multi-child merges append log
        entries identically everywhere) — and the parent adopts the
        child's log rebased onto its own; peering catches up holders
        that had no child data.  EC chunks land at the holder's CHILD
        shard position, which may differ from its parent position:
        those serve as mispositioned recovery sources
        (extra_recovery_sources) while log recovery reconstructs the
        proper placement.  Runs on the STORE, not the PG objects, so
        merges pending from shrink-while-down complete on restart."""
        # cheap gate: scan the store only when some pool's pg_num
        # actually DECREASED since the last map we processed (or on
        # the first map after boot, covering shrink-while-down)
        prev = getattr(self, "_prev_pool_pgnums", None)
        cur = {pid: p.pg_num for pid, p in osdmap.pools.items()}
        self._prev_pool_pgnums = cur
        if prev is not None and all(
                cur[pid] >= prev.get(pid, 0) for pid in cur):
            return
        import re as _re

        from ..store.objectstore import GHObject, Transaction
        from .osdmap import pg_split_source
        from .pg import PGMETA_OID
        from .pglog import MissingSet, PGLog
        try:
            colls = sorted(self.store.list_collections())
        except Exception:
            return
        groups: Dict[Tuple[int, int], List[Tuple[str, int]]] = {}
        for coll in colls:
            m = _re.fullmatch(r"(\d+)\.([0-9a-f]+)(?:s(\d+))?", coll)
            if not m:
                continue
            pool_id = int(m.group(1))
            seed = int(m.group(2), 16)
            shard = int(m.group(3)) if m.group(3) is not None else -1
            pool = osdmap.pools.get(pool_id)
            if pool is None or seed < pool.pg_num:
                continue                 # pool gone (purge handles) or
                                         # still a live PG
            groups.setdefault((pool_id, seed), []).append((coll,
                                                           shard))
        import json as _json
        for (pool_id, seed) in sorted(groups):
            pool = osdmap.pools[pool_id]
            tseed = pg_split_source(seed, pool.pg_num)
            base = f"{pool_id}.{tseed:x}"
            _, _, p_acting, _ = osdmap.pg_to_up_acting_osds(
                PGid(pool_id, tseed))
            if self.whoami not in [o for o in p_acting
                                   if o is not None] \
                    and not pool.is_erasure():
                # replicated pool, and we hold child data but are NOT
                # a parent acting member: the merge gate required a
                # fully CLEAN cluster, so the acting set holds
                # everything current — our copy may even be a STALE
                # stray left by churn.  Folding it could rebase stale
                # history into the parent; drop it instead (the purge
                # we would get anyway, just earlier).  EC pools take
                # the fold path below even when non-acting: each
                # holder owns ONE chunk position, so the parent acting
                # set alone cannot reconstruct the merged objects —
                # the holder must keep serving its chunk as a
                # shard-qualified stray source until recovery lands
                # (adopt_merge's stray branch; split machinery in
                # reverse).  Quiesce like the fold path: a racing
                # client op must bounce, not ack into a collection
                # being removed.
                with self.pg_lock:
                    dropped = self.pgs.pop(PGid(pool_id, seed), None)
                import contextlib as _ctx
                guard = dropped.lock if dropped is not None \
                    else _ctx.nullcontext()
                with guard:
                    if dropped is not None:
                        dropped._merged_away = True
                    txn = Transaction()
                    for coll, _shard in sorted(
                            groups[(pool_id, seed)]):
                        txn.remove_collection(coll)
                    try:
                        self.store.queue_transactions([txn],
                                                      op="pg_merge")
                    except Exception:
                        pass
                self.log.dout(1, f"dropped non-acting child copy "
                              f"{pool_id}.{seed:x} at merge")
                continue
            # the in-memory child PG dies first; late ops bounce to
            # the client, which re-targets the parent off the new map.
            # The object snapshot + move txn run UNDER the child's
            # lock with the merged-away flag set, so no write can
            # commit between the snapshot and the collection removal
            # (an acked write must never be silently dropped)
            with self.pg_lock:
                child = self.pgs.pop(PGid(pool_id, seed), None)
            import contextlib
            child_guard = child.lock if child is not None \
                else contextlib.nullcontext()
            child_log = None
            child_missing = None
            merged_locs: Dict[str, int] = {}   # oid -> local shard
            ok = True
            with child_guard:
                if child is not None:
                    child._merged_away = True
                txn = Transaction()
                for coll, shard in sorted(groups[(pool_id, seed)]):
                    tcoll = base if shard < 0 else f"{base}s{shard}"
                    if child_log is None:
                        try:
                            omap = self.store.omap_get(
                                coll, GHObject(PGMETA_OID, shard))
                            raw = omap.get("info")
                            if raw:
                                child_log = PGLog.decode(raw)
                            raw = omap.get("missing")
                            if raw:
                                child_missing = MissingSet.from_dict(
                                    _json.loads(raw.decode()))
                        except Exception:
                            pass
                    if not self.store.collection_exists(tcoll):
                        txn.create_collection(tcoll)
                    for obj in self.store.collection_list(coll):
                        if obj.oid == PGMETA_OID:
                            continue
                        merged_locs.setdefault(obj.oid, shard)
                        txn.collection_move_rename(coll, obj, tcoll,
                                                   obj)
                    txn.remove_collection(coll)
                try:
                    self.store.queue_transactions([txn],
                                                  op="pg_merge")
                except Exception as e:
                    self.log.dout(1, f"merge of {pool_id}.{seed:x} -> "
                                  f"{base} failed: {e!r}; retrying on "
                                  f"the next map")
                    ok = False
            if not ok:
                continue
            parent = self._ensure_pg(PGid(pool_id, tseed), osdmap)
            if parent is not None:
                parent.adopt_merge(child_log, child_missing,
                                   pool.pg_num, merged_locs,
                                   merge_epoch=pool.pg_num_epoch)
            self.log.dout(1, f"merged pg {pool_id}.{seed:x} -> {base}")

    def _advance_pgs(self, osdmap: OSDMap) -> None:
        """Instantiate PGs mapped here and advance every hosted PG
        (reference consume_map / handle_pg_create).  Splits run before
        interval handling so children hold their objects before their
        peering starts (reference OSD::advance_pg split-then-peer
        ordering, osd/OSD.cc:8926)."""
        self._maybe_merge_collections(osdmap)
        for pool_id in list(osdmap.pools):
            for pgid in osdmap.pgs_for_pool(pool_id):
                _, _, acting, _ = osdmap.pg_to_up_acting_osds(pgid)
                if self.whoami in [o for o in acting if o is not None]:
                    self._ensure_pg(pgid, osdmap)
        with self.pg_lock:
            pgs = list(self.pgs.values())
        for pg in pgs:
            try:
                pg.maybe_split(osdmap)
            except Exception as e:   # one sick PG must not wedge the
                self.log.dout(1, f"split {pg.pgid} failed: {e!r}")
        with self.pg_lock:
            pgs = list(self.pgs.values())  # splits may add children
        for pg in pgs:
            try:
                pg.advance_map(osdmap)
            except Exception as e:   # map pump (all PGs starve if one
                self.log.dout(1,     # advance raises)
                              f"advance {pg.pgid} failed: {e!r}")

    def _ensure_pg(self, pgid: PGid, osdmap: OSDMap) -> Optional[PG]:
        with self.pg_lock:
            pg = self.pgs.get(pgid)
            if pg is not None:
                return pg
            pool = osdmap.get_pool(pgid.pool)
            if pool is None:
                return None
            pg = PG(self.service, pgid, pool)
            self._pg_created(pg)
            self.pgs[pgid] = pg
            return pg

    def _pg_created(self, pg: PG) -> None:
        """Backend hook on PG instantiation; the crimson OSD stamps
        the owning reactor shard here."""

    def _lookup_pg(self, pgid: PGid, create: bool = True
                   ) -> Optional[PG]:
        with self.pg_lock:
            pg = self.pgs.get(pgid)
        if pg is not None:
            return pg
        if not create:
            return None
        # message raced our map: create if the current map places this
        # PG here (reference wait-for-map + create semantics)
        with self.map_lock:
            osdmap = self.osdmap
        if pgid.pool not in osdmap.pools:
            return None
        _, _, acting, _ = osdmap.pg_to_up_acting_osds(pgid)
        if self.whoami not in [o for o in acting if o is not None]:
            return None
        pg = self._ensure_pg(pgid, osdmap)
        if pg is not None:
            pg.advance_map(osdmap)
        return pg

    # ------------------------------------------------------------------
    # dispatch (reference ms_fast_dispatch :7008)
    # ------------------------------------------------------------------
    def ms_dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, MOSDOp):
            msg.stamp_hop("dispatch_queued")
            self._enqueue_op(conn, msg)
            return True
        if isinstance(msg, _BACKEND_MSGS):
            self.perf.inc("subop")
            msg.stamp_hop("dispatch_queued")
            pgid = PGid.parse(msg.pgid)
            pg = self._lookup_pg(pgid)
            if pg is not None:
                with pg.lock:
                    msg.stamp_hop("pg_locked")
                    if pg.pool.is_erasure() and pg.own_shard < 0:
                        # map race: we are not (yet) in this PG's
                        # acting set, so there is no shard collection
                        # to apply against — park until advance_map
                        # assigns the shard
                        pg.waiting_for_shard.append(msg)
                    else:
                        pg.backend.handle_message(msg)
            return True
        if isinstance(msg, MCommand):
            self._handle_command(conn, msg)
            return True
        if isinstance(msg, _PEERING_MSGS):
            pgid = PGid.parse(msg.pgid)
            pg = self._lookup_pg(pgid)
            if pg is None:
                return True
            if isinstance(msg, MOSDPGQuery):
                pg.handle_pg_query(msg)
            elif isinstance(msg, MOSDPGNotify):
                pg.handle_pg_notify(msg)
            else:
                pg.handle_pg_log(msg)
            return True
        if isinstance(msg, MOSDPGRemove):
            pg = self._lookup_pg(PGid.parse(msg.pgid), create=False)
            if pg is not None:
                pg.handle_pg_remove(msg)
            return True
        if isinstance(msg, (MOSDScrub, MRepScrub, MRepScrubMap)):
            pg = self._lookup_pg(PGid.parse(msg.pgid))
            if pg is not None:
                with pg.lock:
                    if isinstance(msg, MOSDScrub):
                        pg.scrubber.start(msg.deep, msg.repair)
                    elif isinstance(msg, MRepScrub):
                        pg.scrubber.handle_rep_scrub(msg)
                    else:
                        pg.scrubber.handle_rep_scrub_map(msg)
            return True
        if isinstance(msg, MOSDPing):
            self._handle_ping(conn, msg)
            return True
        return False        # MOSDMap etc. fall through to the MonClient

    # -- sharded op queue (reference enqueue_op/dequeue_op) -------------
    def _enqueue_op(self, conn: Connection, msg: MOSDOp) -> None:
        pgid = PGid(msg.pool, msg.pgid_seed)
        # track from ENQUEUE so queue-wait shows in the event timeline
        # (reference OpTracker starts at op receipt, not dequeue)
        msg.tracked = self.op_tracker.create(
            f"osd_op({msg.client}.{msg.tid} {pgid} {msg.oid} "
            f"{'+'.join(op.op for op in msg.ops)})")
        # class tag consumed by SLOEngine.observe_op at retirement
        msg.tracked.slo_class = "client_write" \
            if any(PG._op_is_write(op) for op in msg.ops) \
            else "client_read"
        msg.tracked.mark_event("queued_for_pg")
        msg.stamp_hop("pg_queued")
        shard = hash(pgid) % self._n_shards
        self._shard_queues[shard].enqueue("client", (conn, msg))

    def _shard_of_pg(self, pg: PG) -> int:
        return hash(pg.pgid) % self._n_shards

    def queue_recovery_item(self, pg: PG) -> None:
        """One recovery scheduling unit for this PG (reference
        PGRecovery OpSchedulerItem); deduped so a PG holds at most one
        queued item."""
        with pg.lock:
            if getattr(pg, "_recovery_queued", False):
                return
            pg._recovery_queued = True
        self._shard_queues[self._shard_of_pg(pg)].enqueue(
            "recovery", pg)

    def _tuned(self, base: str):
        """hdd/ssd-tuned option resolution (reference dual-default
        options): an EXPLICITLY SET base value wins — including an
        explicit 0 (e.g. osd_recovery_sleep=0 to disable pacing) —
        otherwise the store medium picks the _hdd/_ssd variant."""
        v = self.conf[base]
        if v or self.conf.is_overridden(base):
            return v
        medium = getattr(self.store, "medium", "ssd")
        return self.conf[f"{base}_{medium}"]

    def _run_recovery_item(self, pg: PG) -> None:
        with pg.lock:
            pg._recovery_queued = False
        try:
            budget = min(self._tuned("osd_recovery_max_active"),
                         max(1, self.conf[
                             "osd_recovery_max_single_start"]))
            started = pg.start_recovery_ops(budget)
        except Exception:
            import traceback
            traceback.print_exc()
            started = 0
        if started:
            self.perf.inc("recovery_ops", started)
            with pg.lock:
                more = pg.is_primary() and pg.num_missing() > 0
            if more:
                sleep = self._tuned("osd_recovery_sleep")
                if sleep:
                    # pace WITHOUT blocking the shard worker (a sleep
                    # here would stall queued client ops): defer the
                    # requeue instead
                    self.timer_wheel.call_later(
                        sleep, lambda pg=pg: self.queue_recovery_item(pg))
                else:
                    self.queue_recovery_item(pg)

    def _op_worker(self, shard: int) -> None:
        q = self._shard_queues[shard]
        while True:
            out = q.dequeue()
            if out is None:
                return
            self._run_sched_item(*out)

    def _run_sched_item(self, cls: str, item) -> None:
        """Run one scheduled op-queue item.  Shared by the classic
        shard workers and the crimson per-shard reactor drain."""
        if cls == "recovery":
            self._run_recovery_item(item)
            return
        if cls == "scrub":
            try:
                item()
            except Exception:
                import traceback
                traceback.print_exc()
            return
        conn, msg = item
        if getattr(msg, "_crossed_shard", False):
            # crimson: the op was enqueued from a foreign reactor —
            # charge the hop now that the owner shard picked it up
            msg._crossed_shard = False
            msg.stamp_hop("xshard_handoff")
        self._run_client_op(conn, msg)

    # -- op-queue telemetry (ISSUE 13) ---------------------------------
    def _op_queue_stats(self) -> Dict[str, Dict[str, float]]:
        """Aggregate per-class scheduler stats over every shard."""
        agg: Dict[str, Dict[str, float]] = {}
        for q in self._shard_queues:
            for cls, row in q.stats().items():
                a = agg.setdefault(cls, {"queued": 0, "served": 0,
                                         "deficit": 0.0,
                                         "depth_hwm": 0})
                a["queued"] += row["queued"]
                a["served"] += row["served"]
                a["deficit"] += row["deficit"]
                a["depth_hwm"] = max(a["depth_hwm"], row["depth_hwm"])
        return agg

    def _refresh_op_queue_perf(self) -> Dict[str, Dict[str, float]]:
        agg = self._op_queue_stats()
        perf = self.op_queue_perf
        for cls, row in agg.items():
            try:
                perf.set(f"{cls}_queued_now", row["queued"])
                perf.set(f"{cls}_served", row["served"])
                perf.set(f"{cls}_depth_hwm", row["depth_hwm"])
                perf.set(f"{cls}_deficit_now",
                         round(row["deficit"], 4))
            except KeyError:
                pass            # ad-hoc class outside DEFAULT_QOS
        # growth streak for OP_QUEUE_BACKLOG: consecutive refreshes
        # where the client class got strictly deeper
        depth = int((agg.get("client") or {}).get("queued", 0))
        if depth > self._opq_last_depth:
            self._opq_growth_ticks += 1
        else:
            self._opq_growth_ticks = 0
        self._opq_last_depth = depth
        return agg

    def _run_client_op(self, conn: Connection, msg: MOSDOp) -> None:
        """Dequeued client op: span + perf + PG dispatch.  Shared by
        the classic shard workers and the crimson reactor (which runs
        it as a continuation instead of on a pool thread)."""
        pgid = PGid(msg.pool, msg.pgid_seed)
        tracked = getattr(msg, "tracked", None)
        pg = self._lookup_pg(pgid)
        if pg is None:
            # not our PG: tell the client to refresh its map
            from ..msg.messages import MOSDOpReply
            conn.send_message(MOSDOpReply(
                tid=msg.tid, result=-108, epoch=self.osdmap.epoch))
            if tracked is not None:
                tracked.finish()
            return
        is_write = any(PG._op_is_write(op) for op in msg.ops)
        span = self.tracer.start(
            "osd_op", msg.trace_id,
            getattr(msg, "parent_span_id", 0)) \
            if msg.trace_id else None
        if span is not None:
            span.tag("pg", str(pgid)).tag("oid", msg.oid) \
                .tag("write", is_write)
            # child sub-ops (EC shard writes) parent under us
            msg.osd_span_id = span.span_id
        if tracked is not None:
            tracked.mark_event("reached_pg")
        t0 = time.monotonic()
        self.perf.inc("op")
        self.perf.inc("op_w" if is_write else "op_r")
        if is_write:
            self.perf.inc("op_in_bytes",
                          sum(len(op.data or b"") for op in msg.ops))
        try:
            pg.do_request(msg, conn)
        except Exception:
            import traceback
            traceback.print_exc()
        finally:
            # latency = queue dispatch time; commit waits are async
            # (reference splits l_osd_op_*_lat similarly)
            dt = time.monotonic() - t0
            self.perf.tinc("op_latency", dt)
            self.perf.tinc("op_w_latency" if is_write
                           else "op_r_latency", dt)
            # async writes hand the tracked op to the commit
            # pipeline (PG._reply finishes it); parked ops (latest
            # event "waiting ...") stay in flight for
            # dump_blocked_ops until requeued.  finish() is
            # idempotent, so a synchronous reply that already
            # retired the op is a no-op here.
            if tracked is not None and \
                    not getattr(msg, "_tracked_async", False) and \
                    not (tracked.events and
                         tracked.events[-1][1].startswith(
                             "waiting")):
                tracked.finish()
            if span is not None:
                span.finish()

    # ------------------------------------------------------------------
    # daemon-direct commands (reference 'ceph tell osd.N', MCommand;
    # command set mirrors the admin socket's, common/admin_socket.cc)
    # ------------------------------------------------------------------
    def _exec_command(self, cmd: dict) -> Tuple[int, str, dict]:
        """Shared command table behind both MCommand ('ceph tell') and
        the unix admin socket ('ceph daemon') — one implementation, two
        transports (reference common/admin_socket.cc)."""
        prefix = cmd.get("prefix", "")
        retcode, rs, out = 0, "", {}
        try:
            if prefix == "perf dump":
                self._refresh_op_queue_perf()
                out = self.perf_coll.perf_dump()
                # fault-injection trip counters ride the same dump so
                # admin socket / tell / mgr prometheus all see them
                from ..utils import faults as faultlib
                counters = faultlib.registry().counters()
                if counters:
                    out["faults"] = counters
            elif prefix == "dump_traces":
                out = {"spans": self.tracer.dump()}
            elif prefix == "dump_historic_ops":
                out = {"ops": self.op_tracker.dump_historic_ops()}
            elif prefix == "dump_historic_slow_ops":
                out = {"ops":
                       self.op_tracker.dump_historic_slow_ops()}
            elif prefix == "dump_blocked_ops":
                out = {"ops": self.op_tracker.dump_blocked_ops()}
            elif prefix == "dump_ops_in_flight":
                out = {"ops": self.op_tracker.dump_ops_in_flight()}
            elif prefix == "dump_slow_ops":
                out = {"ops": self.op_tracker.slow_ops()}
            elif prefix == "dump_flight_recorder":
                out = self.flight_recorder.dump_state()
            elif prefix == "dump_critical_path":
                out = self.critpath.dump()
            elif prefix == "dump_hops":
                # write view at top level (back-compat), read/recovery
                # class views nested
                out = self.hops.dump()
                out["read"] = self.hops_read.dump()
                out["recovery"] = self.hops_recovery.dump()
            elif prefix == "dump_slo":
                out = self.slo.dump()
            elif prefix == "dump_trace":
                out = self._trace_bundle()
            elif prefix == "dump_profile":
                from ..utils.sampler import global_sampler
                s = global_sampler()
                out = {"samples": s.samples,
                       "hz": s.hz,
                       "running": s.running,
                       "folded": s.dump_folded(
                           prefix=f"osd{self.whoami}-"),
                       "self_time": s.top_self_time(
                           prefix=f"osd{self.whoami}-", n=10)}
            elif prefix == "dump_device":
                out = self.encode_batcher.device_dump()
            elif prefix == "dump_op_queue":
                out = {"classes": self._refresh_op_queue_perf(),
                       "shards": [q.stats()
                                  for q in self._shard_queues],
                       "growth_ticks": self._opq_growth_ticks}
            elif prefix == "dump_tuner":
                out = self.tuner.dump()
                out["enabled"] = bool(
                    self.conf["osd_tuner_enable"])
            elif prefix == "dump_store":
                out = self.store.dump_store()
            elif prefix == "dump_health":
                out = self._health_dump()
            elif prefix == "status":
                with self.pg_lock:
                    n_pgs = len(self.pgs)
                out = {"osd": self.whoami, "num_pgs": n_pgs,
                       "osdmap_epoch": self.osdmap.epoch,
                       "state": "active"}
            elif prefix == "config get":
                out = {"value": self.conf.get(cmd["name"])}
            elif prefix == "config set":
                self.conf.set(cmd["name"], cmd["value"])
            else:
                retcode, rs = -22, f"unknown command {prefix!r}"
        except Exception as e:
            retcode, rs = -22, str(e)
        return retcode, rs, out

    def _health_dump(self) -> dict:
        """``dump_health``: this daemon's view of the named cluster
        health checks (mgr/health.py); bench merges every daemon's
        view into the one-look HEALTH_* line."""
        from ..mgr import health as healthlib
        slow = blocked = 0
        try:
            slow = len(self.op_tracker.slow_ops())
            blocked = len(self.op_tracker.dump_blocked_ops())
        except Exception:
            pass
        down = [o for o, info in self.osdmap.osds.items()
                if not info.up]
        with self.pg_lock:
            total_pgs = len(self.pgs)
            degraded = sum(1 for pg in self.pgs.values()
                           if pg.state != STATE_ACTIVE)
        oq = self._op_queue_stats().get("client") or {}
        checks = healthlib.checks_from_signals(
            breaker_open=getattr(self.encode_batcher,
                                 "_breaker_open", False),
            slo=self.slo.dump(),
            slow_ops=slow, blocked_ops=blocked,
            down_osds=down,
            degraded_pgs=degraded, total_pgs=total_pgs,
            op_queue={"client_queued": int(oq.get("queued", 0)),
                      "client_growth_ticks": self._opq_growth_ticks},
            store=self.store.store_stall_signals())
        out = healthlib.summarize(checks)
        out["daemon"] = f"osd.{self.whoami}"
        return out

    def _trace_bundle(self) -> dict:
        """Raw material for tools/trace_export.py (one bundle per
        daemon, merged into a single Perfetto trace): recent hop
        ledgers by op class, optracker stage timelines, flight-
        recorder events, per-shard reactor utilization samples
        (crimson; classic OSDs report none), and the sampler's folded
        stacks for this daemon."""
        reactors = []
        for r in getattr(self, "reactors", []) or []:
            reactors.append({"shard": r.shard,
                             "ticks": r.ticks,
                             "busy_s": r.busy_s,
                             "loop_lag_s": r.loop_lag_s,
                             "util": r.util_dump()})
        folded = {}
        try:
            from ..utils.sampler import global_sampler
            folded = global_sampler().dump_folded(
                prefix=f"osd{self.whoami}-")
        except Exception:
            pass
        return {
            "daemon": f"osd.{self.whoami}",
            "ledgers": {"write": self.hops.recent(),
                        "read": self.hops_read.recent(),
                        "recovery": self.hops_recovery.recent()},
            "ops": (self.op_tracker.dump_historic_ops()
                    + self.op_tracker.dump_ops_in_flight()),
            "flight": self.flight_recorder.dump_state(),
            "reactors": reactors,
            "device": self.encode_batcher.device_trace_block(),
            "store": {"ledgers":
                      self.store._store_accum().recent()},
            "folded": folded,
        }

    def _handle_command(self, conn: Connection, msg: MCommand) -> None:
        retcode, rs, out = self._exec_command(msg.cmd)
        conn.send_message(MCommandReply(tid=msg.tid, retcode=retcode,
                                        rs=rs, out=out))

    def _admin_socket_hook(self, cmd: dict):
        retcode, rs, out = self._exec_command(cmd)
        if retcode != 0:
            raise RuntimeError(rs or f"error {retcode}")
        return out

    # ------------------------------------------------------------------
    # peer messaging
    # ------------------------------------------------------------------
    def send_osd(self, osd: int, msg) -> None:
        if osd == self.whoami:
            # local delivery loops through dispatch (the reference
            # short-circuits local sub-ops similarly)
            self.ms_dispatch(None, msg)
            return
        with self.map_lock:
            addr = self.osdmap.get_addr(osd)
        if addr is None:
            self.log.dout(10, f"no addr for osd.{osd}, dropping "
                          f"{type(msg).__name__}")
            return
        self.msgr.connect_to(addr, lossless=True,
                             peer_name=f"osd.{osd}").send_message(msg)

    def objecter_ioctx(self, pool_id: int, bypass_tier: bool = True):
        """IoCtx on the OSD's own internal client (the reference
        OSD's objecter, used by copy-from and cache tiering —
        reference ceph_osd.cc objecter messenger + PrimaryLogPG
        do_copy_from).  ``bypass_tier``: internal promote/flush IO
        must address the named pool DIRECTLY (reference
        CEPH_OSD_FLAG_IGNORE_OVERLAY), or a tiered base pool's
        redirect would bounce the promote right back into the cache
        that issued it; a tiered copy_from's SOURCE fetch instead
        wants the overlay (the source may live only in the base after
        an evict — the read promotes it back)."""
        with self.map_lock:
            pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            raise KeyError(f"no pool {pool_id}")
        with self._int_client_lock:
            if self._int_client is None:
                from ..client.rados import Rados
                self._int_client = Rados(self._mon_addr,
                                         conf=self.conf).connect()
        io = self._int_client.open_ioctx(pool.name)
        io._bypass_tier = bypass_tier
        return io

    # ------------------------------------------------------------------
    # timers + laggard reporting (EC sub-write deadlines)
    # ------------------------------------------------------------------
    def _call_later(self, delay: float, fn):
        """One-shot cancellable timer on the per-OSD hashed timer
        wheel (utils/timer_wheel.py): O(1) arm/cancel on a single
        daemon thread instead of one thread per timer — the EC fanout
        arms k+m of these per segment.  CrimsonOSD shares the same
        wheel but marshals the fire onto its reactor so deadline
        continuations keep running on the reactor thread."""
        return self.timer_wheel.call_later(delay, fn)

    def report_laggard(self, osd: int, elapsed: float) -> None:
        """A peer sat on an EC sub-write past two deadlines: report it
        to the monitor exactly like a missed heartbeat (reference
        MOSDFailure).  Enough distinct reporters mark it down, the map
        change re-peers the PG and clients resend."""
        self.log.dout(1, f"osd.{osd} laggard on EC sub-write "
                      f"({elapsed * 1000:.0f}ms), reporting")
        try:
            self.monc.report_failure(osd, self.whoami, elapsed,
                                     self.osdmap.epoch)
        except Exception:
            pass

    # ------------------------------------------------------------------
    # heartbeats (reference OSD.cc:5079-5632)
    # ------------------------------------------------------------------
    def _hb_peers(self) -> List[int]:
        """Up peers to ping.  Large clusters ping a ring neighborhood
        of at least osd_heartbeat_min_peers instead of everyone
        (reference maybe_update_heartbeat_peers, OSD.cc:5079 — crush-
        adjacent plus padding to the minimum); every OSD still has
        enough watchers for the monitor's reporter quorum."""
        with self.map_lock:
            up = sorted(o for o, info in self.osdmap.osds.items()
                        if info.up and o != self.whoami)
        want = self.conf["osd_heartbeat_min_peers"]
        if len(up) <= want:
            return up
        # ring neighborhood centered on our id: deterministic, and
        # the union over all OSDs covers every peer both ways
        import bisect
        at = bisect.bisect_left(up, self.whoami)
        half = (want + 1) // 2
        sel = {up[(at + i) % len(up)] for i in range(1, half + 1)}
        sel |= {up[(at - i) % len(up)] for i in range(1, half + 1)}
        return sorted(sel)

    def _handle_ping(self, conn: Connection, msg: MOSDPing) -> None:
        if msg.op == MOSDPing.PING:
            self.send_osd(msg.from_osd, MOSDPing(
                op=MOSDPing.PING_REPLY, from_osd=self.whoami,
                epoch=self.osdmap.epoch, stamp=msg.stamp))
        else:
            self._hb_last_rx[msg.from_osd] = time.monotonic()

    def _heartbeat_loop(self) -> None:
        interval = self.conf["osd_heartbeat_interval"]
        while not self._stop.wait(interval):
            self._heartbeat_once()

    def _heartbeat_once(self) -> None:
        """One heartbeat round: ping peers, report the silent ones.
        Shared by the classic heartbeat thread and the crimson
        reactor's heartbeat timer — the grace/report behavior is
        IDENTICAL across backends by construction."""
        grace = self.conf["osd_heartbeat_grace"]
        now = time.monotonic()
        for peer in self._hb_peers():
            last = self._hb_last_rx.get(peer)
            if last is None:
                self._hb_last_rx[peer] = now       # grace starts now
            elif now - last > grace:
                reported = self._hb_reported.get(peer, 0)
                if now - reported > grace:
                    self._hb_reported[peer] = now
                    self.log.dout(1, f"osd.{peer} silent "
                                  f"{now - last:.1f}s, reporting")
                    try:
                        self.monc.report_failure(
                            peer, self.whoami, now - last,
                            self.osdmap.epoch)
                    except Exception:
                        pass
            pad = self.conf["osd_heartbeat_min_size"]
            self.send_osd(peer, MOSDPing(
                op=MOSDPing.PING, from_osd=self.whoami,
                epoch=self.osdmap.epoch, stamp=now,
                padding="x" * pad))
        # forget peers no longer up (map took them out)
        up = set(self._hb_peers())
        for peer in list(self._hb_last_rx):
            if peer not in up:
                self._hb_last_rx.pop(peer, None)
                self._hb_reported.pop(peer, None)

    # ------------------------------------------------------------------
    # recovery (reference start_recovery_ops + recovery_wq)
    # ------------------------------------------------------------------
    def kick_recovery(self) -> None:
        self._recovery_kick.set()

    def _recovery_loop(self) -> None:
        """Scan for PGs owing recovery and hand them to the sharded
        op queues as ``recovery``-class items — the mClock scheduler
        arbitrates them against client IO (reference: recovery work
        rides OpSchedulerItems through the same queues)."""
        while not self._stop.is_set():
            self._recovery_kick.wait(timeout=0.2)
            self._recovery_kick.clear()
            if self._stop.is_set():
                return
            self._recovery_scan()

    def _recovery_scan(self) -> None:
        """One pass over hosted PGs, queueing recovery items up to the
        backfill budget.  Shared by the classic recovery thread and
        the crimson reactor's recovery timer."""
        with self.pg_lock:
            pgs = list(self.pgs.values())
        # osd_max_backfills: bound the PGs QUEUED for recovery at
        # once per daemon (reference backfill reservations) so one
        # OSD's rebuild never floods every PG simultaneously.
        # Only count transient queued state — an in-backend
        # recovery op wedged on a dead peer must not eat a slot
        # forever (its PG re-queues via the tick's stuck-retry)
        slots = self.conf["osd_max_backfills"] * 4
        active_recovering = sum(
            1 for pg in pgs
            if getattr(pg, "_recovery_queued", False))
        for pg in pgs:
            if self._stop.is_set():
                return
            if active_recovering >= slots:
                break                    # next kick continues
            try:
                with pg.lock:
                    need = pg.is_primary() and \
                        pg.state == STATE_ACTIVE and \
                        (pg.num_missing() > 0
                         or pg.waiting_for_degraded)
                if need:
                    self.queue_recovery_item(pg)
                    active_recovering += 1
            except Exception:
                import traceback
                traceback.print_exc()

    # ------------------------------------------------------------------
    # tick: pg stats + stuck-peering retry
    # ------------------------------------------------------------------
    def _tick_loop(self) -> None:
        interval = self.conf["osd_tick_interval"]
        while not self._stop.wait(interval):
            self._tick_once()

    def _tick_once(self) -> None:
        """One maintenance tick.  Shared by the classic tick thread
        and the crimson reactor's tick timer."""
        # osd_mon_report_interval throttles stat traffic on big
        # clusters; 0 reports every tick (test default)
        min_gap = self.conf["osd_mon_report_interval"]
        if time.monotonic() - getattr(self, "_last_stat_report",
                                      0.0) >= min_gap:
            self._last_stat_report = time.monotonic()
            self._send_pg_stats()
        self._retry_stuck_peering()
        self._renotify_strays()
        self._refresh_op_queue_perf()
        self._maybe_schedule_scrub()
        self._maybe_trim_snaps()
        self._maybe_trim_pg_logs()
        self._maybe_cache_agent()
        self._maybe_reboot()
        self._maybe_tuner_tick()

    def _maybe_tuner_tick(self) -> None:
        """Per-OSD closed-loop tuner tick (ROADMAP item 5).  Runs on
        BOTH backends for free: the classic tick thread and the
        crimson reactor timer share _tick_once.  Every
        osd_tuner_interval_ticks ticks it feeds the controller one
        (objective, signals, guard) sample — objective is EC requests
        retired per second, signals are the overlap/waterfall/stall
        ladder, the guard trips on SLO burn, an open device breaker,
        or an overlap collapse — then re-applies the batcher's live
        knobs so an accepted step lands within this tick."""
        try:
            if not self.conf["osd_tuner_enable"]:
                return
            interval = max(1, self.conf["osd_tuner_interval_ticks"])
        except (KeyError, TypeError):
            return
        self._tuner_ticks += 1
        if self._tuner_ticks % interval:
            return
        b = self.encode_batcher
        now = time.monotonic()
        reqs = b.reqs_total + b.dec_reqs
        last_t, last_reqs = self._tuner_last
        self._tuner_last = (now, reqs)
        if last_t is None or now <= last_t:
            return                   # first sample: baseline only
        objective = (reqs - last_reqs) / (now - last_t)
        signals, guard = self._tuner_signals()
        self.tuner.step(objective, signals=signals, guard=guard)
        b.apply_tuning()

    def _tuner_signals(self):
        """(signals, guard) for the controller: the observability
        ladder collapsed to one cheap snapshot.  Must not raise —
        a telemetry hiccup must never take down the tick."""
        b = self.encode_batcher
        signals = {}
        guard = None
        try:
            from ..utils.device_ledger import overlap_stats
            ov = overlap_stats(b.ledger_accum.recent())
            frac = ov.get("pipeline_overlap_frac", 0.0)
            signals["overlap_frac"] = frac
            if ov.get("bounding_phase"):
                signals["bounding_phase"] = ov["bounding_phase"]
            ps = dict(b.ledger_accum.phase_seconds)
            if ps:
                signals["top_hop"] = max(ps, key=ps.get)
            signals["staging_stalls"] = b._staging_stalls_seen
            cperf = getattr(self.contention, "cperf", None)
            if cperf is not None:
                signals["contention_stalls"] = int(
                    cperf.get("stalls"))
            # guard 1: overlap collapse — a step that halves a
            # previously healthy overlap is wrong no matter what the
            # throughput sample says this tick
            last = self._tuner_last_overlap
            self._tuner_last_overlap = frac
            if last is not None and last >= 0.25 and frac < 0.5 * last:
                guard = "overlap_collapse"
            # guard 2: SLO burn — any class consuming its error
            # budget faster than allowed vetoes the current probe
            for cls in self.slo.CLASSES:
                burn = self.slo.burn(cls)
                if burn > 1.0:
                    signals[f"{cls}_burn"] = round(burn, 3)
                    guard = f"slo_burn:{cls}"
            # guard 3: an open device circuit breaker means the
            # device is sick — never walk knobs on top of that
            if b.device_dump().get("breaker_open"):
                guard = "breaker_open"
        except Exception:
            pass
        return signals, guard

    def _reapply_mclock(self) -> None:
        """Config-observer target for the osd_mclock_scheduler_*
        options: push the current triples into every live shard
        queue.  The mgr tuner module's `config set` lands here via
        the central config riding the next map epoch."""
        try:
            if self.conf["osd_op_queue"] == "fifo":
                return
            from .scheduler import qos_from_conf
            qos = qos_from_conf(self.conf)
            changed = False
            for sq in self._shard_queues:
                changed = sq.set_qos(qos) or changed
            if changed:
                self.flight_recorder.note(
                    "mclock_retune",
                    **{cls: str(tuple(qos[cls]))
                       for cls in sorted(qos)})
        except Exception:
            pass

    def _renotify_strays(self) -> None:
        """Stray copies (split children on the parent's holders,
        migrated-away PGs) re-announce themselves until the primary
        purges them — covers notifies lost to races or primary
        failover."""
        with self.pg_lock:
            pgs = list(self.pgs.values())
        with self.map_lock:
            osdmap = self.osdmap
        for pg in pgs:
            try:
                if pg.is_stray():
                    pg.maybe_notify_stray(osdmap)
                pg.maybe_announce_merge(osdmap)
            except Exception:
                pass

    def _maybe_trim_snaps(self) -> None:
        """Drive snap trimming on primary PGs (reference OSD ticks the
        SnapTrimmer via the snap_trim work queue)."""
        with self.pg_lock:
            pgs = list(self.pgs.values())
        for pg in pgs:
            try:
                pg.maybe_trim_snaps()
            except Exception:
                import traceback
                traceback.print_exc()

    def _maybe_trim_pg_logs(self) -> None:
        """Clean primaries trim their log to osd_min_pg_log_entries
        (reference PeeringState::calc_trim_to: min while clean, max
        while degraded — degraded PGs keep history for log-based
        catch-up)."""
        min_e = self.conf["osd_min_pg_log_entries"]
        with self.pg_lock:
            pgs = list(self.pgs.values())
        for pg in pgs:
            try:
                with pg.lock:
                    if pg.is_primary() and pg.state == STATE_ACTIVE \
                            and pg.num_missing() == 0 \
                            and not any(ms.items for ms in
                                        pg.peer_missing.values()):
                        pg.log.trim_to(min_e)
            except Exception:
                pass

    def _maybe_cache_agent(self) -> None:
        """Drive the cache-tier agent on primary tier-pool PGs
        (reference OSD tick -> agent_work)."""
        with self.pg_lock:
            pgs = list(self.pgs.values())
        for pg in pgs:
            try:
                if pg.pool.is_tier():
                    pg.cache_agent()
            except Exception:
                import traceback
                traceback.print_exc()

    def _maybe_reboot(self) -> None:
        """The boot can be lost to a mon election (commit rejected by
        a dissolving quorum, or a lossy mon session dropping it):
        keep re-announcing until the map shows us up (reference OSD
        start_boot retry ticks)."""
        with self.map_lock:
            info = self.osdmap.osds.get(self.whoami)
        if (info is None or not info.up or
                tuple(info.addr or ()) != tuple(self.my_addr)) \
                and not self._stop.is_set():
            try:
                self.monc.send_boot(self.whoami, self.my_addr)
            except Exception:
                pass

    def _maybe_schedule_scrub(self) -> None:
        """Periodic scrub scheduling (reference OSD::sched_scrub:
        shallow every osd_scrub_interval, deep every
        osd_deep_scrub_interval; 0 disables)."""
        shallow = self.conf["osd_scrub_interval"]
        deep_iv = self.conf["osd_deep_scrub_interval"]
        now = time.time()
        with self.pg_lock:
            pgs = list(self.pgs.values())
        for pg in pgs:
            with pg.lock:
                pg.scrubber.maybe_abort_stuck()
                pg.scrubber.kick()       # drain-wait retries
        if shallow <= 0:
            return
        # reference osd_scrub_load_threshold: a loaded host defers
        # background scrubbing entirely
        load_cap = self.conf["osd_scrub_load_threshold"]
        if load_cap > 0:
            try:
                import os as _os
                if _os.getloadavg()[0] > load_cap:
                    return
            except OSError:
                pass
        # osd_max_scrubs bounds concurrent scrub rounds per daemon
        # (reference osd_max_scrubs + scrub reservations)
        budget = self.conf["osd_max_scrubs"] - sum(
            1 for pg in pgs if pg.scrubber.active)
        if self.conf["osd_scrub_sleep"] > 0:
            # pacing (reference osd_scrub_sleep, applied between scrub
            # chunks there): schedule at most one PG's round per tick
            # — lock-free pacing, no sleeping under the PG lock
            budget = min(budget, 1)
        if not self.conf["osd_scrub_during_recovery"] and any(
                pg.is_primary() and pg.num_missing() > 0
                for pg in pgs):
            # reference osd_scrub_during_recovery=false: recovery IO
            # outranks background scrub on this daemon
            return
        # per-PG jittered cadence (reference osd_scrub_min_interval /
        # osd_scrub_max_interval): a stable per-PG offset spreads
        # rounds out instead of scrubbing every PG in one burst
        smin = self.conf["osd_scrub_min_interval"]
        smax = self.conf["osd_scrub_max_interval"]
        for pg in pgs:
            if budget <= 0:
                break
            with pg.lock:
                if not pg.is_primary() or pg.state != STATE_ACTIVE \
                        or pg.scrubber.active:
                    continue
                interval = shallow
                if 0 < smin < smax:
                    frac = (hash(str(pg.pgid)) & 0xFFFF) / 0xFFFF
                    interval = smin + frac * (smax - smin)
                if now - pg.scrubber.last_scrub < interval:
                    continue
                budget -= 1
                deep = deep_iv > 0 and \
                    now - pg.scrubber.last_deep_scrub >= deep_iv
                self._queue_scrub(pg, deep)

    def _queue_scrub(self, pg: PG, deep: bool) -> None:
        """Scrub-class work goes through the scheduler so it never
        outruns client IO (reference PGScrub items); the crimson OSD
        queues it on the reactor instead."""
        self._shard_queues[self._shard_of_pg(pg)].enqueue(
            "scrub", lambda p=pg, d=deep: self._start_scrub(p, d))

    def _start_scrub(self, pg: PG, deep: bool) -> None:
        with pg.lock:
            if not pg.is_primary() or pg.state != STATE_ACTIVE \
                    or pg.scrubber.active:
                return
            # re-check freshness: stacked queue items must not run
            # back-to-back scrubs of the same PG
            if time.time() - pg.scrubber.last_scrub < \
                    self.conf["osd_scrub_interval"]:
                return
            pg.scrubber.start(deep=deep, repair=False)

    def _send_pg_stats(self) -> None:
        stats: Dict[str, dict] = {}
        with self.pg_lock:
            pgs = list(self.pgs.items())
        for pgid, pg in pgs:
            if pg.is_primary():
                try:
                    stats[str(pgid)] = pg.get_stats()
                except Exception:
                    pass
        # osd_stat_t analog: store fullness feeds the monitor's
        # OSD_FULL/OSD_NEARFULL health checks (mon_osd_full_ratio /
        # mon_osd_nearfull_ratio); only capacity-capped stores report
        osd_stat = {}
        cap = getattr(self.store, "max_bytes", 0)
        if cap:
            osd_stat = {"kb": cap >> 10,
                        "kb_used": getattr(self.store, "_data_bytes",
                                           0) >> 10}
        if stats or osd_stat:
            try:
                self.monc.send_pg_stats(self.whoami, self.osdmap.epoch,
                                        stats, osd_stat=osd_stat)
            except Exception:
                pass

    def _retry_stuck_peering(self) -> None:
        """A peering Query or recovery sub-op can race a peer's map
        (messages for PGs it can't place yet are dropped); the primary
        re-queries / re-runs recovery until everyone answers (the
        reference's peering statechart retries via map-epoch events)."""
        with self.pg_lock:
            pgs = list(self.pgs.values())
        kick = False
        for pg in pgs:
            with pg.lock:
                if pg.is_primary() and pg.state == STATE_PEERING:
                    pg._start_peering()
                if pg.is_primary() and pg.requeue_stale_recovery():
                    kick = True
                if pg.is_primary() and pg.state == STATE_ACTIVE \
                        and pg.num_missing() > 0:
                    kick = True          # belt-and-braces recovery kick
        if kick:
            self.kick_recovery()
