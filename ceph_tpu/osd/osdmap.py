"""OSDMap — the cluster's authoritative placement state.

Python-native equivalent of the reference's OSDMap (reference
src/osd/OSDMap.{h,cc}): an epoch-versioned snapshot of OSD up/in
state, pools, erasure-code profiles and the CRUSH map, plus the
object→PG→OSD mapping pipeline
(``object_locator_to_pg`` → ``pg_to_up_acting_osds`` →
``crush.do_rule``; reference osd/OSDMap.cc:2403-2415).

Replicated pools prune down OSDs and shift survivors left; erasure
pools keep per-position holes (``None``) because EC acting-set
positions are *not interchangeable* (reference
doc/dev/osd_internals/erasure_coding/ecbackend.rst, "Distinguished
acting set positions").

Maps advance by applying ``Incremental`` deltas committed by the
monitor (reference OSDMap::Incremental, apply_incremental).
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..crush.mapper import CRUSH_ITEM_NONE, crush_hash32_2
from ..crush.wrapper import CrushWrapper

POOL_TYPE_REPLICATED = "replicated"
POOL_TYPE_ERASURE = "erasure"


def ceph_str_hash_rjenkins(s: bytes) -> int:
    """Jenkins one-at-a-time style string hash over 12-byte blocks
    (behavioral port of the published rjenkins string hash the
    reference uses for object names, common/ceph_hash.cc)."""
    M32 = 0xFFFFFFFF
    a, b = 0x9E3779B9, 0x9E3779B9
    c = 0  # the hash
    i, length = 0, len(s)

    def mix(a, b, c):
        a = (a - b - c) & M32; a ^= c >> 13
        b = (b - c - a) & M32; b ^= (a << 8) & M32
        c = (c - a - b) & M32; c ^= b >> 13
        a = (a - b - c) & M32; a ^= c >> 12
        b = (b - c - a) & M32; b ^= (a << 16) & M32
        c = (c - a - b) & M32; c ^= b >> 5
        a = (a - b - c) & M32; a ^= c >> 3
        b = (b - c - a) & M32; b ^= (a << 10) & M32
        c = (c - a - b) & M32; c ^= b >> 15
        return a, b, c

    while length - i >= 12:
        a = (a + int.from_bytes(s[i:i + 4], "little")) & M32
        b = (b + int.from_bytes(s[i + 4:i + 8], "little")) & M32
        c = (c + int.from_bytes(s[i + 8:i + 12], "little")) & M32
        a, b, c = mix(a, b, c)
        i += 12
    tail = s[i:]
    c = (c + length) & M32
    pad = tail + b"\x00" * (12 - len(tail))
    a = (a + int.from_bytes(pad[0:4], "little")) & M32
    b = (b + int.from_bytes(pad[4:8], "little")) & M32
    # skip the low byte of the last word, as the original does (length
    # already folded into c)
    c = (c + (int.from_bytes(pad[8:12], "little") << 8 & M32)) & M32
    a, b, c = mix(a, b, c)
    return c


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """Stable modulo: doubling b reassigns at most half the inputs
    (reference include/ceph_hash.h ceph_stable_mod)."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


def pg_num_mask(pg_num: int) -> int:
    m = 1
    while m < pg_num:
        m <<= 1
    return m - 1


def pg_split_parent(seed: int) -> int:
    """Structural split parent of a child PG seed: the seed with its
    top bit cleared.  Algebraic consequence of ceph_stable_mod: for any
    old/new pg_num pair with old <= seed < new, the objects that land
    in child ``seed`` previously hashed to exactly this parent
    (reference pg_t::parent_of / is_split, osd/osd_types.h)."""
    assert seed > 0
    return seed & ~(1 << (seed.bit_length() - 1))


def pg_split_ancestors(seed: int, created_pg_num: int) -> List[int]:
    """Ancestor chain of a split child down to (and including) the
    first seed that existed at pool creation — the framework's
    map-history-free stand-in for the reference's past_intervals: data
    for a split child can only ever live with its structural
    ancestors' holders."""
    out = []
    while seed >= max(created_pg_num, 1):
        seed = pg_split_parent(seed)
        out.append(seed)
    return out


def pg_split_source(seed: int, old_pg_num: int) -> int:
    """The pre-growth PG (< old_pg_num) that holds the objects of
    child ``seed``: walk the structural parent chain down below
    old_pg_num."""
    while seed >= old_pg_num:
        seed = pg_split_parent(seed)
    return seed


def pg_split_children(seed: int, old_pg_num: int,
                      new_pg_num: int) -> List[int]:
    """Child seeds whose objects PG ``seed`` holds when pg_num grows
    old -> new (reference pg_t::is_split, osd/osd_types.h)."""
    return [c for c in range(old_pg_num, new_pg_num)
            if pg_split_source(c, old_pg_num) == seed]


@dataclass(frozen=True, order=True)
class PGid:
    """(pool id, placement seed) — reference pg_t."""
    pool: int
    seed: int

    def __str__(self) -> str:
        return f"{self.pool}.{self.seed:x}"

    @classmethod
    def parse(cls, s: str) -> "PGid":
        pool, seed = s.split(".")
        return cls(int(pool), int(seed, 16))


@dataclass(frozen=True, order=True)
class SPGid:
    """Shard-qualified pg id (reference spg_t): EC shard identity."""
    pgid: PGid
    shard: int = -1  # -1 = NO_SHARD (replicated)

    def __str__(self) -> str:
        if self.shard < 0:
            return str(self.pgid)
        return f"{self.pgid}s{self.shard}"


@dataclass
class PGPool:
    """reference pg_pool_t (osd/osd_types.h)."""
    name: str
    pool_id: int
    type: str = POOL_TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    pg_num: int = 32
    created_pg_num: int = 0      # pg_num at pool creation (split anchor)
    crush_rule: int = 0
    erasure_code_profile: str = ""
    stripe_width: int = 0
    ec_overwrites: bool = False   # allows_ecoverwrites, osd_types.h:1600
    fast_read: bool = False       # EC read-all-reconstruct-first-k
                                  # (reference pg_pool_t FLAG_EC_FAST_READ)
    # snapshots (reference pg_pool_t snap fields, osd/osd_types.h):
    snap_seq: int = 0                  # newest allocated snap id
    removed_snaps: List[int] = field(default_factory=list)
    pool_snaps: Dict[str, int] = field(default_factory=dict)  # name->id
    # cache tiering (reference pg_pool_t tier fields, osd/osd_types.h:
    # tier_of / read_tier / write_tier / cache_mode; applied by
    # PrimaryLogPG::maybe_handle_cache_detail, PrimaryLogPG.cc:2700)
    pg_num_epoch: int = 0              # epoch of the last pg_num
                                       # change (merge rebase anchor)
    tier_of: int = -1                  # base pool this pool caches
    read_tier: int = -1                # on the BASE pool: overlay tier
    write_tier: int = -1
    cache_mode: str = "none"           # none | writeback | readonly
    target_max_objects: int = 0        # tier agent evict thresholds
    target_max_bytes: int = 0
    cache_target_dirty_ratio: float = 0.4

    def is_erasure(self) -> bool:
        return self.type == POOL_TYPE_ERASURE

    def is_tier(self) -> bool:
        return self.tier_of >= 0

    def has_tiers(self) -> bool:
        return self.read_tier >= 0 or self.write_tier >= 0

    def raw_pg_to_pps(self, seed: int) -> int:
        """Placement seed for CRUSH input (reference
        pg_pool_t::raw_pg_to_pps HASHPSPOOL path)."""
        return crush_hash32_2(
            ceph_stable_mod(seed, self.pg_num, pg_num_mask(self.pg_num)),
            self.pool_id)


@dataclass
class OSDInfo:
    up: bool = False
    weight: int = 0          # in/out: 16.16 fixed, 0 = out
    addr: Optional[Tuple[str, int]] = None
    up_from: int = 0
    down_at: int = 0


class Incremental:
    """Delta between consecutive epochs (reference OSDMap::Incremental)."""

    def __init__(self, epoch: int):
        self.epoch = epoch
        self.new_up: Dict[int, Tuple[str, int]] = {}    # osd -> addr
        self.new_down: List[int] = []
        self.new_weight: Dict[int, int] = {}            # osd -> 16.16
        self.new_pools: Dict[int, PGPool] = {}
        self.old_pools: List[int] = []
        self.new_profiles: Dict[str, dict] = {}
        self.old_profiles: List[str] = []
        self.new_crush: Optional[CrushWrapper] = None
        self.new_max_osd: Optional[int] = None
        # central config deltas (reference ConfigMonitor collapsed
        # into the map: overrides ride map publication to daemons)
        self.new_config: Dict[str, str] = {}
        self.old_config: List[str] = []


class OSDMap:
    def __init__(self) -> None:
        self.epoch = 0
        self.fsid = ""
        self.max_osd = 0
        self.osds: Dict[int, OSDInfo] = {}
        self.pools: Dict[int, PGPool] = {}
        self.pool_name_to_id: Dict[str, int] = {}
        self.erasure_code_profiles: Dict[str, dict] = {
            "default": {"plugin": "jerasure", "technique": "reed_sol_van",
                        "k": "2", "m": "1"}}
        self.crush = CrushWrapper()
        self._next_pool_id = 1
        # cluster-wide config overrides (name -> raw string value);
        # daemons apply them on every map publish (observers fire)
        self.cluster_config: Dict[str, str] = {}

    # -- state queries ----------------------------------------------------
    def is_up(self, osd: int) -> bool:
        return osd in self.osds and self.osds[osd].up

    def is_in(self, osd: int) -> bool:
        return osd in self.osds and self.osds[osd].weight > 0

    def get_addr(self, osd: int) -> Optional[Tuple[str, int]]:
        info = self.osds.get(osd)
        return info.addr if info else None

    def osd_weights(self) -> List[int]:
        return [self.osds[o].weight if o in self.osds else 0
                for o in range(self.max_osd)]

    def get_pool(self, name_or_id) -> Optional[PGPool]:
        if isinstance(name_or_id, str):
            pid = self.pool_name_to_id.get(name_or_id)
            return self.pools.get(pid) if pid is not None else None
        return self.pools.get(name_or_id)

    # -- object -> pg -> osds pipeline ------------------------------------
    def object_locator_to_pg(self, oid: str, pool_id: int) -> PGid:
        """reference Objecter's object_locator_to_pg
        (osdc/Objecter.cc:2820 → OSDMap::object_locator_to_pg)."""
        pool = self.pools[pool_id]
        ps = ceph_str_hash_rjenkins(oid.encode())
        return PGid(pool_id, ceph_stable_mod(ps, pool.pg_num,
                                             pg_num_mask(pool.pg_num)))

    def pg_to_raw_osds(self, pgid: PGid) -> List[Optional[int]]:
        """CRUSH mapping with EC holes as None (reference
        _pg_to_raw_osds, OSDMap.cc:2403)."""
        pool = self.pools[pgid.pool]
        pps = pool.raw_pg_to_pps(pgid.seed)
        raw = self.crush.do_rule(pool.crush_rule, pps, pool.size,
                                 self.osd_weights())
        return [None if o == CRUSH_ITEM_NONE else o for o in raw]

    def pg_to_up_acting_osds(self, pgid: PGid
                             ) -> Tuple[List[Optional[int]], Optional[int],
                                        List[Optional[int]], Optional[int]]:
        """-> (up, up_primary, acting, acting_primary) (reference
        OSDMap::pg_to_up_acting_osds).  Without pg_temp, up == acting
        after down-filtering."""
        pool = self.pools[pgid.pool]
        raw = self.pg_to_raw_osds(pgid)
        if pool.is_erasure():
            up: List[Optional[int]] = [
                o if o is not None and self.is_up(o) else None for o in raw]
        else:
            up = [o for o in raw if o is not None and self.is_up(o)]
        primary = next((o for o in up if o is not None), None)
        acting = list(up)
        return up, primary, acting, primary

    def pg_shard_osd(self, pgid: PGid, shard: int) -> Optional[int]:
        up, _, _, _ = self.pg_to_up_acting_osds(pgid)
        if 0 <= shard < len(up):
            return up[shard]
        return None

    def pgs_for_pool(self, pool_id: int) -> List[PGid]:
        pool = self.pools[pool_id]
        return [PGid(pool_id, s) for s in range(pool.pg_num)]

    # -- mutation (monitor side) ------------------------------------------
    def apply_incremental(self, inc: Incremental) -> None:
        assert inc.epoch == self.epoch + 1, \
            f"incremental {inc.epoch} does not follow epoch {self.epoch}"
        if inc.new_crush is not None:
            self.crush = inc.new_crush
        if inc.new_max_osd is not None:
            self.max_osd = inc.new_max_osd
        for osd, addr in inc.new_up.items():
            brand_new = osd not in self.osds
            info = self.osds.setdefault(osd, OSDInfo())
            info.up = True
            info.addr = addr
            info.up_from = inc.epoch
            if brand_new and info.weight == 0:
                # first-ever boot starts in; a REJOINING out OSD's
                # weight is the monitor's call (mon_osd_auto_mark_in
                # rides inc.new_weight), not an automatic side effect
                info.weight = 0x10000
            self.max_osd = max(self.max_osd, osd + 1)
        for osd in inc.new_down:
            if osd in self.osds:
                self.osds[osd].up = False
                self.osds[osd].down_at = inc.epoch
        for osd, w in inc.new_weight.items():
            self.osds.setdefault(osd, OSDInfo()).weight = w
        self.cluster_config.update(inc.new_config)
        for name in inc.old_config:
            self.cluster_config.pop(name, None)
        for pid, pool in inc.new_pools.items():
            self.pools[pid] = pool
            self.pool_name_to_id[pool.name] = pid
            self._next_pool_id = max(self._next_pool_id, pid + 1)
        for pid in inc.old_pools:
            pool = self.pools.pop(pid, None)
            if pool:
                self.pool_name_to_id.pop(pool.name, None)
        for name, profile in inc.new_profiles.items():
            self.erasure_code_profiles[name] = dict(profile)
        for name in inc.old_profiles:
            self.erasure_code_profiles.pop(name, None)
        self.epoch = inc.epoch

    def clone(self) -> "OSDMap":
        return copy.deepcopy(self)

    # -- wire form (reference OSDMap::encode/decode, shipped in MOSDMap) --
    def to_wire_dict(self) -> Dict:
        return {
            "epoch": self.epoch,
            "fsid": self.fsid,
            "max_osd": self.max_osd,
            "osds": {str(o): {"up": i.up, "weight": i.weight,
                              "addr": list(i.addr) if i.addr else None,
                              "up_from": i.up_from, "down_at": i.down_at}
                     for o, i in self.osds.items()},
            "pools": {str(p.pool_id): {
                "name": p.name, "type": p.type, "size": p.size,
                "min_size": p.min_size, "pg_num": p.pg_num,
                "created_pg_num": p.created_pg_num,
                "crush_rule": p.crush_rule,
                "erasure_code_profile": p.erasure_code_profile,
                "stripe_width": p.stripe_width,
                "ec_overwrites": p.ec_overwrites,
                "fast_read": p.fast_read,
                "snap_seq": p.snap_seq,
                "removed_snaps": p.removed_snaps,
                "pool_snaps": p.pool_snaps,
                "pg_num_epoch": p.pg_num_epoch,
                "tier_of": p.tier_of,
                "read_tier": p.read_tier,
                "write_tier": p.write_tier,
                "cache_mode": p.cache_mode,
                "target_max_objects": p.target_max_objects,
                "target_max_bytes": p.target_max_bytes,
                "cache_target_dirty_ratio": p.cache_target_dirty_ratio}
                for p in self.pools.values()},
            "erasure_code_profiles": self.erasure_code_profiles,
            "cluster_config": dict(self.cluster_config),
            "crush": self.crush.to_wire_dict(),
        }

    @classmethod
    def from_wire_dict(cls, d: Dict) -> "OSDMap":
        m = cls()
        m.epoch = d["epoch"]
        m.fsid = d["fsid"]
        m.max_osd = d["max_osd"]
        for o, i in d["osds"].items():
            m.osds[int(o)] = OSDInfo(
                up=i["up"], weight=i["weight"],
                addr=tuple(i["addr"]) if i["addr"] else None,
                up_from=i["up_from"], down_at=i["down_at"])
        for pid, p in d["pools"].items():
            pool = PGPool(name=p["name"], pool_id=int(pid), type=p["type"],
                          size=p["size"], min_size=p["min_size"],
                          pg_num=p["pg_num"],
                          created_pg_num=p.get("created_pg_num",
                                               p["pg_num"]),
                          crush_rule=p["crush_rule"],
                          erasure_code_profile=p["erasure_code_profile"],
                          stripe_width=p["stripe_width"],
                          ec_overwrites=p.get("ec_overwrites", False),
                          fast_read=p.get("fast_read", False),
                          snap_seq=p.get("snap_seq", 0),
                          removed_snaps=list(p.get("removed_snaps", [])),
                          pool_snaps=dict(p.get("pool_snaps", {})),
                          pg_num_epoch=p.get("pg_num_epoch", 0),
                          tier_of=p.get("tier_of", -1),
                          read_tier=p.get("read_tier", -1),
                          write_tier=p.get("write_tier", -1),
                          cache_mode=p.get("cache_mode", "none"),
                          target_max_objects=p.get(
                              "target_max_objects", 0),
                          target_max_bytes=p.get("target_max_bytes", 0),
                          cache_target_dirty_ratio=p.get(
                              "cache_target_dirty_ratio", 0.4))
            m.pools[int(pid)] = pool
            m.pool_name_to_id[pool.name] = int(pid)
            m._next_pool_id = max(m._next_pool_id, int(pid) + 1)
        m.erasure_code_profiles = {
            k: dict(v) for k, v in d["erasure_code_profiles"].items()}
        m.cluster_config = dict(d.get("cluster_config", {}))
        m.crush = CrushWrapper.from_wire_dict(d["crush"])
        return m

    # -- dump --------------------------------------------------------------
    def dump(self) -> Dict:
        return {
            "epoch": self.epoch,
            "max_osd": self.max_osd,
            "osds": [{"osd": o, "up": int(i.up),
                      "in": int(i.weight > 0),
                      "weight": i.weight / 0x10000,
                      "addr": list(i.addr) if i.addr else None}
                     for o, i in sorted(self.osds.items())],
            "pools": [{"pool": p.pool_id, "name": p.name, "type": p.type,
                       "size": p.size, "min_size": p.min_size,
                       "pg_num": p.pg_num, "crush_rule": p.crush_rule,
                       "erasure_code_profile": p.erasure_code_profile,
                       "stripe_width": p.stripe_width}
                      for p in sorted(self.pools.values(),
                                      key=lambda p: p.pool_id)],
            "erasure_code_profiles": self.erasure_code_profiles,
        }
