"""Placement-group state machine: op execution, peering, recovery.

Python-native equivalent of the reference's PG / PrimaryLogPG /
PeeringState stack (reference src/osd/PG.cc, PrimaryLogPG.cc 15.5k LoC,
PeeringState.{h,cc} boost::statechart) reduced to the states the
framework drives:

* **op execution** (primary): ``do_request`` -> ``do_op`` -> the op
  switch (reference PrimaryLogPG::do_osd_ops' giant switch, :5737) —
  write-class ops lower to one logical ``Mutation`` and go through
  ``backend.submit_transaction`` with a PG-log entry (reference
  issue_repop, :10650); read-class ops run against the backend
  (EC reads reconstruct asynchronously);
* **peering** (reference PeeringState): on every map interval change
  the primary Queries the acting set, members Notify with their
  bounded full log, the primary picks the authoritative log (best
  last_update), adopts it if behind, computes per-shard missing sets
  and Activates everyone with catch-up entries — or a ``backfill``
  object list when a shard's log no longer overlaps (reference
  GetInfo/GetLog/GetMissing/Activate collapsed to one round trip);
* **recovery** (primary): ``start_recovery_ops(budget)`` drains the
  union of missing sets through ``backend.recover_object`` (reference
  PrimaryLogPG::start_recovery_ops / recover_primary + recover_
  replicas), prioritizing objects client ops are blocked on
  (``waiting_for_degraded``, the reference's wait_for_degraded_object);
* EC pools reject omap and truncate unless ``ec_overwrites``
  (reference pg_pool_t::allows_ecoverwrites, osd_types.h:1600).

Degraded writes block until the object recovers, as the reference does
(PrimaryLogPG::wait_for_degraded_object), keeping all acting shards
write-consistent.

Locking: one RLock per PG serializes every entry point (the
reference's PG lock); store-commit callbacks re-enter through
``on_local_commit`` which takes the lock.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..msg.messages import (MOSDOp, MOSDOpReply, MOSDPGLog, MOSDPGNotify,
                            MOSDPGQuery, MOSDPGRemove, OSDOp)
from ..store.objectstore import GHObject, Transaction
from ..utils.log import Dout
from .backend import OI_ATTR, Mutation, ObjectInfo, build_pg_backend
from .ecbackend import ECBackend
from .osdmap import OSDMap, PGPool, PGid, POOL_TYPE_ERASURE
from .pglog import (DELETE, MODIFY, Eversion, LogEntry, MissingSet,
                    PGLog)

PGMETA_OID = "_pgmeta"          # reference pgmeta_oid
LOG_KEY_PREFIX = "log."
INFO_KEY = "info"
SPLIT_KEY = "split_pgnum"       # pool pg_num this PG last split at
STRAY_SHARD_KEY = "stray_shard"  # EC shard identity kept while stray
SPLIT_SRC_KEY = "split_src"     # parent shard whose chunks we hold
MERGE_SRC_KEY = "merge_srcs"    # all child shards a merge folded here
SPLIT_ADOPTED_KEY = "split_adopted"  # a local parent split fed us
MISSING_KEY = "missing"         # persisted pg_missing_t (reference
                                # PGLog write_log_and_missing)

STATE_INACTIVE = "inactive"
STATE_PEERING = "peering"
STATE_ACTIVE = "active"

WRITE_OPS = {"write", "writefull", "append", "create", "delete",
             "truncate", "setxattr", "rmxattr", "rmxattrs",
             "omap_set", "omap_rm",
             "omap_clear", "call", "rollback", "copy_from",
             "cache_flush", "cache_evict"}
READ_OPS = {"read", "stat", "getxattr", "getxattrs", "omap_get",
            "omap_get_by_key", "pgls", "list_snaps",
            "watch", "unwatch", "notify", "notify_ack",
            "list_watchers"}
# read-class ops that always address the HEAD (never snap-resolved
# even while the client holds a read snap)
HEAD_PINNED_OPS = {"watch", "unwatch", "notify", "notify_ack",
                   "list_watchers", "list_snaps", "pgls"}


class PG:
    """One placement group as hosted by one OSD (primary or replica
    shard).  ``service`` is the hosting OSD's service surface (see
    osd.OSDService): whoami, conf, store, send_osd, get_osdmap."""

    def __init__(self, service, pgid: PGid, pool: PGPool):
        self.service = service
        self.pgid = pgid
        self.pool = pool
        # PG lock with contention telemetry when the host provides a
        # sink (utils/locks.py); bare hosts in unit tests fall back to
        # an untimed lockdep lock
        from ..utils.locks import TimedLock
        self.lock = TimedLock("pg_lock",
                              stats=getattr(service, "contention", None))
        # shard-per-core (crimson): the reactor shard that owns this
        # PG's state — every client op, sub-op and recovery item for
        # the PG executes there (hash(pgid) % n_reactors), so the
        # lock above is uncontended on the data path.  None on the
        # classic backend.
        self.home_shard: Optional[int] = None
        self.state = STATE_INACTIVE
        self.up: List[Optional[int]] = []
        self.acting: List[Optional[int]] = []
        self.primary_osd: Optional[int] = None
        self.interval_start = 0          # epoch of last acting change
        try:                             # reference osd_max_pg_log_entries
            max_entries = service.conf["osd_max_pg_log_entries"]
        except (AttributeError, KeyError):
            max_entries = PGLog.DEFAULT_MAX_ENTRIES
        self.log = PGLog(max_entries)
        self.missing = MissingSet()      # objects THIS shard lacks
        self.peer_missing: Dict[int, MissingSet] = {}
        self._peer_notifies: Dict[int, dict] = {}
        self.waiting_for_active: deque = deque()
        # backend sub-ops that raced our map: an EC shard message
        # arriving before this OSD's map places it in the acting set
        # has no home shard collection yet (own_shard -1) — applying
        # it would write to a collection that does not exist.  Queued
        # until advance_map assigns the shard (reference: op queue
        # waits on waiting_for_map / waiting_peering)
        self.waiting_for_shard: deque = deque()
        self.waiting_for_degraded: Dict[str, deque] = {}
        # per-object write tracking at the PG level (oid -> in-flight
        # count).  Most write classes serialize per object so size-
        # dependent logic (appends, snapshots) can't go stale; plain
        # partial overwrites on EC-overwrites pools PIPELINE instead —
        # the backend's extent overlay (ExtentCache) keeps their RMW
        # reads coherent
        self.inflight_writes: Dict[str, int] = {}
        # oid -> newest in-flight version (prior_version chaining for
        # pipelined writes); dropped when the object settles
        self._pending_versions: Dict[str, Eversion] = {}
        self.waiting_for_obj: Dict[str, deque] = {}
        self.waiting_for_scrub: deque = deque()
        # recent committed-op outputs for dup-resend replay (class
        # call payloads); insertion-ordered, bounded
        self._reply_cache: Dict[Tuple[str, int], List[bytes]] = {}
        # every client op this PG currently holds, by reqid; on an
        # interval change they all bounce back to the client for
        # re-targeting (reference on_change requeue + client resend)
        self._client_ops: Dict[Tuple[str, int], Tuple] = {}
        self._last_assigned: Eversion = (0, 0)
        # oid -> start time; recovery sub-ops can be dropped by peers
        # that raced a map epoch, so stale entries are requeued by the
        # OSD tick (the reference retries via peering-event machinery)
        self.recovering: Dict[str, float] = {}
        # cache tiering (reference PrimaryLogPG cache machinery,
        # PrimaryLogPG.cc:2700 maybe_handle_cache_detail): in-flight
        # promotes (oid -> parked (msg, conn) waiters), objects being
        # flushed to the base pool, and observability counters
        self._promoting: Dict[str, List[Tuple]] = {}
        self._flushing: Set[str] = set()
        self._evicting: Set[str] = set()
        self._base_deleting: Set[str] = set()
        self.cache_promotes = 0
        self.cache_flushes = 0
        self.cache_evicts = 0
        # watch/notify (reference osd/Watch.cc): primary-side watcher
        # registry, volatile — clients re-register through lingering
        # ops on every map change, so failover self-heals
        self.watchers: Dict[str, Dict[Tuple[str, int], object]] = {}
        self._notifies: Dict[int, Dict] = {}
        self._next_notify_id = 0
        # -- PG split (reference OSD::split_pgs, osd/OSD.cc:8926) ------
        # pool pg_num this PG has split to; growth beyond it triggers
        # maybe_split().  Fresh PGs start current; the persisted value
        # (pgmeta) wins on restart so growth-while-down still splits.
        self._last_split_pgnum = pool.created_pg_num or pool.pg_num
        # stray side (we hold data for a PG whose acting set excludes
        # us — split children start life this way on the parent's
        # holders; the reference's past_intervals machinery is replaced
        # by strays announcing themselves to the current primary):
        self._stray_shard = -1       # EC shard identity we held
        # EC split: the parent shard whose physical chunks this copy
        # holds.  EC positions are NOT interchangeable (reference
        # ecbackend.rst "Distinguished acting set positions"): a child
        # acting member may hold parent-shard-s chunks while being
        # assigned position j != s — its position data is then MISSING
        # (audited on activation) while its s-chunks serve as a
        # recovery source.
        self._split_source_shard = -1
        # EC merge: ALL distinct child shards whose chunks a merge
        # folded into our collections (a parent may absorb several
        # children, each at a different position).  Every one is
        # audited at merge time and re-audited on interval change
        # until recovery homes our own position's chunks.
        self._merge_source_shards: List[int] = []
        # True once a local parent split adopted this copy: its content
        # (even empty) is the ancestry's authoritative answer for this
        # child seed
        self._split_adopted = False
        # primary side: stray notifies (osd -> notify payload) and the
        # object sets they can serve as recovery sources
        self._stray_notifies: Dict[int, dict] = {}
        self._stray_sources: Dict[int, Dict[str, Eversion]] = {}
        self.backend = build_pg_backend(self, pool, service.ec_registry)
        from .scrub import Scrubber
        self.scrubber = Scrubber(self)
        self._ensure_collections()
        self._load_pgmeta()

    # ------------------------------------------------------------------
    # PGHost surface (consumed by the backend)
    # ------------------------------------------------------------------
    @property
    def whoami(self) -> int:
        return self.service.whoami

    @property
    def pgid_str(self) -> str:
        return str(self.pgid)

    @property
    def own_shard(self) -> int:
        if not self.pool.is_erasure():
            return -1
        for i, osd in enumerate(self.acting):
            if osd == self.whoami:
                return i
        # a split/migration stray keeps serving the shard it held when
        # it left the acting set (collection + read identity)
        return self._stray_shard

    @property
    def store(self):
        return self.service.store

    @property
    def conf(self):
        return self.service.conf

    @property
    def epoch(self) -> int:
        return self.service.get_osdmap().epoch

    def coll_of(self, shard: int) -> str:
        if shard < 0:
            return str(self.pgid)
        return f"{self.pgid}s{shard}"

    @property
    def coll(self) -> str:
        return self.coll_of(self.own_shard)

    def acting_shards(self) -> List[Tuple[int, Optional[int]]]:
        return list(enumerate(self.acting))

    def send_shard(self, osd: int, msg) -> None:
        self.service.send_osd(osd, msg)

    def observe_hops(self, hops, kind: str = "write") -> None:
        """Fold a completed sub-op round-trip ledger into this OSD's
        hops accumulator for the given op class — "write" (sub-write
        round trips), "read" (client-facing shard reads) or "recovery"
        (pushes/pulls, recovery reads, decode/scrub windows).  Bare
        test hosts have no accumulators."""
        attr = {"read": "hops_read",
                "recovery": "hops_recovery"}.get(kind, "hops")
        acc = getattr(self.service, attr, None)
        if acc is not None:
            acc.observe_wire(hops)

    def prepare_log_txn(self, txn: Transaction,
                        log_entries: List[dict]) -> None:
        """Persist log entries + info into the pgmeta object's omap in
        the same transaction as the data (reference: pgmeta omap)."""
        for e in log_entries:
            entry = LogEntry.from_dict(e)
            if entry.version > self.log.last_update:
                self.log.add(entry)
        self._append_pgmeta_ops(txn)

    def on_local_commit(self, fn: Callable[[], None]) -> None:
        with self.lock:
            fn()

    @property
    def encode_batcher(self):
        """The OSD-wide cross-op encode coalescer (osd/batcher.py);
        None under hosts without one (unit-test stubs) — the backend
        then encodes synchronously."""
        return getattr(self.service, "encode_batcher", None)

    def ec_profile(self) -> Dict[str, str]:
        prof = self.service.get_osdmap().erasure_code_profiles.get(
            self.pool.erasure_code_profile)
        return dict(prof or {"plugin": "jerasure", "k": "2", "m": "1"})

    def trace_span(self, name: str, trace_id: int,
                   parent_id: int = 0):
        tracer = getattr(self.service, "tracer", None)
        if tracer is None:
            return None
        return tracer.start(name, trace_id, parent_id)

    @property
    def osd_perf(self):
        """The hosting OSD's perf counters (None under test stubs)."""
        return getattr(self.service, "perf", None)

    @property
    def flight_recorder(self):
        """The hosting OSD's flight recorder (None under test
        stubs) — backends note routing/fault events into it."""
        return getattr(self.service, "flight_recorder", None)

    def call_later(self, delay: float, fn):
        """One-shot cancellable timer via the hosting OSD (EC
        sub-write deadlines); None under hosts without timers."""
        call = getattr(self.service, "call_later", None)
        if call is None:
            return None
        return call(delay, fn)

    def report_laggard(self, osd: int, elapsed: float) -> None:
        """Report a peer that sat on a sub-write past its deadline."""
        rep = getattr(self.service, "report_laggard", None)
        if rep is not None:
            rep(osd, elapsed)

    def note_object_recovered(self, oid: str, version) -> None:
        """A recovery push committed on THIS shard: durable missing-set
        update (reference recover_got)."""
        with self.lock:
            if self.missing.is_missing(oid):
                self.missing.got(oid, tuple(version))
                self._persist_pgmeta()

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------
    def _meta_obj(self) -> GHObject:
        return GHObject(PGMETA_OID, self.own_shard)

    def _ensure_collections(self) -> None:
        """Create this OSD's collection(s) for the PG.  EC shards are
        positional so the collection is created lazily per interval;
        all possible shard collections are created up front so a
        position change never races transaction application."""
        txn = Transaction()
        made = False
        if self.pool.is_erasure():
            for s in range(self.pool.size):
                if not self.store.collection_exists(self.coll_of(s)):
                    txn.create_collection(self.coll_of(s))
                    made = True
        else:
            if not self.store.collection_exists(self.coll_of(-1)):
                txn.create_collection(self.coll_of(-1))
                made = True
        if made:
            self.store.queue_transactions([txn], op="pg_create")

    def _append_pgmeta_ops(self, txn: Transaction) -> None:
        import json as _json
        kvs = {INFO_KEY: self.log.encode(),
               MISSING_KEY: _json.dumps(
                   self.missing.to_dict()).encode(),
               SPLIT_KEY: str(self._last_split_pgnum).encode(),
               STRAY_SHARD_KEY: str(self._stray_shard).encode(),
               SPLIT_SRC_KEY: str(self._split_source_shard).encode(),
               MERGE_SRC_KEY: _json.dumps(
                   self._merge_source_shards).encode(),
               SPLIT_ADOPTED_KEY:
                   (b"1" if self._split_adopted else b"0")}
        txn.omap_setkeys(self.coll, self._meta_obj(), kvs)

    def _persist_pgmeta(self) -> None:
        if self.pool.is_erasure() and self.own_shard < 0:
            return    # not in the acting set (late completion after an
                      # interval change): no home shard to persist to
        txn = Transaction()
        self._append_pgmeta_ops(txn)
        self.store.queue_transactions([txn], op="pgmeta")

    def _load_pgmeta(self) -> None:
        """Restart is resume (reference OSD::init loads PGs from disk):
        the log (and through it last_update) and the persistent missing
        set come back from omap — a shard that adopted log entries but
        never finished recovering them must still know it lacks the
        data (reference PGLog::read_log_and_missing)."""
        import json as _json
        for s in ([self.own_shard] if not self.pool.is_erasure()
                  else range(self.pool.size)):
            coll = self.coll_of(s if self.pool.is_erasure() else -1)
            obj = GHObject(PGMETA_OID, s if self.pool.is_erasure() else -1)
            try:
                omap = self.store.omap_get(coll, obj)
            except FileNotFoundError:
                continue
            data = omap.get(INFO_KEY)
            if data:
                log = PGLog.decode(data)
                if log.last_update > self.log.last_update:
                    self.log = log
                    raw = omap.get(MISSING_KEY)
                    if raw:
                        self.missing = MissingSet.from_dict(
                            _json.loads(raw.decode()))
            raw = omap.get(SPLIT_KEY)
            if raw:                  # persisted split anchor wins: a
                self._last_split_pgnum = int(raw)  # restart must still
            raw = omap.get(STRAY_SHARD_KEY)        # split past growth
            if raw and int(raw) >= 0:
                self._stray_shard = int(raw)
            raw = omap.get(SPLIT_SRC_KEY)
            if raw and int(raw) >= 0:
                self._split_source_shard = int(raw)
            raw = omap.get(MERGE_SRC_KEY)
            if raw:
                merged = _json.loads(raw.decode())
                if merged:
                    self._merge_source_shards = sorted(
                        set(self._merge_source_shards) | set(merged))
            raw = omap.get(SPLIT_ADOPTED_KEY)
            if raw == b"1":
                self._split_adopted = True

    # ------------------------------------------------------------------
    # PG split (reference OSDMonitor pg_num pool-set -> OSD::split_pgs,
    # osd/OSD.cc:8926, PG.cc split_colls / PGLog::split_out_child)
    # ------------------------------------------------------------------
    def maybe_split(self, osdmap: OSDMap) -> None:
        """If the pool's pg_num grew past our split anchor, rehash our
        objects into the child PGs this seed feeds (created locally
        even when we are not in a child's acting set — such children
        are split STRAYS that announce themselves to the child's
        primary and serve as recovery sources until purged).

        Runs on every replica independently; all replicas move the
        same oids and produce identical child logs (split_out keeps
        head/tail), so child peering elections are trivial.  Idempotent
        and anchored on the persisted split pg_num, so growth while an
        OSD was down still splits on restart."""
        pool = osdmap.get_pool(self.pgid.pool)
        if pool is None:
            return
        new = pool.pg_num
        with self.lock:
            old = self._last_split_pgnum
            if new < old:
                # a merge shrank the pool under us: follow the anchor
                # down (every OSD, child-holder or not) so future
                # growth re-splits from the new baseline
                self._last_split_pgnum = old = new
                self._persist_pgmeta()
            if new <= old:
                return
            if self.pgid.seed >= old:
                # we ARE a child of this growth (or fresh): just move
                # the anchor forward
                self._last_split_pgnum = new
                self._persist_pgmeta()
                return
            from .osdmap import pg_split_children
            children = pg_split_children(self.pgid.seed, old, new)
        # create the children OUTSIDE our lock (ensure_pg may take
        # other PG locks — no pg->pg nesting); their collections must
        # exist before the move transaction below lands
        child_pgs = []
        for c in children:
            child = self.service.ensure_pg(PGid(self.pgid.pool, c))
            if child is not None:
                child_pgs.append((c, child))
        with self.lock:
            if self._last_split_pgnum != old:
                return               # raced a concurrent map advance

            def rehash(oid: str) -> int:
                # snapshot clones ride with their head, matching
                # client targeting (head/clone colocation invariant)
                return osdmap.object_locator_to_pg(
                    oid.split("@", 1)[0], self.pgid.pool).seed

            moves: Dict[int, List[str]] = {}
            for oid in self.backend.list_objects():
                if oid == PGMETA_OID:
                    continue
                target = rehash(oid)
                if target != self.pgid.seed:
                    moves.setdefault(target, []).append(oid)
            # snapshot BEFORE the destructive in-place work below
            # (split_out strips log entries/reqids, missing.rm drops
            # tracking): if the move txn fails — e.g. a replica-op
            # delete raced the object listing — EVERYTHING rolls back
            # so the next map advance retries the split instead of
            # stranding parent data with a half-stripped log
            import copy
            log_snapshot = copy.deepcopy(self.log)
            missing_snapshot = copy.deepcopy(self.missing)
            prev_adopted = self._split_adopted
            # split the LOG by rehash too (covers deleted/missing oids
            # that no longer exist as store objects)
            entry_moves: Dict[int, set] = {c: set() for c in children}
            for e in self.log.entries:
                t = rehash(e.oid)
                if t != self.pgid.seed and t in entry_moves:
                    entry_moves[t].add(e.oid)
            child_logs = {c: self.log.split_out(entry_moves[c])
                          for c in children}
            # child missing entries follow their objects
            child_missing: Dict[int, Dict[str, tuple]] = {}
            for oid in list(self.missing.items.keys()):
                target = rehash(oid)
                if target != self.pgid.seed:
                    need, have = self.missing.items[oid]
                    child_missing.setdefault(target, {})[oid] = (need,
                                                                 have)
                    self.missing.rm(oid)
            shard = self.own_shard
            my_head = self.log.last_update
            self._last_split_pgnum = new
            self._split_adopted = True   # we answered our own split
            txn = Transaction()
            for c, oids in moves.items():
                ccoll = self._child_coll(c, shard)
                for oid in oids:
                    txn.collection_move_rename(
                        self.coll, GHObject(oid, shard),
                        ccoll, GHObject(oid, shard))
            self._append_pgmeta_ops(txn)
            # apply UNDER the lock: no client write can interleave
            # between the in-memory split and its durable txn, so the
            # rollback above can never clobber a concurrent append
            try:
                self.store.queue_transactions([txn], op="pg_split")
            except Exception as e:
                self.log = log_snapshot
                self.missing = missing_snapshot
                self._last_split_pgnum = old
                self._split_adopted = prev_adopted
                Dout("osd").dwarn(
                    "pg %s split %d->%d move txn failed (%r); split "
                    "state rolled back, will retry on next map "
                    "advance", self.pgid, old, new, e)
                return
        for c, child in child_pgs:
            child.adopt_split(my_head, child_logs.get(c),
                              child_missing.get(c, {}), new, shard)

    def _child_coll(self, seed: int, shard: int) -> str:
        base = f"{self.pgid.pool}.{seed:x}"
        return base if shard < 0 else f"{base}s{shard}"

    def adopt_split(self, parent_head, child_log, missing: Dict,
                    split_pgnum: int, parent_shard: int) -> None:
        """Child side of maybe_split (same OSD): adopt the parent's
        log head (and its entries for our objects), inherit missing
        entries for objects the parent shard itself lacked, and record
        the shard identity in case we are a stray here."""
        with self.lock:
            if child_log is not None and \
                    child_log.last_update > self.log.last_update:
                self.log = child_log
            elif parent_head > self.log.last_update:
                self.log = PGLog.from_dict(
                    {"last_update": list(parent_head),
                     "tail": list(parent_head), "entries": []})
            for oid, (need, have) in missing.items():
                self.missing.add(oid, tuple(need),
                                 tuple(have) if have else None)
            self._last_split_pgnum = max(self._last_split_pgnum,
                                         split_pgnum)
            self._split_adopted = True
            if parent_shard >= 0:
                self._split_source_shard = parent_shard
            if self.whoami not in [o for o in self.acting
                                   if o is not None]:
                self._stray_shard = parent_shard
            self._persist_pgmeta()

    def adopt_merge(self, child_log, child_missing,
                    merge_pgnum: int,
                    merged_locs: Optional[Dict[str, int]] = None,
                    merge_epoch: int = 0) -> None:
        """Parent side of a PG merge (reference PG::merge_from): the
        child's objects were just folded into our collection by the
        OSD.  The child's log entries are REBASED onto our log with
        fresh versions — deterministically (same child log + same
        parent head on every holder), so merging replicas produce an
        identical advanced log and ordinary peering/catch-up teaches
        everyone else: a parent holder with no child collection (e.g.
        the new primary) sees a peer with a newer head, elects its
        log, and log-recovers the merged objects."""
        with self.lock:
            # the rebase epoch is pinned to the epoch the POOL shrank
            # (passed from the map that carries the shrink — the PG's
            # own pool snapshot may be stale here): every holder uses
            # the same value no matter when it merges, so a late
            # merger (down during the shrink) produces versions BEHIND
            # the cluster's and gets corrected by catch-up instead of
            # overriding fresher state
            merge_epoch = merge_epoch or self.pool.pg_num_epoch \
                or self.epoch
            acting_here = self.whoami in [o for o in self.acting
                                          if o is not None]
            stray_here = not acting_here
            if stray_here:
                # a NON-acting holder must not rebase the child log
                # into its (possibly empty) parent log: a fresh PG's
                # (0,0) base would yield a high-epoch head carrying
                # ONLY the child's history, win the next election, and
                # backfill everyone else DOWN to two objects.  A stray
                # just serves its folded data (stray sources).
                child_log = None
            seq = max(self.log.last_update[1],
                      self._last_assigned[1])
            if child_log is not None:
                for e in child_log.entries:
                    seq += 1
                    v = (merge_epoch, seq)
                    ne = LogEntry(e.op, e.oid, v,
                                  prior_version=(0, 0),
                                  reqid=e.reqid)
                    self.log.entries.append(ne)
                    if e.reqid is not None:
                        self.log.reqids[e.reqid] = v
                if child_log.entries:
                    self.log.last_update = (merge_epoch, seq)
                    self._last_assigned = (merge_epoch, seq)
                # reqids of entries the child already trimmed still
                # guard against very old client resends
                for reqid, ver in child_log.reqids.items():
                    self.log.reqids.setdefault(reqid, ver)
            if child_missing is not None:
                for oid, (need, have) in child_missing.items.items():
                    self.missing.add(oid, tuple(need),
                                     tuple(have) if have else None)
            self._last_split_pgnum = min(self._last_split_pgnum,
                                         merge_pgnum)
            merged_locs = merged_locs or {}
            shards = {s for s in merged_locs.values() if s >= 0}
            if stray_here and merged_locs:
                # we hold merged data without being in the parent's
                # acting set: serve as a stray source until purged
                # (same machinery as split strays; for EC the folded
                # chunks keep their CHILD shard identity)
                if shards:
                    self._stray_shard = sorted(shards)[0]
            elif shards:
                # EC acting member: a merge may fold chunks from
                # SEVERAL children, each at its own CHILD acting
                # position.  Any position other than ours means our
                # position data is missing until recovery
                # reconstructs it, while each folded shard serves as
                # a shard-qualified recovery source — the split audit
                # machinery in reverse (reference merge_from + the
                # distinguished-position rule of ecbackend.rst; chunk
                # bytes are portable between PGs because shard s of
                # an object encodes identically wherever it is
                # placed).  Audit once per DISTINCT folded shard —
                # including one that equals own_shard (its audit is
                # the own-position existence check) — so mispositioned
                # chunks are caught now, not deferred to scrub.
                self._merge_source_shards = sorted(
                    set(self._merge_source_shards) | shards)
                foreign = [s for s in sorted(shards)
                           if s != self.own_shard]
                if foreign:
                    self._split_source_shard = foreign[0]
                osdmap_now = self.service.get_osdmap()
                for s in sorted(shards):
                    self._audit_split_shard(osdmap_now, src=s)
            self._persist_pgmeta()
            if self.is_primary():
                # our log advanced: re-peer so activation pushes the
                # rebased entries to every member (they mark missing
                # and recovery fills them in)
                self.state = STATE_PEERING
                self._peer_notifies.clear()
                self._start_peering()
            else:
                # tell the primary we are ahead: the stray-notify
                # ACTIVE path re-peers when our head outruns its log
                self._merge_notify_pending = True

    def maybe_announce_merge(self, osdmap: OSDMap) -> None:
        """Acting member after a merge: announce our advanced log to
        the primary (the stray-notify handler's head comparison
        triggers its re-peer).  Called from map advance + the OSD
        tick until sent."""
        with self.lock:
            if not getattr(self, "_merge_notify_pending", False):
                return
            _, _, acting, primary = osdmap.pg_to_up_acting_osds(
                self.pgid)
            if primary is None or primary == self.whoami:
                self._merge_notify_pending = False
                return
            auth = self._authoritative_objects()
            objects = {oid: list(auth.get(oid, (0, 0)))
                       for oid in self.backend.list_objects()
                       if oid != PGMETA_OID}
            msg = MOSDPGNotify(
                pgid=str(self.pgid), shard=self.own_shard,
                from_osd=self.whoami,
                epoch=osdmap.epoch, log=self.log.to_dict(),
                missing=self.missing.to_dict(), stray=True,
                objects=objects,
                stray_shard=self._stray_shard
                if self._stray_shard >= 0 else self.own_shard,
                split_adopted=self._split_adopted)
            self._merge_notify_pending = False
        self.service.send_osd(primary, msg)

    # -- stray side ----------------------------------------------------
    def is_stray(self) -> bool:
        with self.lock:
            return self.whoami not in [o for o in self.acting
                                       if o is not None]

    def maybe_notify_stray(self, osdmap: OSDMap) -> None:
        """Announce our data to the PG's current primary (reference
        strays notify the primary via past-interval queries; here the
        stray speaks first).  Called on map advance and from the OSD
        tick until the primary purges us."""
        with self.lock:
            if self.whoami in [o for o in self.acting if o is not None]:
                return
            _, _, acting, primary = osdmap.pg_to_up_acting_osds(
                self.pgid)
            if primary is None or primary == self.whoami:
                return
            # advertise what we can physically SERVE: on-disk objects
            # only (our own missing set covers log-adopted objects we
            # never recovered — offering those would send recovery to
            # a holder with no data, review finding r3)
            auth = self._authoritative_objects()
            objects = {oid: list(auth.get(oid, (0, 0)))
                       for oid in self.backend.list_objects()
                       if oid != PGMETA_OID}
            msg = MOSDPGNotify(
                pgid=str(self.pgid), shard=-1, from_osd=self.whoami,
                epoch=osdmap.epoch, log=self.log.to_dict(),
                missing=self.missing.to_dict(), stray=True,
                objects=objects, stray_shard=self._stray_shard,
                split_adopted=self._split_adopted)
        self.service.send_osd(primary, msg)

    def handle_pg_remove(self, msg) -> None:
        """The current primary no longer needs our stray copy: delete
        it (reference MOSDPGRemove -> PG removal)."""
        with self.lock:
            osdmap = self.service.get_osdmap()
            _, _, acting, primary = osdmap.pg_to_up_acting_osds(
                self.pgid)
            if msg.from_osd != primary:
                return               # stale sender
            if self.whoami in [o for o in acting if o is not None]:
                return               # we're acting: never self-delete
            txn = Transaction()
            if self.pool.is_erasure():
                for s in range(self.pool.size):
                    if self.store.collection_exists(self.coll_of(s)):
                        txn.remove_collection(self.coll_of(s))
            else:
                if self.store.collection_exists(self.coll_of(-1)):
                    txn.remove_collection(self.coll_of(-1))
            self.store.queue_transactions([txn], op="pg_delete")
            self.state = STATE_INACTIVE
            self.log = PGLog()
            self.missing = MissingSet()
        self.service.forget_pg(self.pgid)

    def _audit_split_shard(self, osdmap: OSDMap,
                           src: int = None) -> None:
        """EC acting member holding chunks from a foreign shard
        position (split child whose chunks came from parent shard
        ``_split_source_shard``, or merge parent that folded a child
        shard ``src``): our acting POSITION may differ — position
        data we don't physically hold is missing (recoverable by
        decode), while the chunks we do hold are advertised to the
        primary as a shard-qualified source.  Idempotent
        (existence-checked), so re-running on every interval is safe
        and converges to a no-op once recovery lands our position's
        chunks."""
        own = self.own_shard
        if own < 0:
            return
        audited = 0
        for oid, ver in self._authoritative_objects().items():
            obj = GHObject(oid, own)
            if not self.store.exists(self.coll_of(own), obj):
                if not self.missing.is_missing(oid):
                    self.missing.add(oid, ver, None)
                    audited += 1
        if audited:
            self._persist_pgmeta()
        if src is None:
            src = self._split_source_shard
        if src == own:
            return                   # lucky position match: data home
        objects = {}
        try:
            for oid in self.store.collection_list(self.coll_of(src)):
                name = oid.oid if hasattr(oid, "oid") else str(oid)
                if name != PGMETA_OID:
                    objects[name] = None
        except FileNotFoundError:
            return
        if not objects:
            return
        versions = self._authoritative_objects()
        objects = {o: list(versions.get(o, (0, 0)))
                   for o in objects}
        _, _, _, primary = osdmap.pg_to_up_acting_osds(self.pgid)
        if primary is None:
            return
        msg = MOSDPGNotify(
            pgid=str(self.pgid), shard=-1, from_osd=self.whoami,
            epoch=osdmap.epoch, log=self.log.to_dict(),
            missing=self.missing.to_dict(), stray=True,
            objects=objects, stray_shard=src,
            split_adopted=self._split_adopted)
        if primary == self.whoami:
            self._handle_stray_notify(msg)
        else:
            self.service.send_osd(primary, msg)

    # -- primary side --------------------------------------------------
    def extra_recovery_sources(self, oid: str):
        """Stray holders that can serve ``oid`` (shard, osd) — extends
        the backends' acting-set source selection during post-split
        recovery."""
        out = []
        for osd, objs in self._stray_sources.items():
            if oid in objs:
                nd = self._stray_notifies.get(osd, {})
                out.append((nd.get("stray_shard", -1), osd))
        return out

    def _maybe_purge_strays(self) -> None:
        """Once the acting set is whole, retire every stray copy
        (reference: strays are removed after peering declares them
        unneeded).  "Whole" means FULLY clean — no missing objects, no
        acting-set holes, full pool size: purging while a position is
        a hole would delete the only redundant copy and turn the next
        failure into data loss."""
        if not self.is_primary() or self.state != STATE_ACTIVE:
            return
        if self.num_missing() > 0:
            return
        alive = [o for o in self.acting if o is not None]
        if None in self.acting or len(alive) < self.pool.size:
            return
        acting = {o for o in self.acting if o is not None}
        for osd in list(self._stray_notifies):
            if osd in acting:        # mispositioned acting member:
                continue             # never remove, it IS the PG
            self.service.send_osd(osd, MOSDPGRemove(
                pgid=str(self.pgid), from_osd=self.whoami,
                epoch=self.epoch))
        self._stray_notifies.clear()
        self._stray_sources.clear()

    # ------------------------------------------------------------------
    # map / interval handling (reference PG::handle_advance_map)
    # ------------------------------------------------------------------
    def advance_map(self, osdmap: OSDMap) -> None:
        with self.lock:
            pool = osdmap.get_pool(self.pgid.pool)
            if pool is None:
                return
            self.pool = pool
            up, up_p, acting, acting_p = \
                osdmap.pg_to_up_acting_osds(self.pgid)
            if acting == self.acting and self.state != STATE_INACTIVE:
                return                   # same interval
            prev_shard = self.own_shard  # before acting changes
            self.up, self.acting = up, acting
            self.primary_osd = acting_p
            self.interval_start = osdmap.epoch
            self.backend.on_change()
            self.scrubber.reset()
            self._peer_notifies.clear()
            self.peer_missing.clear()
            self.recovering.clear()
            # NOTE: self.missing survives the interval change — it is
            # persistent state ("I adopted log entries whose data I do
            # not have"), not peering scratch.  Clearing it here would
            # let a data-less shard with a current log masquerade as
            # whole after re-peering (reference pg_missing_t is
            # likewise durable, PGLog write_log_and_missing).
            self.waiting_for_degraded.clear()
            # bounce every held client op: the backend just dropped its
            # sub-ops; the client re-targets against the new map and
            # resends, reqid dedup suppressing re-application of
            # anything that already committed (reference: requeue_ops
            # on interval change + osd_reqid_t dup detection)
            # watchers re-register through lingering client ops; in-
            # flight notifies bounce with the other held client ops
            self.watchers.clear()
            for state in self._notifies.values():
                state["timer"].cancel()
            self._notifies.clear()
            held = list(self._client_ops.values())
            self._client_ops.clear()
            self.waiting_for_active.clear()
            self.waiting_for_obj.clear()
            self._evicting.clear()
            self.inflight_writes.clear()
            self._pending_versions.clear()
            for m, conn in held:
                tracked = getattr(m, "tracked", None)
                if tracked is not None:
                    tracked.finish()
                if conn is not None:
                    reply = MOSDOpReply(tid=m.tid, result=-108,
                                        epoch=osdmap.epoch)
                    conn.send_message(reply)
            if self.whoami not in [o for o in acting if o is not None]:
                if self._stray_shard < 0 and prev_shard >= 0:
                    self._stray_shard = prev_shard  # keep EC identity
                # sub-ops parked for a shard assignment that never
                # came are from a dead interval: drop (the primary's
                # new interval re-issues what still matters)
                self.waiting_for_shard.clear()
                self.state = STATE_INACTIVE
                # announce ourselves to the current primary — WITH data
                # (recovery source) or EMPTY (the split-child gate needs
                # an explicit "my ancestry holds nothing" answer or an
                # empty child would wait forever)
                self.maybe_notify_stray(osdmap)
                return
            self._stray_shard = -1       # back in the acting set
            if self.pool.is_erasure():
                if self._split_source_shard >= 0:
                    self._audit_split_shard(osdmap)
                # merge-folded shards are re-audited per distinct
                # source until recovery homes our position's chunks
                for s in self._merge_source_shards:
                    if s != self._split_source_shard:
                        self._audit_split_shard(osdmap, src=s)
            # back in the acting set with a shard collection: apply
            # the backend sub-ops that raced this map (queued by
            # ms_dispatch while own_shard was -1)
            if self.own_shard >= 0 or not self.pool.is_erasure():
                self._ensure_collections()
                while self.waiting_for_shard:
                    self.backend.handle_message(
                        self.waiting_for_shard.popleft())
            self.state = STATE_PEERING
            if self.is_primary():
                self._start_peering()

    def is_primary(self) -> bool:
        return self.primary_osd == self.whoami

    def _other_members(self) -> List[Tuple[int, int]]:
        return [(s, o) for s, o in enumerate(self.acting)
                if o is not None and o != self.whoami]

    def _start_peering(self) -> None:
        """Query every other acting member (reference GetInfo)."""
        others = self._other_members()
        if not others:
            # still routes through the election so the split-child
            # gate and stray adoption apply even to 1-wide acting sets
            self._choose_and_activate()
            return
        for shard, osd in others:
            self.service.send_osd(osd, MOSDPGQuery(
                pgid=str(self.pgid), shard=shard,
                from_osd=self.whoami, epoch=self.epoch))

    # -- peering message handlers --------------------------------------
    def handle_pg_query(self, msg: MOSDPGQuery) -> None:
        with self.lock:
            self.service.send_osd(msg.from_osd, MOSDPGNotify(
                pgid=str(self.pgid), shard=msg.shard,
                from_osd=self.whoami, epoch=self.epoch,
                log=self.log.to_dict(),
                missing=self.missing.to_dict(),
                split_adopted=self._split_adopted))

    def handle_pg_notify(self, msg: MOSDPGNotify) -> None:
        with self.lock:
            if getattr(msg, "stray", False):
                self._handle_stray_notify(msg)
                return
            if not self.is_primary() or self.state != STATE_PEERING:
                return
            self._peer_notifies[msg.shard] = {
                "log": msg.log, "missing": msg.missing,
                "split_adopted": getattr(msg, "split_adopted", False)}
            wanted = {s for s, _ in self._other_members()}
            if wanted <= set(self._peer_notifies):
                self._choose_and_activate()

    def _handle_stray_notify(self, msg: MOSDPGNotify) -> None:
        """A non-acting holder announced data for this PG (split child
        stray or migrated-away copy).  Record it as an election
        candidate + recovery source; purge it once we're whole."""
        if not self.is_primary():
            return
        self._stray_notifies[msg.from_osd] = {
            "log": msg.log, "missing": msg.missing,
            "objects": msg.objects, "stray_shard": msg.stray_shard,
            "split_adopted": getattr(msg, "split_adopted", False)}
        self._stray_sources[msg.from_osd] = {
            oid: tuple(v) for oid, v in msg.objects.items()}
        # an ACTING member can send these too (EC split: mispositioned
        # chunks — see _audit_split_shard): fold its self-reported
        # missing into peer_missing so recovery pushes reach it even
        # when its audit raced our peering round
        for shard, osd_a in enumerate(self.acting):
            if osd_a == msg.from_osd and osd_a != self.whoami:
                ms = self.peer_missing.get(shard) or MissingSet()
                for oid, ent in MissingSet.from_dict(
                        msg.missing).items.items():
                    if not ms.is_missing(oid):
                        ms.add(oid, tuple(ent[0]),
                               tuple(ent[1]) if ent[1] else None)
                self.peer_missing[shard] = ms
        if self.state == STATE_PEERING:
            wanted = {s for s, _ in self._other_members()}
            if wanted <= set(self._peer_notifies):
                self._choose_and_activate()
            return
        if self.state != STATE_ACTIVE:
            return
        stray_head = tuple(msg.log.get("last_update", (0, 0)))
        if stray_head > self.log.last_update:
            # the stray is AHEAD of the elected authority (e.g. an old
            # primary resurfacing): re-run peering to fold it in —
            # terminates because the next election adopts its head
            self.state = STATE_PEERING
            self._peer_notifies.clear()
            self._start_peering()
        elif self.num_missing() == 0:
            self._maybe_purge_strays()
        else:
            self.service.kick_recovery(self)

    def _adopt_stray_objects(self, osd: int, head) -> None:
        """Backfill-style adoption of a stray's authoritative object
        set (mirrors the replica side of handle_pg_log's backfill
        path): our log restarts at the stray's head and every object
        we lack at its version becomes missing, recoverable from the
        stray via extra_recovery_sources."""
        objs = self._stray_sources.get(osd, {})
        for oid in self.backend.list_objects():
            if oid == PGMETA_OID:
                continue
            if oid not in objs:
                obj = GHObject(oid, self.own_shard)
                txn = Transaction()
                txn.remove(self.coll, obj)
                self.store.queue_transactions([txn],
                                              op="recovery_trim")
        for oid, ver in objs.items():
            oi = self.backend.get_object_info(oid)
            local = oi.version if oi is not None else None
            if local != ver:
                self.missing.add(oid, ver, local)
        self.log = PGLog.from_dict(
            {"last_update": list(head), "tail": list(head),
             "entries": []})
        self._persist_pgmeta()

    def _choose_and_activate(self) -> None:
        """Pick the authoritative log; adopt it if a peer is ahead
        (reference GetLog); then activate (reference Activate).
        Split-children and migrated-away strays participate in the
        election; a child seed refuses to activate empty before its
        ancestry has spoken (the past-intervals stand-in)."""
        best_shard, best_head = None, self.log.last_update
        for shard, nd in self._peer_notifies.items():
            head = tuple(nd["log"]["last_update"])
            if head > best_head:
                best_shard, best_head = shard, head
        best_stray, best_stray_head = None, (0, 0)
        for osd, nd in self._stray_notifies.items():
            head = tuple(nd["log"]["last_update"])
            if head > best_stray_head:
                best_stray, best_stray_head = osd, head
        created = self.pool.created_pg_num or self.pool.pg_num
        # only an ANCESTRY-DERIVED answer lifts the gate: a copy fed
        # by a local parent split (split_adopted, even when empty), or
        # a stray that actually carries history — a random fresh empty
        # copy answering would let a child activate empty while the
        # real data sits with a slower holder
        answered = (self._split_adopted
                    or any(nd.get("split_adopted")
                           for nd in self._peer_notifies.values())
                    or any(nd.get("split_adopted")
                           or tuple(nd["log"]["last_update"]) > (0, 0)
                           for nd in self._stray_notifies.values()))
        if (self.pgid.seed >= created and best_head == (0, 0)
                and not answered):
            # we are a split child and NOBODY in the acting set has
            # data yet: activating now could present an empty PG while
            # the parent's holders still have our objects.  Stay in
            # PEERING; strays self-notify (and re-notify on the OSD
            # tick) until one arrives.
            return
        if best_stray is not None and best_stray_head > best_head:
            self._adopt_stray_objects(best_stray, best_stray_head)
            # _activate's per-peer pass sees our fresh log with tail =
            # head, so every behind peer takes the backfill path
            self._activate()
            return
        if best_shard is not None:
            peer = PGLog.from_dict(self._peer_notifies[best_shard]["log"])
            self.log.merge_authoritative(
                peer.entries, peer.last_update,
                lambda oid, need, have: self.missing.add(oid, need,
                                                         have),
                lambda oid, prior: self._roll_back_local(oid, prior))
            # the authoritative shard may itself lack data for entries
            # it logged (its own persistent missing): those objects are
            # missing everywhere we can't prove otherwise — but for
            # *us* only if we don't have them; our own missing already
            # reflects our state, so nothing more to adopt here.
            self._persist_pgmeta()
        self._activate()

    def _roll_back_local(self, oid: str, prior: Eversion) -> None:
        """Divergent local entry: drop our copy and re-recover it at the
        authoritative version (log-based rollback stand-in; reference
        EC rollback uses per-op rollback info, ecbackend.rst:10-27)."""
        obj = GHObject(oid, self.own_shard)
        if self.store.exists(self.coll, obj):
            txn = Transaction()
            txn.remove(self.coll, obj)
            self.store.queue_transactions([txn],
                                          op="recovery_trim")
        if prior > (0, 0):
            self.missing.add(oid, prior, None)

    def _authoritative_objects(self) -> Dict[str, Eversion]:
        """oid -> version of every live object the primary knows:
        on-disk objects (their OI) overlaid with in-log versions."""
        out: Dict[str, Eversion] = {}
        for oid in self.backend.list_objects():
            if oid == PGMETA_OID:
                continue
            oi = self.backend.get_object_info(oid)
            if oi is not None:
                out[oid] = oi.version
        out.update(self.log.object_versions())
        for oid, (need, _) in list(self.missing.items.items()):
            out[oid] = need
        return out

    def _activate(self) -> None:
        """Primary side: compute per-peer missing, send activation,
        go active (reference PeeringState::Activate).  A peer's
        missing = its self-reported persistent missing (log current,
        data absent) ∪ the log delta we're about to send it."""
        auth_objects = None
        for shard, nd in self._peer_notifies.items():
            peer_head = tuple(nd["log"]["last_update"])
            entries = self.log.entries_since(peer_head)
            osd = self.acting[shard]
            ms = MissingSet.from_dict(nd.get("missing", {}))
            if entries is None:
                # no log overlap: backfill everything
                if auth_objects is None:
                    auth_objects = self._authoritative_objects()
                for oid, ver in auth_objects.items():
                    ms.add(oid, ver, None)
                self.peer_missing[shard] = ms
                self.service.send_osd(osd, MOSDPGLog(
                    pgid=str(self.pgid), shard=shard,
                    from_osd=self.whoami, epoch=self.epoch,
                    last_update=self.log.last_update,
                    backfill={oid: list(ver) for oid, ver
                              in auth_objects.items()}))
            else:
                known: Dict[str, Eversion] = {}
                for e in entries:
                    if e.is_error():
                        continue
                    if e.is_delete():
                        ms.rm(e.oid)
                    else:
                        ms.add(e.oid, e.version, known.get(e.oid))
                    known[e.oid] = e.version
                self.peer_missing[shard] = ms
                self.service.send_osd(osd, MOSDPGLog(
                    pgid=str(self.pgid), shard=shard,
                    from_osd=self.whoami, epoch=self.epoch,
                    last_update=self.log.last_update,
                    entries=[e.to_dict() for e in entries]))
        self.state = STATE_ACTIVE
        self._peer_notifies.clear()
        # pool geometry goes hot NOW, not on the first client write:
        # compile the encode executables and preallocate the device
        # staging rings for this pool's (k, m, stripe) while the
        # client is still discovering the map (background thread,
        # idempotent per geometry)
        warm = getattr(self.backend, "prewarm_geometry", None)
        if warm is not None:
            try:
                warm()
            except Exception:
                pass
        self._requeue_waiting()
        self.service.pg_activated(self)

    def handle_pg_log(self, msg: MOSDPGLog) -> None:
        """Replica side: adopt the authoritative log and go active
        (reference PG::RecoveryState::ReplicaActive)."""
        with self.lock:
            if self.pool.is_erasure() and self.own_shard < 0:
                # our map hasn't placed us in this PG yet (activation
                # raced the map); drop — the primary's stuck-peering
                # retry re-sends once our map catches up
                return
            if msg.backfill is not None:
                # authoritative object set: drop extras, note that the
                # primary will push everything (stale copies get
                # overwritten by pushes)
                auth = {oid: tuple(v) for oid, v in msg.backfill.items()}
                local: Dict[str, Eversion] = {}
                for oid in self.backend.list_objects():
                    if oid == PGMETA_OID:
                        continue
                    if oid not in auth:
                        obj = GHObject(oid, self.own_shard)
                        txn = Transaction()
                        txn.remove(self.coll, obj)
                        self.store.queue_transactions(
                            [txn], op="recovery_trim")
                    else:
                        oi = self.backend.get_object_info(oid)
                        if oi is not None:
                            local[oid] = oi.version
                self.log = PGLog.from_dict(
                    {"last_update": list(msg.last_update),
                     "tail": list(msg.last_update), "entries": []})
                # durable missing: the log head we just adopted claims
                # objects our store lacks — record that, or an interval
                # change would strand them (see advance_map note)
                self.missing = MissingSet()
                for oid, ver in auth.items():
                    if local.get(oid) != ver:
                        self.missing.add(oid, ver, local.get(oid))
            else:
                entries = [LogEntry.from_dict(e) for e in msg.entries]
                self.log.merge_authoritative(
                    entries, msg.last_update,
                    lambda oid, need, have: self.missing.add(oid, need,
                                                             have),
                    lambda oid, prior: self._roll_back_local(oid,
                                                             prior))
                # apply deletes that happened while we were away
                for e in entries:
                    if e.is_delete():
                        obj = GHObject(e.oid, self.own_shard)
                        if self.store.exists(self.coll, obj):
                            txn = Transaction()
                            txn.remove(self.coll, obj)
                            self.store.queue_transactions(
                                [txn], op="recovery_trim")
                        self.missing.rm(e.oid)
            self._persist_pgmeta()
            self.state = STATE_ACTIVE
            self._requeue_waiting()

    def _requeue_waiting(self) -> None:
        while self.waiting_for_active:
            msg, conn = self.waiting_for_active.popleft()
            self._do_op(msg, conn)

    @staticmethod
    def _mark_waiting(msg, event: str) -> None:
        """Stamp a park on the op's tracker timeline; ops whose latest
        event is a wait surface through dump_blocked_ops (reference
        OpTracker blocked-op accounting)."""
        tracked = getattr(msg, "tracked", None)
        if tracked is not None:
            tracked.mark_event(event)

    # ------------------------------------------------------------------
    # client op execution (reference do_request -> do_op -> do_osd_ops)
    # ------------------------------------------------------------------
    def do_request(self, msg: MOSDOp, conn) -> None:
        with self.lock:
            msg.stamp_hop("pg_locked")
            if getattr(self, "_merged_away", False):
                # this PG was folded into its split parent (pg merge):
                # the client refreshes its map and re-targets
                self._reply(conn, msg, -108, [])
                return
            if not self.is_primary():
                # client raced a map change: reply with our epoch so it
                # refreshes and resends (reference resend-on-new-map)
                self._reply(conn, msg, -108, [])   # -ESHUTDOWN marker
                return
            self._client_ops[(msg.client, msg.tid)] = (msg, conn)
            if self.state != STATE_ACTIVE:
                self._mark_waiting(msg, "waiting for active")
                self.waiting_for_active.append((msg, conn))
                return
            self._do_op(msg, conn)

    def _get_snapset(self, oid: str):
        """-> (SnapSet | None, came_from_snapdir).  The SnapSet lives
        on the head, or on the snapdir companion while the head is
        deleted (reference find_object_context's snapdir path)."""
        from .snaps import SS_ATTR, SnapSet, snapdir_oid
        for target, from_sd in ((oid, False),
                                (snapdir_oid(oid), True)):
            try:
                buf = self.store.getattr(
                    self.coll, GHObject(target, self.own_shard),
                    SS_ATTR)
                return SnapSet.decode(buf), from_sd
            except (FileNotFoundError, KeyError, ValueError):
                continue
        return None, False

    def _is_degraded(self, oid: str) -> bool:
        if self.missing.is_missing(oid):
            return True
        return any(ms.is_missing(oid)
                   for s, ms in self.peer_missing.items()
                   if self.acting[s] is not None)

    @staticmethod
    def _op_is_write(op) -> bool:
        if op.op == "call":
            # method flags decide (reference CLS_METHOD_WR)
            from ..objclass import call_is_write
            return call_is_write(op.name)
        return op.op in WRITE_OPS

    def _do_op(self, msg: MOSDOp, conn) -> None:
        has_write = any(self._op_is_write(op) for op in msg.ops)
        oid = msg.oid
        # reference osd_client_message_size_cap: bound a single op's
        # payload before any of it is staged
        payload = sum(len(op.data) for op in msg.ops if op.data)
        cap = self.conf["osd_client_message_size_cap"]
        if cap and payload > cap:
            self._reply(conn, msg, -90, [])      # EMSGSIZE
            return
        if "@" in oid and not oid.startswith(".pgls."):
            # '@' is the snapshot-object namespace (oid@snap,
            # oid@snapdir): a client object named 'foo@10' would
            # collide with clones — be hidden from listings, served
            # for snap reads, even deleted by the trimmer.  EINVAL,
            # like the reference reserving internal namespaces.
            self._reply(conn, msg, -22, [])
            return
        if not oid.startswith(".pgls."):
            # misdirected op (client targeted us from a pre-split map):
            # bounce so it refreshes and re-targets the child PG
            # (reference PrimaryLogPG::do_op "misdirected op" check)
            target = self.service.get_osdmap().object_locator_to_pg(
                oid, self.pgid.pool)
            if target.seed != self.pgid.seed:
                self._client_ops.pop((msg.client, msg.tid), None)
                self._reply(conn, msg, -108, [])
                return
        if any(op.op in ("cache_flush", "cache_evict")
               for op in msg.ops):
            # explicit tier maintenance (reference
            # CEPH_OSD_OP_CACHE_FLUSH/CACHE_EVICT): addressed AT the
            # cache pool, never promoted
            self._do_cache_op(msg, conn)
            return
        if not oid.startswith(".pgls.") and \
                self._maybe_handle_cache(msg, conn, has_write):
            return                       # parked / promoted / rejected
        if has_write and self.scrubber.write_blocked():
            # scrub snapshots must describe one committed state; new
            # writes wait for the round (reference write blocking on
            # the scrubbed chunk)
            self._mark_waiting(msg, "waiting for scrub")
            self.waiting_for_scrub.append((msg, conn))
            return
        if has_write and self._is_degraded(oid):
            # block until recovered (reference wait_for_degraded_object)
            self._mark_waiting(msg, "waiting for degraded object")
            self.waiting_for_degraded.setdefault(oid, deque()).append(
                (msg, conn))
            self.service.kick_recovery(self)
            return
        if has_write:
            if any(op.op == "copy_from" for op in msg.ops):
                self._start_copy_from(msg, conn)
                return
            if oid in self.inflight_writes and \
                    not self._can_pipeline(msg, oid):
                self._mark_waiting(msg, "waiting for object")
                self.waiting_for_obj.setdefault(oid, deque()).append(
                    (msg, conn))
                return
            self._do_write(msg, conn)
        else:
            if self.missing.is_missing(oid):
                # the primary's own copy is unreadable until recovery
                # (reference wait_for_unreadable_object)
                self._mark_waiting(msg, "waiting for degraded object")
                self.waiting_for_degraded.setdefault(
                    oid, deque()).append((msg, conn))
                self.service.kick_recovery(self)
                return
            self._do_reads(msg, conn)

    # ------------------------------------------------------------------
    # cache tiering (reference PrimaryLogPG::maybe_handle_cache_detail,
    # PrimaryLogPG.cc:2700, called from do_op at :8084): this PG is the
    # CACHE pool; misses promote from the base pool, writes are marked
    # dirty for the tier agent to flush, deletes write through to the
    # base (in place of the reference's whiteouts — simpler, same
    # no-resurrection guarantee for the model checker)
    # ------------------------------------------------------------------
    CACHE_DIRTY_ATTR = "cache_dirty"     # user-ns xattr on dirty heads

    def _maybe_handle_cache(self, msg: MOSDOp, conn,
                            has_write: bool) -> bool:
        """True when the op was consumed (parked, being promoted, or
        rejected); False lets it continue down the normal path."""
        pool = self.pool
        if not pool.is_tier() or pool.cache_mode == "none":
            return False
        oid = msg.oid
        if "@" in oid:
            return False                 # snap namespace: no tiering
        if pool.cache_mode == "readonly" and has_write:
            self._reply(conn, msg, -30, [])      # EROFS
            return True
        if oid in self._flushing:
            # a flush holds the object stable; ops resume when the
            # clean-mark commits (its done callback drains the queue)
            self.waiting_for_obj.setdefault(oid, deque()).append(
                (msg, conn))
            return True
        if oid in self._evicting:
            # mid-evict window (internal delete in flight): a read
            # probing now finds the object gone but can't promote
            # (the delete holds inflight_writes) and would ENOENT an
            # object that still exists in the base — park until the
            # evict commits, then the re-run promotes it back
            # (reference: ops wait on the blocked object context
            # during evict)
            self.waiting_for_obj.setdefault(oid, deque()).append(
                (msg, conn))
            return True
        if not getattr(msg, "_promote_checked", False) and \
                self.backend.get_object_info(oid) is None and \
                not self._is_degraded(oid) and \
                oid not in self.inflight_writes:
            # absent AND not merely unrecovered: a backfilling primary
            # that promoted every locally-missing object would install
            # stale base copies over acked cache state — degraded
            # objects instead fall through to the recovery parking in
            # _do_op (reference waits for recovery before promote)
            self._start_promote(msg, conn)
            return True
        if pool.cache_mode == "writeback" and \
                any(op.op == "delete" for op in msg.ops) and \
                not getattr(msg, "_base_deleted", False):
            self._start_base_delete(msg, conn)
            return True
        return False

    def _do_cache_op(self, msg: MOSDOp, conn) -> None:
        """cache_flush / cache_evict client ops (reference
        CEPH_OSD_OP_CACHE_FLUSH/CACHE_EVICT in do_osd_ops): operator-
        driven tier maintenance, e.g. `rados cache-flush-evict-all`."""
        if not self.pool.is_tier():
            self._reply(conn, msg, -22, [])
            return
        oid = msg.oid
        if self.backend.get_object_info(oid) is None:
            self._reply(conn, msg, -2, [])
            return
        try:
            self.store.getattr(self.coll,
                               GHObject(oid, self.own_shard),
                               "u_" + self.CACHE_DIRTY_ATTR)
            dirty = True
        except (FileNotFoundError, KeyError):
            dirty = False
        if msg.ops[0].op == "cache_flush":
            if not dirty:
                self._reply(conn, msg, 0, [])
                return
            if not self._flush_object(oid):
                self._reply(conn, msg, -16, [])      # EBUSY
                return
            # park; the flush's clean-mark re-runs us and the now-
            # clean object answers 0
            self.waiting_for_obj.setdefault(oid, deque()).append(
                (msg, conn))
        else:                            # cache_evict
            if dirty:
                self._reply(conn, msg, -16, [])      # flush first
                return
            ok = self._evict_object(oid)
            self._reply(conn, msg, 0 if ok else -16, [])

    def _cache_reenter(self, entries: List[Tuple]) -> None:
        """Re-run ops after an async cache step (lock held); each is
        stamped so the presence probe doesn't loop on objects that
        exist nowhere.  One op's failure must not starve the rest —
        a leaked waiter is a client op wedged until its timeout."""
        for m, c in entries:
            m._promote_checked = True
            try:
                self._do_op(m, c)
            except Exception:
                import traceback
                traceback.print_exc()
                try:
                    self._client_ops.pop((m.client, m.tid), None)
                    self._reply(c, m, -5, [])
                except Exception:
                    pass

    def _start_promote(self, msg: MOSDOp, conn) -> None:
        """Fetch the object from the base pool and install it in the
        cache (a clean, replicated, logged internal write), then
        re-run the op (reference promote_object)."""
        oid = msg.oid
        waiters = self._promoting.get(oid)
        if waiters is not None:
            waiters.append((msg, conn))
            return
        self._promoting[oid] = []
        base_pool = self.pool.tier_of
        base = self.service.get_osdmap().pools.get(base_pool)
        base_has_omap = base is not None and not base.is_erasure()

        def fetch() -> None:
            data = attrs = None
            omap = {}
            err = 0
            try:
                io = self.service.objecter_ioctx(base_pool)
                data = io.read(oid)
                attrs = io.getxattrs(oid)
                if base_has_omap:
                    omap = io.omap_get(oid)
            except Exception as e:
                errno = getattr(e, "errno", 0) or 5
                if errno != 2:
                    err = errno          # base unreachable: fail ops
                data = None
            with self.lock:
                waiting = self._promoting.pop(oid, [])
                all_ops = [(msg, conn)] + waiting
                if not self.is_primary() or \
                        self.state != STATE_ACTIVE:
                    # lost the PG mid-promote (thrash failover): a
                    # non-primary install would fan out split-brain
                    # sub-writes; bounce the clients to re-target
                    for m, c in all_ops:
                        self._client_ops.pop((m.client, m.tid), None)
                        self._reply(c, m, -108, [])
                    return
                if err:
                    for m, c in all_ops:
                        self._client_ops.pop((m.client, m.tid), None)
                        self._reply(c, m, -err, [])
                    return
                if data is None or \
                        self.backend.get_object_info(oid) is not None \
                        or oid in self.inflight_writes:
                    # nothing to promote (absent in base too, or a
                    # racing write created it): just re-run
                    self._cache_reenter(all_ops)
                    return
                mut = Mutation()
                mut.writes.append((0, data))
                mut.truncate = len(data)
                for k, v in attrs.items():
                    if k != self.CACHE_DIRTY_ATTR:
                        mut.attrs[k] = v
                mut.omap_set.update(omap)
                self.cache_promotes += 1
                try:
                    self._submit_internal(
                        oid, mut,
                        on_done=lambda res: self._cache_reenter(
                            all_ops))
                except Exception:
                    # install failed outright: answer every waiter
                    # rather than leaking them until client timeout
                    for m, c in all_ops:
                        self._client_ops.pop((m.client, m.tid), None)
                        self._reply(c, m, -5, [])

        threading.Thread(target=fetch, name="cache-promote",
                         daemon=True).start()

    def _start_base_delete(self, msg: MOSDOp, conn) -> None:
        """Write-through delete: remove the base copy BEFORE the cache
        delete is applied/acked, so a later miss can never resurrect a
        deleted object from the base pool.  ``_base_deleting`` fences
        the tier agent — a flush racing this window would rewrite the
        base copy we just removed (resurrection via flush)."""
        base_pool = self.pool.tier_of
        self._base_deleting.add(msg.oid)

        def run() -> None:
            try:
                self.service.objecter_ioctx(base_pool).remove(msg.oid)
            except Exception as e:
                if getattr(e, "errno", 0) != 2:
                    with self.lock:
                        self._base_deleting.discard(msg.oid)
                        self._client_ops.pop((msg.client, msg.tid),
                                             None)
                        self._reply(conn, msg,
                                    -(getattr(e, "errno", 0) or 5), [])
                    return
            msg._base_deleted = True
            with self.lock:
                msg._promote_checked = True
                try:
                    # the local delete submits inside _do_op, so the
                    # object is inflight (flush-proof) before we lift
                    # the fence
                    self._do_op(msg, conn)
                except RuntimeError:
                    pass                 # teardown raced (store gone)
                finally:
                    self._base_deleting.discard(msg.oid)

        threading.Thread(target=run, name="cache-basedel",
                         daemon=True).start()

    def cache_agent(self) -> Tuple[int, int]:
        """One tier-agent pass (reference TierAgentState / agent_work):
        flush dirty objects past the dirty ratio, evict clean ones
        while the cache exceeds its targets; -> (flushed, evicted).
        Runs from the OSD tick on the primary."""
        pool = self.pool
        if not pool.is_tier() or pool.cache_mode != "writeback":
            return (0, 0)
        with self.lock:
            if not self.is_primary() or self.state != STATE_ACTIVE:
                return (0, 0)
            objs: List[Tuple[str, int, bool]] = []   # oid, size, dirty
            for oid in self.backend.list_objects():
                if oid == PGMETA_OID or "@" in oid:
                    continue
                if self._is_degraded(oid):
                    continue             # local copy may be stale:
                                         # recover first, then flush
                info = self.backend.get_object_info(oid)
                if info is None:
                    continue
                try:
                    self.store.getattr(
                        self.coll, GHObject(oid, self.own_shard),
                        "u_" + self.CACHE_DIRTY_ATTR)
                    dirty = True
                except (FileNotFoundError, KeyError):
                    dirty = False
                objs.append((oid, info.size, dirty))
            total = len(objs)
            total_bytes = sum(s for _, s, _ in objs)
            dirty_objs = [o for o in objs if o[2]]
            # pool-wide targets scale to this PG's share (reference
            # TierAgentState: agent targets divide by pg_num)
            pg_num = max(1, pool.pg_num)
            obj_target = pool.target_max_objects / pg_num \
                if pool.target_max_objects else 0
            byte_target = pool.target_max_bytes / pg_num \
                if pool.target_max_bytes else 0
            over_objs = obj_target and total > obj_target
            over_bytes = byte_target and total_bytes > byte_target
            over_dirty = dirty_objs and (
                (obj_target and
                 len(dirty_objs) > pool.cache_target_dirty_ratio
                 * obj_target)
                or over_objs or over_bytes)
            flush_list = [o for o, _, d in objs if d][:4] \
                if over_dirty else []
            evict_budget = 0
            if over_objs:
                evict_budget = int(total - obj_target) + 1
            if over_bytes:
                evict_budget = max(evict_budget, 4)
            evict_list = [o for o, _, d in objs
                          if not d and o not in self.inflight_writes
                          and o not in self._flushing][:evict_budget]
        flushed = 0
        for oid in flush_list:
            if self._flush_object(oid):
                flushed += 1
        evicted = 0
        for oid in evict_list:
            if self._evict_object(oid):
                evicted += 1
        return (flushed, evicted)

    def _flush_object(self, oid: str) -> bool:
        """Write a dirty object back to the base pool, then mark it
        clean (reference agent_maybe_flush / start_flush).  Ops on the
        object park while the flush holds it stable."""
        with self.lock:
            if oid in self.inflight_writes or oid in self._flushing \
                    or oid in self._promoting \
                    or oid in self._base_deleting \
                    or self._is_degraded(oid):
                # a log-recovering primary's LOCAL copy can be stale —
                # flushing it would overwrite the base with old bytes
                # that a later evict+promote would resurrect
                return False
            obj = GHObject(oid, self.own_shard)
            try:
                data = self.store.read(self.coll, obj)
                raw_attrs = self.store.getattrs(self.coll, obj)
                omap = self.store.omap_get(self.coll, obj)
            except OSError:
                # missing OR store-csum EIO: skip this object (scrub
                # repair re-homes good bytes) instead of aborting the
                # whole agent pass
                return False
            attrs = {k[2:]: v for k, v in raw_attrs.items()
                     if k.startswith("u_")
                     and k[2:] != self.CACHE_DIRTY_ATTR}
            base = self.service.get_osdmap().pools.get(
                self.pool.tier_of)
            if omap and (base is None or base.is_erasure()):
                # omap can't land on an EC base (ENOTSUP there): the
                # object stays dirty in the cache — this is exactly
                # how a cache tier gives an EC pool omap support
                # (reference: omap-bearing objects pin in the tier)
                return False
            self._flushing.add(oid)
        base_pool = self.pool.tier_of

        def run() -> None:
            try:
                from ..msg.messages import OSDOp
                io = self.service.objecter_ioctx(base_pool)
                # ONE compound op: content + attr/omap replacement
                # land atomically at the base PG — a flush interrupted
                # by a kill can never leave the base with new content
                # but missing xattrs (a later promote would serve the
                # torn copy)
                ops = [OSDOp("rmxattrs"),
                       OSDOp("writefull", 0, len(data), data)]
                for k, v in attrs.items():
                    ops.append(OSDOp("setxattr", data=v, name=k))
                if omap:
                    ops.append(OSDOp("omap_clear"))
                    for k, v in omap.items():
                        ops.append(OSDOp("omap_set", data=v, name=k))
                io._obj_op(oid, ops)
            except Exception:
                with self.lock:
                    self._flushing.discard(oid)
                    q = self.waiting_for_obj.pop(oid, None)
                    if q:
                        for m, c in q:
                            self._do_op(m, c)
                return
            with self.lock:
                mut = Mutation()
                mut.attrs[self.CACHE_DIRTY_ATTR] = None
                self.cache_flushes += 1

                def done(res: int) -> None:
                    self._flushing.discard(oid)
                    q = self.waiting_for_obj.pop(oid, None)
                    if q:
                        for m, c in q:
                            try:
                                self._do_op(m, c)
                            except Exception:
                                import traceback
                                traceback.print_exc()
                try:
                    self._submit_internal(oid, mut, on_done=done)
                except Exception:
                    done(-5)

        threading.Thread(target=run, name="cache-flush",
                         daemon=True).start()
        return True

    def _evict_object(self, oid: str) -> bool:
        """Drop a CLEAN object from the cache (reference
        agent_maybe_evict): the base pool holds it; the next miss
        promotes it back.  Goes through _submit_internal directly, so
        the write-through base delete never fires."""
        with self.lock:
            if oid in self.inflight_writes or oid in self._flushing \
                    or oid in self._promoting \
                    or self._is_degraded(oid):
                return False
            if self.backend.get_object_info(oid) is None:
                return False
            # re-check cleanliness UNDER THE LOCK: a client write may
            # have re-dirtied the object after the agent's listing —
            # evicting it would drop acked data and a later miss
            # would promote the stale base copy
            try:
                self.store.getattr(self.coll,
                                   GHObject(oid, self.own_shard),
                                   "u_" + self.CACHE_DIRTY_ATTR)
                return False             # dirty again: flush first
            except (FileNotFoundError, KeyError):
                pass
            mut = Mutation()
            mut.delete = True
            self.cache_evicts += 1
            self._evicting.add(oid)

            def done(res: int) -> None:
                self._evicting.discard(oid)
                q = self.waiting_for_obj.pop(oid, None)
                if q:
                    for m, c in q:
                        try:
                            self._do_op(m, c)
                        except Exception:
                            import traceback
                            traceback.print_exc()
            try:
                self._submit_internal(oid, mut, on_done=done)
            except Exception:
                done(-5)
                return False
        return True

    def _can_pipeline(self, msg: MOSDOp, oid: str) -> bool:
        """May this write run concurrently with in-flight writes on
        the same object?  Plain partial overwrites on EC-overwrites
        pools pipeline through the backend's extent overlay (reference
        ExtentCache, ECBackend.cc:1891-1920).  Anything that depends
        on settled object state — appends, snapshot contexts (the
        SnapSet must be fresh for the clone decision), waiting
        same-object ops (order!) — serializes as before."""
        return (self.pool.is_erasure() and self.pool.ec_overwrites
                and msg.snap_seq == 0
                and oid not in self.waiting_for_obj
                and all(op.op == "write" for op in msg.ops))

    def _inflight_add(self, oid: str) -> None:
        self.inflight_writes[oid] = \
            self.inflight_writes.get(oid, 0) + 1

    def _inflight_remove(self, oid: str) -> None:
        n = self.inflight_writes.get(oid, 0) - 1
        if n <= 0:
            self.inflight_writes.pop(oid, None)
        else:
            self.inflight_writes[oid] = n

    def _next_version(self) -> Eversion:
        """Monotonic even while earlier writes are still in the async
        pipeline (log.last_update only advances at local apply)."""
        v = max(self._last_assigned[1], self.log.last_update[1]) + 1
        self._last_assigned = (self.epoch, v)
        return self._last_assigned

    def _start_copy_from(self, msg: MOSDOp, conn) -> None:
        """CEPH_OSD_OP_COPY_FROM (reference PrimaryLogPG.cc:2816
        do_copy_from): the primary fetches the SOURCE object — possibly
        homed in another PG — through the OSD's internal objecter, then
        folds it into this op as a full replace (data + user xattrs +
        omap on replicated pools).  The fetch runs off the PG lock;
        the op re-enters the normal write path when it lands, so dup
        detection/snapshots/EC rules all apply unchanged."""
        src = next(op for op in msg.ops if op.op == "copy_from")
        src_oid = src.name
        # on a cache-tier pool the source resolves through the
        # OVERLAY: it may live only in the base after an evict, and
        # the overlay read promotes it back before serving
        if self.pool.is_tier():
            pool_id, bypass = self.pool.tier_of, False
        else:
            pool_id, bypass = self.pgid.pool, True
        replicated = not self.pool.is_erasure()

        def fetch() -> None:
            try:
                io = self.service.objecter_ioctx(pool_id, bypass)
                # ONE compound read: data+xattrs+omap snapshot the
                # source atomically at its PG — separate ops would
                # leave windows where a tier evict/promote (or any
                # concurrent writer) changes the object between them
                fetch_ops = [OSDOp("read"), OSDOp("getxattrs")]
                if replicated:
                    fetch_ops.append(OSDOp("omap_get"))
                reply = io._obj_op(src_oid, fetch_ops)
                data = reply.out_data[0]
                attrs = {k: v.encode("latin1") for k, v in
                         reply.extra.get("xattrs", {}).items()}
                omap = {k: v.encode("latin1") for k, v in
                        reply.extra.get("omap", {}).items()} \
                    if replicated else {}
            except Exception as e:
                code = getattr(e, "errno", 0) or 5
                with self.lock:
                    self._client_ops.pop((msg.client, msg.tid), None)
                    self._reply(conn, msg, -code, [])
                return
            with self.lock:
                if not self.is_primary() or self.state != STATE_ACTIVE:
                    self._client_ops.pop((msg.client, msg.tid), None)
                    self._reply(conn, msg, -108, [])
                    return
                # the result must be an EXACT copy: pre-existing
                # destination xattrs/omap keys absent from the source
                # must not survive (reference CEPH_OSD_OP_COPY_FROM
                # replaces the object wholesale).  The clearing ops
                # resolve at EXECUTION time ("rmxattrs" enumerates the
                # dest's attrs in _do_write) — this op may yet park
                # behind an in-flight write whose attrs must also be
                # cleared, so a name list computed here would be stale.
                new_ops: List[OSDOp] = []
                for op in msg.ops:
                    if op.op != "copy_from":
                        new_ops.append(op)
                        continue
                    new_ops.append(OSDOp("rmxattrs"))
                    if replicated:
                        new_ops.append(OSDOp("omap_clear"))
                    new_ops.append(OSDOp("writefull", 0, len(data),
                                         data))
                    for k, v in attrs.items():
                        new_ops.append(OSDOp("setxattr", data=v,
                                             name=k))
                    for k, v in omap.items():
                        new_ops.append(OSDOp("omap_set", data=v,
                                             name=k))
                msg.ops = new_ops
                self._do_op(msg, conn)

        threading.Thread(target=fetch, name="copy-from",
                         daemon=True).start()

    def _do_write(self, msg: MOSDOp, conn) -> None:
        # dup detection: a resend of an already-committed op must not
        # re-apply (reference PGLog dup handling / already_complete)
        if self.log.has_reqid(msg.client, msg.tid) is not None:
            # resend of a committed op: replay its outputs so calls
            # with payloads (class methods) don't lose their result
            # (reference keeps completed-op reply data with the log)
            cached = self._reply_cache.get((msg.client, msg.tid), [])
            self._reply(conn, msg, 0, cached)
            return
        mut = Mutation()
        mut.trace_id = msg.trace_id
        # child spans (EC shard sub-writes) hang off the primary's
        # osd_op span; the tracked op rides along so the backend /
        # batcher can stamp stage events on the client op's timeline
        mut.parent_span_id = getattr(msg, "osd_span_id", 0)
        mut.tracked_op = getattr(msg, "tracked", None)
        mut.client_msg = msg
        err = 0
        ec = self.pool.is_erasure()
        full_replace = any(op.op == "writefull" for op in msg.ops)
        info = self.backend.get_object_info(msg.oid)
        cur_size = info.size if info else 0
        rollback_snap: Optional[int] = None
        call_outputs: List[bytes] = [b""] * len(msg.ops)
        for i, op in enumerate(msg.ops):
            o = op.op
            if o == "call":
                # object classes run at the primary and stage their
                # effects into this op's mutation (reference
                # CEPH_OSD_OP_CALL); ENOTSUP on EC pools like the
                # reference (ecbackend.rst "Object Classes")
                if ec:
                    err = -95
                    break
                from ..objclass import dispatch_call
                ret, out = dispatch_call(self, msg.oid, op.name,
                                         op.data, mut)
                call_outputs[i] = out
                if ret < 0:
                    err = ret
                    break
            elif o == "write":
                mut.writes.append((op.offset, op.data))
            elif o == "writefull":
                mut.writes.append((0, op.data))
                mut.truncate = len(op.data)
            elif o == "append":
                mut.writes.append((cur_size, op.data))
                cur_size += len(op.data)
            elif o == "create":
                mut.create = True
            elif o == "delete":
                mut.delete = True
            elif o == "truncate":
                if ec and not self.pool.ec_overwrites:
                    err = -95
                    break
                mut.truncate = op.offset
            elif o == "rollback":
                # selfmanaged snap rollback: snapid rides in offset
                # (reference CEPH_OSD_OP_ROLLBACK)
                rollback_snap = op.offset
            elif o == "setxattr":
                mut.attrs[op.name] = op.data
            elif o == "rmxattr":
                mut.attrs[op.name] = None
            elif o == "rmxattrs":
                # clear ALL user xattrs, resolved at EXECUTION time
                # (copy_from's exact-copy clearing: resolving earlier
                # — at fetch completion — would miss attrs written by
                # ops this one parked behind)
                try:
                    cur = self.store.getattrs(
                        self.coll, GHObject(msg.oid, self.own_shard))
                except FileNotFoundError:
                    cur = {}
                for name in cur:
                    if name.startswith("u_"):
                        mut.attrs.setdefault(name[2:], None)
            elif o in ("omap_set", "omap_rm", "omap_clear"):
                if ec:
                    err = -95            # ENOTSUP on EC pools
                    break
                if o == "omap_set":
                    mut.omap_set[op.name] = op.data
                elif o == "omap_rm":
                    mut.omap_rm.append(op.name)
                else:
                    mut.omap_clear = True
            elif o in READ_OPS:
                err = -22                # no mixed read/write ops
                break
            else:
                err = -95
                break
        # reference osd_max_object_size: reject objects growing past
        # the cap (checked on the projected write extent)
        if not err:
            limit = self.conf["osd_max_object_size"]
            projected = mut.truncate if mut.truncate is not None \
                else cur_size
            for off, data in mut.writes:
                projected = max(projected, off + len(data))
            if limit and projected > limit:
                err = -27                # EFBIG
        if ec and not self.pool.ec_overwrites and not mut.delete \
                and not full_replace \
                and not mut.append_only_at(info.size if info else 0):
            err = -95                    # overwrite needs ec_overwrites
        if err:
            self._reply(conn, msg, err, [])
            return
        if self.pool.is_tier() and self.pool.cache_mode == "writeback" \
                and not mut.delete:
            # dirty marker for the tier agent's flush pass (reference
            # object_info_t FLAG_DIRTY)
            mut.attrs[self.CACHE_DIRTY_ATTR] = b"1"

        # -- snapshots (reference PrimaryLogPG::make_writeable) --------
        from .snaps import SnapContext, SnapSet, clone_oid, snapdir_oid
        # stale client contexts may still list deleted snaps: filter
        # against the pool's removed set (reference filter_snapc) so
        # no clone is ever created covering a snap the trimmer already
        # processed
        removed = set(self.pool.removed_snaps)
        snapc = SnapContext(msg.snap_seq,
                            [s for s in msg.snaps if s not in removed])
        ss, ss_from_snapdir = self._get_snapset(msg.oid)
        entries: List[LogEntry] = []
        if rollback_snap is not None:
            solo = len(msg.ops) == 1
            kind, cid = (ss or SnapSet()).resolve_read(rollback_snap)
            if kind == "head" and solo:
                # the head already IS the state at that snap: pure
                # no-op — crucially it must NOT advance the SnapSet
                # seq, or the snaps between ss.seq and snapc.seq
                # (whose state is the head) become unresolvable.
                # (Bundled with other ops, those still apply below;
                # the rollback component simply contributes nothing.)
                self._reply(conn, msg, 0, call_outputs)
                return
            if kind == "clone":
                if not solo:
                    # rollback replaces the whole head; mixing it with
                    # other mutations in one op has no sound ordering
                    # (the EC write plan would RMW pre-rollback bytes)
                    self._reply(conn, msg, -22, [])
                    return
                src = clone_oid(msg.oid, cid)
                if self._is_degraded(src):
                    self.waiting_for_degraded.setdefault(
                        src, deque()).append((msg, conn))
                    self.service.kick_recovery(self)
                    return
                mut.rollback_from = src
                mut.rollback_size = ss.clone_size[cid]
            elif kind == "enoent":
                # rolling back to before the object existed = delete
                # (reference _rollback_to ENOENT -> whiteout/delete)
                if not solo:
                    # delete-then-apply-other-ops is inexpressible in
                    # one Mutation; reject the mix instead of silently
                    # dropping either half
                    self._reply(conn, msg, -22, [])
                    return
                if info is None:
                    self._reply(conn, msg, 0, call_outputs)
                    return
                mut.delete = True
        if snapc and info is not None and \
                (ss or SnapSet()).needs_clone(snapc):
            # COW the head before this write/delete/ROLLBACK mutates
            # it — a rollback destroys the head too, and a snap taken
            # since the last write still needs the pre-rollback state
            # (reference: rollback goes through make_writeable)
            if ss is None:
                ss = SnapSet()
            cver = self._next_version()
            cid = ss.add_clone(snapc, info.size)
            coid = clone_oid(msg.oid, cid)
            mut.clone_to = coid
            mut.clone_attrs = {OI_ATTR: ObjectInfo(
                size=info.size, version=cver).encode()}
            entries.append(LogEntry(
                MODIFY, coid, cver, prior_version=(0, 0),
                reqid=(f"{msg.client}.clone", msg.tid)))
        elif snapc and info is None:
            # creating under a snap context: the era advances so snap
            # reads at or before the creating snapc resolve to ENOENT.
            # Existing objects never advance without cloning — the
            # snaps in between see the (unchanged) head.
            if ss is None:
                ss = SnapSet()
            ss.advance_seq(snapc)
        if mut.delete:
            if ss is not None and not ss.empty:
                # clones outlive the head: SnapSet moves to snapdir.
                # The snapdir's creation is LOGGED at its own version —
                # unlogged object lifecycle diverges peering's missing
                # sets from the store under thrash
                sd_oid = snapdir_oid(msg.oid)
                sd_ver = self._next_version()
                mut.snapdir_set = (sd_oid, ss.encode(), ObjectInfo(
                    size=0, version=sd_ver).encode())
                sd_info = self.backend.get_object_info(sd_oid)
                entries.append(LogEntry(
                    MODIFY, sd_oid, sd_ver,
                    prior_version=(sd_info.version if sd_info
                                   else (0, 0)),
                    reqid=(f"{msg.client}.snapdir", msg.tid)))
        else:
            if ss_from_snapdir:
                # head recreated: the SnapSet moves back home; the
                # snapdir's removal is likewise logged
                sd_oid = snapdir_oid(msg.oid)
                mut.aux_remove.append(sd_oid)
                sd_ver = self._next_version()
                sd_info = self.backend.get_object_info(sd_oid)
                entries.append(LogEntry(
                    DELETE, sd_oid, sd_ver,
                    prior_version=(sd_info.version if sd_info
                                   else (0, 0)),
                    reqid=(f"{msg.client}.snapdir", msg.tid)))
            if ss is not None:
                mut.snapset = ss.encode()

        version = self._next_version()
        # prior_version chains through IN-FLIGHT writes on the object
        # (committed store state lags pipelined ops; divergent-log
        # handling in peering depends on the true predecessor)
        prior = self._pending_versions.get(
            msg.oid, info.version if info else (0, 0))
        entries.append(LogEntry(DELETE if mut.delete else MODIFY,
                                msg.oid, version, prior_version=prior,
                                reqid=(msg.client, msg.tid)))
        self._pending_versions[msg.oid] = version
        self._inflight_add(msg.oid)
        if mut.tracked_op is not None:
            mut.tracked_op.mark_event("started_write")
        # the commit pipeline owns the tracker entry from here: the
        # shard worker must not retire it when do_request returns
        msg._tracked_async = True
        self.backend.submit_transaction(
            msg.oid, mut, version, entries,
            lambda res: self._op_committed(msg, conn, res,
                                           call_outputs))

    def _op_committed(self, msg: MOSDOp, conn, res: int,
                      out_data: Optional[List[bytes]] = None) -> None:
        tracked = getattr(msg, "tracked", None)
        if tracked is not None:
            tracked.mark_event("op_commit")
        # the backend stamped store_apply at the primary's LOCAL store
        # commit (first-stamp-wins makes this a no-op then); the time
        # from there to the full acting-set ack is peer_ack_wait — an
        # async store that acks fast must not have the distributed
        # round trip charged against it
        msg.stamp_hop("store_apply")
        msg.stamp_hop("peer_ack_wait")
        self._inflight_remove(msg.oid)
        if msg.oid not in self.inflight_writes:
            self._pending_versions.pop(msg.oid, None)
        if res == 0 and out_data and any(out_data):
            self._reply_cache[(msg.client, msg.tid)] = out_data
            while len(self._reply_cache) > 128:
                self._reply_cache.pop(
                    next(iter(self._reply_cache)))
        self._reply(conn, msg, res, out_data or [])
        # serialize-class waiters run only once the object is fully
        # settled — popping one while pipelined writes are still in
        # flight would requeue it BEHIND later waiters, inverting the
        # client's submission order
        q = self.waiting_for_obj.get(msg.oid) \
            if msg.oid not in self.inflight_writes else None
        if q:
            nmsg, nconn = q.popleft()
            if not q:
                del self.waiting_for_obj[msg.oid]
            self._do_op(nmsg, nconn)
        # a scrub waiting for the write pipeline to drain may now run
        self.scrubber.kick()

    def _do_reads(self, msg: MOSDOp, conn) -> None:
        out_data: List[bytes] = [b""] * len(msg.ops)
        extra: Dict = {}

        # snap read resolution (reference find_object_context): a
        # snapid resolves to the head, a clone object, or ENOENT
        oid = msg.oid
        if msg.snapid:
            from .snaps import clone_oid
            ss, _ = self._get_snapset(msg.oid)
            if ss is not None:
                kind, cid = ss.resolve_read(msg.snapid)
                if kind == "clone":
                    oid = clone_oid(msg.oid, cid)
                    if self.missing.is_missing(oid):
                        self.waiting_for_degraded.setdefault(
                            oid, deque()).append((msg, conn))
                        self.service.kick_recovery(self)
                        return
                elif kind == "enoent":
                    self._reply(conn, msg, -2, out_data)
                    return
            # no SnapSet: the object was never written under a snap
            # context, so the head (if any) is its state at every snap

        def finish(res: int) -> None:
            self._reply(conn, msg, res, out_data, extra)

        def run(i: int) -> None:
            if i >= len(msg.ops):
                finish(0)
                return
            op = msg.ops[i]
            o = op.op
            if o == "read":
                def cb(res: int, data: bytes, i=i) -> None:
                    if res < 0:
                        finish(res)
                    else:
                        out_data[i] = data
                        run(i + 1)
                length = op.length if op.length else (1 << 62)
                self.backend.objects_read(oid, op.offset, length, cb,
                                          hop_msg=msg)
                return
            if o == "call":
                # read-only class method (reference CLS_METHOD_RD):
                # no transaction; staging writes fails in dispatch
                if self.pool.is_erasure():
                    finish(-95)
                    return
                from ..objclass import dispatch_call
                ret, out = dispatch_call(self, msg.oid, op.name,
                                         op.data, None)
                if ret < 0:
                    finish(ret)
                    return
                out_data[i] = out
            elif o == "stat":
                info = self.backend.get_object_info(oid)
                if info is None:
                    finish(-2)
                    return
                extra["size"] = info.size
                extra["version"] = list(info.version)
            elif o == "list_snaps":
                # reference CEPH_OSD_OP_LIST_SNAPS: the object's clone
                # inventory from its SnapSet
                ss, _ = self._get_snapset(msg.oid)
                if ss is None:
                    extra["snaps"] = {"seq": 0, "clones": []}
                else:
                    extra["snaps"] = {
                        "seq": ss.seq,
                        "clones": [{"id": c,
                                    "snaps": ss.clone_snaps.get(c, []),
                                    "size": ss.clone_size.get(c, 0)}
                                   for c in ss.clones]}
            elif o == "getxattr":
                try:
                    out_data[i] = self.store.getattr(
                        self.coll, GHObject(oid, self.own_shard),
                        "u_" + op.name)
                except (FileNotFoundError, KeyError):
                    finish(-61)          # -ENODATA
                    return
            elif o == "getxattrs":
                try:
                    attrs = self.store.getattrs(
                        self.coll, GHObject(oid, self.own_shard))
                except FileNotFoundError:
                    finish(-2)
                    return
                extra["xattrs"] = {k[2:]: v.decode("latin1")
                                   for k, v in attrs.items()
                                   if k.startswith("u_")}
            elif o == "omap_get":
                if self.pool.is_erasure():
                    finish(-95)
                    return
                try:
                    omap = self.store.omap_get(
                        self.coll, GHObject(oid, self.own_shard))
                except FileNotFoundError:
                    finish(-2)
                    return
                extra["omap"] = {k: v.decode("latin1")
                                 for k, v in omap.items()}
            elif o == "omap_get_by_key":
                # single-key lookup (reference omap_get_vals_by_keys):
                # avoids shipping a huge index to read one entry
                if self.pool.is_erasure():
                    finish(-95)
                    return
                try:
                    omap = self.store.omap_get(
                        self.coll, GHObject(oid, self.own_shard))
                except FileNotFoundError:
                    finish(-2)
                    return
                if op.name not in omap:
                    finish(-61)          # -ENODATA
                    return
                out_data[i] = omap[op.name]
            elif o == "watch":
                # register this session as a watcher (reference
                # CEPH_OSD_OP_WATCH, osd/Watch.cc); cookie in offset
                if self.backend.get_object_info(msg.oid) is None:
                    finish(-2)
                    return
                self.watchers.setdefault(msg.oid, {})[
                    (msg.client, op.offset)] = conn
            elif o == "unwatch":
                ws = self.watchers.get(msg.oid, {})
                ws.pop((msg.client, op.offset), None)
                if not ws:
                    self.watchers.pop(msg.oid, None)
            elif o == "list_watchers":
                extra["watchers"] = sorted(
                    f"{cl}:{ck}" for cl, ck in
                    self.watchers.get(msg.oid, {}))
            elif o == "notify":
                self._do_notify(msg, conn, op)
                return               # reply deferred to acks/timeout
            elif o == "notify_ack":
                # notify_id in offset, acking watch's cookie in length
                self._notify_acked(op.offset, msg.client, op.length)
            elif o == "pgls":
                from .snaps import is_snap_oid
                objs = []
                for o2 in self.backend.list_objects():
                    if o2 == PGMETA_OID or is_snap_oid(o2):
                        continue         # clients list heads only
                    objs.append(o2)
                for o2, (need, _) in self.missing.items.items():
                    if o2 not in objs and not is_snap_oid(o2):
                        objs.append(o2)
                extra["objects"] = sorted(objs)
            else:
                finish(-95)
                return
            run(i + 1)

        run(0)

    # ------------------------------------------------------------------
    # watch/notify (reference osd/Watch.cc + PrimaryLogPG::do_osd_ops
    # NOTIFY/NOTIFY_ACK handling)
    # ------------------------------------------------------------------
    def _do_notify(self, msg: MOSDOp, conn, op) -> None:
        """Fan a notify out to every watcher session; the notifier's
        reply waits for all acks or the timeout (reference Notify
        completion)."""
        from ..msg.messages import MWatchNotify
        self._next_notify_id += 1
        nid = self._next_notify_id
        # pending keyed by (client, cookie): one client may hold
        # several watches on the object, each must ack independently
        pending: Set[Tuple[str, int]] = set()
        watchers = self.watchers.get(msg.oid, {})
        for (client, cookie), wconn in list(watchers.items()):
            try:
                wconn.send_message(MWatchNotify(
                    oid=msg.oid, pool=msg.pool, cookie=cookie,
                    notify_id=nid, payload=op.data,
                    notifier=msg.client))
                pending.add((client, cookie))
            except Exception:
                # dead session: the watch dies with it (reference
                # watch timeout/con reset teardown)
                watchers.pop((client, cookie), None)
        if not pending:
            self._reply(conn, msg, 0, [b""] * len(msg.ops),
                        {"acks": [], "timed_out": []})
            return
        state = {"pending": pending, "acks": [], "msg": msg,
                 "conn": conn, "nops": len(msg.ops)}
        self._notifies[nid] = state
        timeout = (op.offset or
                   self.conf["osd_default_notify_timeout"] * 1000) \
            / 1000.0
        # hosted OSDs supply a wheel timer; stubs without one fall back
        # to a plain thread timer
        t = self.call_later(timeout,
                            lambda: self._notify_timeout(nid))
        if t is None:
            t = threading.Timer(timeout, self._notify_timeout,
                                args=(nid,))
            t.daemon = True
            t.start()
        state["timer"] = t

    def _notify_acked(self, nid: int, client: str,
                      cookie: int) -> None:
        state = self._notifies.get(nid)
        if state is None:
            return
        state["pending"].discard((client, cookie))
        tag = f"{client}:{cookie}"
        if tag not in state["acks"]:
            state["acks"].append(tag)
        if not state["pending"]:
            del self._notifies[nid]
            state["timer"].cancel()
            self._reply(state["conn"], state["msg"], 0,
                        [b""] * state["nops"],
                        {"acks": sorted(state["acks"]),
                         "timed_out": []})

    def _notify_timeout(self, nid: int) -> None:
        with self.lock:
            state = self._notifies.pop(nid, None)
            if state is None:
                return
            self._reply(state["conn"], state["msg"], 0,
                        [b""] * state["nops"],
                        {"acks": sorted(state["acks"]),
                         "timed_out": sorted(
                             f"{cl}:{ck}" for cl, ck in
                             state["pending"])})

    def _reply(self, conn, msg: MOSDOp, result: int,
               out_data: List[bytes], extra: Optional[Dict] = None
               ) -> None:
        self._client_ops.pop((msg.client, msg.tid), None)
        # every client reply retires the op's tracker entry (the single
        # chokepoint: reads, write commits, and error bounces all land
        # here); finish() is idempotent
        tracked = getattr(msg, "tracked", None)
        if tracked is not None:
            # SLO error classification: infrastructure failures burn
            # budget; client-semantic errnos (ENOENT, EEXIST, ENODATA,
            # EOPNOTSUPP, ECANCELED, ETIMEDOUT-on-notify) do not — a
            # read of a nonexistent object is a correct answer.  -108
            # (ESHUTDOWN) is the misdirected-op bounce: a routing
            # redirect during map churn that the objecter transparently
            # retries against the new primary, not a service failure
            if result < 0 and result not in (-2, -17, -61, -95, -108, -125):
                tracked.slo_ok = False
            tracked.finish()
        if conn is None:
            return
        reply = MOSDOpReply(tid=msg.tid, result=result,
                            epoch=self.epoch, out_data=list(out_data),
                            extra=extra or {})
        # carry the op's cumulative hop ledger back so the client can
        # close the waterfall (reads skip store_apply; charge() skips
        # absent hops)
        if msg.hops:
            reply.hops = dict(msg.hops)
        reply.stamp_hop("commit_sent")
        conn.send_message(reply)

    # ------------------------------------------------------------------
    # recovery driving (reference start_recovery_ops)
    # ------------------------------------------------------------------
    def missing_objects(self) -> Dict[str, Eversion]:
        """Union of all shards' missing (primary view)."""
        out: Dict[str, Eversion] = {}
        for oid, (need, _) in self.missing.items.items():
            out[oid] = max(out.get(oid, (0, 0)), need)
        for s, ms in self.peer_missing.items():
            if self.acting[s] is None:
                continue
            for oid, (need, _) in ms.items.items():
                out[oid] = max(out.get(oid, (0, 0)), need)
        return out

    def num_missing(self) -> int:
        return len(self.missing_objects())

    def is_clean(self) -> bool:
        with self.lock:
            if self.state != STATE_ACTIVE:
                return False
            if self.is_primary() and self.num_missing() > 0:
                return False
            return None not in self.acting and \
                len(self.acting) >= self.pool.min_size

    # ------------------------------------------------------------------
    # snap trimming (reference SnapTrimmer / PrimaryLogPG::trim_object,
    # collapsed to an idempotent primary-side scan)
    # ------------------------------------------------------------------
    def maybe_trim_snaps(self) -> int:
        """Remove clones whose every covered snap was deleted from the
        pool (pool.removed_snaps); -> trim mutations submitted.  Runs
        from the OSD tick; idempotent, so a crash mid-trim just
        re-scans."""
        from .snaps import SS_ATTR, SnapSet, clone_oid, is_snap_oid
        # reference osd_snap_trim_sleep: pace trim rounds so trimming
        # never starves client IO (checked outside the PG lock —
        # sleeping under it would do the starving)
        pause = self.conf["osd_snap_trim_sleep"]
        if pause > 0 and time.monotonic() - getattr(
                self, "_last_snap_trim", 0.0) < pause:
            return 0
        with self.lock:
            removed = set(self.pool.removed_snaps)
            if not self.is_primary() or self.state != STATE_ACTIVE \
                    or not removed \
                    or removed == getattr(self, "_snaps_trimmed", None):
                return 0
            if self.is_primary() and self.num_missing() > 0:
                return 0                 # recover first, then trim
            self._last_snap_trim = time.monotonic()
            submitted = 0
            skipped = False
            for oid in self.backend.list_objects():
                if oid == PGMETA_OID:
                    continue
                if is_snap_oid(oid) and not oid.endswith("@snapdir"):
                    continue             # clones are handled via heads
                try:
                    ss = SnapSet.decode(self.store.getattr(
                        self.coll, GHObject(oid, self.own_shard),
                        SS_ATTR))
                except (FileNotFoundError, KeyError, ValueError):
                    continue
                before = ss.encode()
                gone = ss.trim(removed)
                if ss.encode() == before:
                    continue             # nothing of ours was removed
                head = oid.split("@", 1)[0]
                if head in self.inflight_writes or \
                        any(clone_oid(head, c) in self.inflight_writes
                            for c in gone):
                    skipped = True       # busy: retry next tick
                    continue
                for cid in gone:
                    mut = Mutation()
                    mut.delete = True
                    self._submit_internal(clone_oid(head, cid), mut)
                    submitted += 1
                is_snapdir = oid.endswith("@snapdir")
                mut = Mutation()
                if is_snapdir and ss.empty:
                    mut.delete = True    # last clone gone: drop snapdir
                else:
                    mut.snapset = ss.encode()
                self._submit_internal(oid, mut)
                submitted += 1
            if not skipped and submitted == 0:
                # memoize only a fully-clean pass: after submitting
                # work (or skipping busy objects) the next tick
                # re-scans until nothing is left to trim
                self._snaps_trimmed = removed
            return submitted

    def _submit_internal(self, oid: str, mut: Mutation,
                         on_done=None) -> None:
        """Primary-internal mutation (snap trim, cache promote/flush/
        evict): full log + replication machinery, no client to answer.
        ``on_done(res)`` runs after local commit, under the PG lock."""
        info = self.backend.get_object_info(oid)
        version = self._next_version()
        self._trim_seq = getattr(self, "_trim_seq", 0) + 1
        prior = self._pending_versions.get(
            oid, info.version if info else (0, 0))
        entry = LogEntry(DELETE if mut.delete else MODIFY, oid, version,
                         prior_version=prior,
                         reqid=(f"osd.{self.whoami}.trim",
                                self._trim_seq))
        self._pending_versions[oid] = version
        self._inflight_add(oid)

        def done(res: int, oid=oid) -> None:
            self._inflight_remove(oid)
            if oid not in self.inflight_writes:
                self._pending_versions.pop(oid, None)
            if on_done is not None:
                try:
                    on_done(res)
                except Exception:
                    import traceback
                    traceback.print_exc()
            q = self.waiting_for_obj.get(oid)
            if q:
                nmsg, nconn = q.popleft()
                if not q:
                    del self.waiting_for_obj[oid]
                self._do_op(nmsg, nconn)
            self.scrubber.kick()
        self.backend.submit_transaction(oid, mut, version, [entry],
                                        done)

    def start_recovery_ops(self, budget: int) -> int:
        """Launch up to ``budget`` object recoveries; -> ops started."""
        with self.lock:
            if not self.is_primary() or self.state != STATE_ACTIVE:
                return 0
            started = 0
            # blocked client ops first (reference recovery priorities)
            queue = list(self.waiting_for_degraded)
            queue += [oid for oid in self.missing_objects()
                      if oid not in queue]
            for oid in queue:
                if started >= budget:
                    break
                if oid in self.recovering:
                    continue
                targets = self._missing_targets(oid)
                if not targets:
                    continue
                version = self.missing_objects().get(oid)
                if version is None:
                    continue
                self.recovering[oid] = time.monotonic()
                entry_exists = not self._is_deleted_in_log(oid)
                if not entry_exists:
                    self._recover_delete(oid, targets)
                    started += 1
                    continue
                self.backend.recover_object(
                    oid, version, targets,
                    lambda res, oid=oid: self._on_recovered(oid, res))
                started += 1
            return started

    def _is_deleted_in_log(self, oid: str) -> bool:
        for e in reversed(self.log.entries):
            if e.oid == oid:
                return e.is_delete()
        return False

    def _recover_delete(self, oid: str,
                        targets: List[Tuple[int, int]]) -> None:
        """The authoritative version of ``oid`` is a delete: remove it
        wherever it lingers (no push needed)."""
        for shard, osd in targets:
            if osd == self.whoami:
                obj = GHObject(oid, self.own_shard)
                if self.store.exists(self.coll, obj):
                    txn = Transaction()
                    txn.remove(self.coll, obj)
                    self.store.queue_transactions(
                        [txn], op="recovery_trim")
        self._on_recovered(oid, 0)

    def requeue_scrub_waiters(self) -> None:
        waiters, self.waiting_for_scrub = \
            self.waiting_for_scrub, deque()
        for msg, conn in waiters:
            self._do_op(msg, conn)

    def mark_shard_missing(self, oid: str, version: Eversion,
                           shard: int, osd: int) -> None:
        """Scrub repair found a bad copy: treat it as missing so the
        recovery path rebuilds it (reference repair_object marking the
        authoritative-divergent shard missing)."""
        if osd == self.whoami:
            self.missing.add(oid, version, None)
            self._persist_pgmeta()
        else:
            ms = self.peer_missing.setdefault(shard, MissingSet())
            ms.add(oid, version, None)

    def _missing_targets(self, oid: str) -> List[Tuple[int, int]]:
        targets: List[Tuple[int, int]] = []
        if self.missing.is_missing(oid):
            targets.append((self.own_shard, self.whoami))
        for s, ms in self.peer_missing.items():
            osd = self.acting[s] if s < len(self.acting) else None
            if osd is not None and ms.is_missing(oid):
                targets.append((s, osd))
        return targets

    def requeue_stale_recovery(self, timeout: float = 2.0) -> bool:
        """Abandon recovery ops stuck past ``timeout`` (lost sub-op,
        peer raced a map) so the next recovery pass retries them."""
        with self.lock:
            now = time.monotonic()
            stale = [oid for oid, t0 in self.recovering.items()
                     if now - t0 > timeout]
            for oid in stale:
                del self.recovering[oid]
                ops = getattr(self.backend, "recovery_ops", None)
                if ops is not None:
                    ops.pop(oid, None)
            return bool(stale)

    def _on_recovered(self, oid: str, res: int) -> None:
        with self.lock:
            t0 = self.recovering.pop(oid, None)
            slo = getattr(self.service, "slo", None)
            if slo is not None and t0 is not None:
                slo.observe("recovery", time.monotonic() - t0,
                            ok=(res == 0))
            if res == 0:
                need = self.missing_objects().get(oid, (1 << 30, 0))
                if self.missing.is_missing(oid):
                    self.missing.got(oid, need)
                    self._persist_pgmeta()
                for ms in self.peer_missing.values():
                    ms.got(oid, need)
            waiting = self.waiting_for_degraded.pop(oid, None)
            if waiting:
                for m, c in waiting:
                    self._do_op(m, c)
            if self.num_missing() == 0:
                self._maybe_purge_strays()
            self.service.kick_recovery(self)

    # ------------------------------------------------------------------
    # stats / scrub
    # ------------------------------------------------------------------
    def get_stats(self) -> Dict:
        with self.lock:
            states = [self.state]
            if self.state == STATE_ACTIVE:
                if self.is_primary() and self.num_missing() > 0:
                    states.append("recovering")
                elif None in self.acting or \
                        len([o for o in self.acting
                             if o is not None]) < self.pool.size:
                    states.append("degraded")
                else:
                    states.append("clean")
            if self.scrubber.errors:
                states.append("inconsistent")
            n_objects = len([o for o in self.backend.list_objects()
                             if o != PGMETA_OID])
            return {
                "state": "+".join(states),
                "last_update": list(self.log.last_update),
                "num_objects": n_objects,
                "num_missing": (self.num_missing()
                                if self.is_primary() else 0),
                "acting": [o if o is not None else -1
                           for o in self.acting],
                "up": [o if o is not None else -1 for o in self.up],
                "num_scrub_errors": self.scrubber.errors,
                "inconsistent": {
                    oid: list(shards) for oid, shards in
                    self.scrubber.inconsistent.items()},
                "last_scrub": self.scrubber.last_scrub,
                "last_deep_scrub": self.scrubber.last_deep_scrub,
            }
