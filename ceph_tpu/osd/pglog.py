"""Per-PG write-ahead log and missing-set tracking.

Python-native equivalent of the reference's PGLog (reference
src/osd/PGLog.{h,cc}) reduced to the machinery the framework's peering
and recovery actually consume:

* ``eversion`` — (epoch, version) ordered pair (reference eversion_t);
* ``LogEntry`` — one logged mutation: MODIFY / DELETE / ERROR with the
  object, its new version and the version it superseded (reference
  pg_log_entry_t);
* ``PGLog`` — bounded ordered log with ``last_update``/``tail``,
  omap persistence (the reference stores log entries in the pgmeta
  object's omap), and the two peering primitives:
  - ``entries_since(v)``: the catch-up slice a lagging shard needs;
  - ``merge_authoritative(entries, on_missing)``: apply the primary's
    authoritative log; entries beyond our head mark their objects
    missing (need recovery), entries we have beyond the authoritative
    head are divergent and roll back to missing at the authoritative
    version (the reference's rewind_divergent_log; EC shards roll back
    divergent writes — doc/dev/osd_internals/erasure_coding/
    ecbackend.rst:10-27);
* ``MissingSet`` — oid -> (need, have) (reference pg_missing_t).
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

Eversion = Tuple[int, int]          # (epoch, version), ordered
EVERSION_ZERO: Eversion = (0, 0)

MODIFY = "modify"
DELETE = "delete"
ERROR = "error"                     # logged failed op (reference ERROR)


@dataclass
class LogEntry:
    """reference pg_log_entry_t (osd/osd_types.h).  ``reqid`` is the
    originating client op id (client name, client tid) — the dup-
    detection key (reference osd_reqid_t / pg_log_dup_t): a client
    resending after an interval change must not re-apply a mutation
    that already committed."""
    op: str
    oid: str
    version: Eversion
    prior_version: Eversion = EVERSION_ZERO
    reqid: Optional[Tuple[str, int]] = None

    def to_dict(self) -> dict:
        d = {"op": self.op, "oid": self.oid,
             "version": list(self.version),
             "prior_version": list(self.prior_version)}
        if self.reqid is not None:
            d["reqid"] = [self.reqid[0], self.reqid[1]]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "LogEntry":
        r = d.get("reqid")
        return cls(op=d["op"], oid=d["oid"],
                   version=tuple(d["version"]),
                   prior_version=tuple(d["prior_version"]),
                   reqid=(r[0], int(r[1])) if r else None)

    def is_delete(self) -> bool:
        return self.op == DELETE

    def is_error(self) -> bool:
        return self.op == ERROR


class MissingSet:
    """oid -> (need, have); have is None when the shard has no usable
    version at all (reference pg_missing_t item.have = 0'0)."""

    def __init__(self) -> None:
        self.items: Dict[str, Tuple[Eversion, Optional[Eversion]]] = {}

    def add(self, oid: str, need: Eversion,
            have: Optional[Eversion]) -> None:
        self.items[oid] = (need, have)

    def rm(self, oid: str) -> None:
        self.items.pop(oid, None)

    def is_missing(self, oid: str) -> bool:
        return oid in self.items

    def got(self, oid: str, version: Eversion) -> None:
        """Recovery delivered ``oid`` at ``version``."""
        need, _ = self.items.get(oid, (None, None))
        if need is not None and version >= need:
            del self.items[oid]

    def num_missing(self) -> int:
        return len(self.items)

    def __iter__(self):
        return iter(sorted(self.items))

    def to_dict(self) -> dict:
        return {oid: {"need": list(need),
                      "have": list(have) if have else None}
                for oid, (need, have) in self.items.items()}

    @classmethod
    def from_dict(cls, d: dict) -> "MissingSet":
        ms = cls()
        for oid, item in d.items():
            ms.add(oid, tuple(item["need"]),
                   tuple(item["have"]) if item["have"] else None)
        return ms


class PGLog:
    """Bounded ordered log (reference PGLog / IndexedLog)."""

    DEFAULT_MAX_ENTRIES = 3000   # reference osd_min_pg_log_entries class

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES):
        self.entries: List[LogEntry] = []
        self.last_update: Eversion = EVERSION_ZERO
        self.tail: Eversion = EVERSION_ZERO   # versions <= tail trimmed
        self.max_entries = max_entries
        # dup detection (reference pg_log_dup_t index)
        self.reqids: Dict[Tuple[str, int], Eversion] = {}

    # -- write path -------------------------------------------------------
    def add(self, entry: LogEntry) -> None:
        assert entry.version > self.last_update, \
            f"log entry {entry.version} <= head {self.last_update}"
        self.entries.append(entry)
        self.last_update = entry.version
        if entry.reqid is not None:
            self.reqids[entry.reqid] = entry.version
        self._trim()

    def has_reqid(self, client: str, tid: int) -> Optional[Eversion]:
        """Version of an already-applied client op, or None (reference
        PGLog::get_request dup detection)."""
        return self.reqids.get((client, tid))

    def _trim(self) -> None:
        if len(self.entries) > self.max_entries:
            cut = len(self.entries) - self.max_entries
            for e in self.entries[:cut]:
                if e.reqid is not None:
                    self.reqids.pop(e.reqid, None)
            self.tail = self.entries[cut - 1].version
            self.entries = self.entries[cut:]

    def trim_to(self, n: int) -> None:
        """Trim to at most ``n`` entries — the clean-PG trim
        (reference osd_min_pg_log_entries: a clean PG keeps only the
        minimum; the max bound applies while degraded)."""
        if len(self.entries) > n:
            keep, self.max_entries = self.max_entries, n
            self._trim()
            self.max_entries = keep

    # -- peering primitives ----------------------------------------------
    def entries_since(self, v: Eversion) -> Optional[List[LogEntry]]:
        """Entries with version > v, or None if v < tail (log no longer
        reaches back that far — the shard needs backfill instead of
        log-based recovery; reference calc_recovery_type)."""
        if v < self.tail:
            return None
        return [e for e in self.entries if e.version > v]

    def merge_authoritative(
            self, auth_entries: List[LogEntry],
            auth_head: Eversion,
            mark_missing: Callable[[str, Eversion, Optional[Eversion]],
                                   None],
            mark_divergent: Callable[[str, Eversion], None]) -> None:
        """Adopt the authoritative log slice from the primary.

        ``auth_entries`` are the authoritative entries after our
        (possibly divergent) head's common ancestor; entries of ours
        newer than ``auth_head`` are divergent and reported via
        ``mark_divergent`` (the shard's copy of those objects must be
        rolled back / re-recovered).  New entries report via
        ``mark_missing(oid, need, have)``.
        """
        # divergent suffix: our entries beyond the authoritative head.
        # Per object, only the OLDEST divergent entry's prior_version is
        # a valid rollback target (later entries' priors are themselves
        # divergent), so report one rollback per oid.
        divergent = [e for e in self.entries if e.version > auth_head]
        self.entries = [e for e in self.entries
                        if e.version <= auth_head]
        for e in divergent:
            if e.reqid is not None:
                self.reqids.pop(e.reqid, None)
        if self.last_update > auth_head:
            self.last_update = auth_head
        seen_divergent = set()
        for e in divergent:
            if e.oid not in seen_divergent:
                seen_divergent.add(e.oid)
                mark_divergent(e.oid, e.prior_version)

        # 'have' is what this shard actually applied (our own log is
        # written atomically with data), NOT versions merely merged in
        # below — multiple auth entries for one oid must all report the
        # same true local version (last mark_missing wins with the
        # final 'need')
        applied = {e.oid: e.version for e in self.entries}
        for e in auth_entries:
            if e.version <= self.last_update:
                continue
            if not e.is_error():
                mark_missing(e.oid, e.version, applied.get(e.oid))
            self.entries.append(e)
            if e.reqid is not None:
                self.reqids[e.reqid] = e.version
            self.last_update = e.version
        self._trim()

    def split_out(self, moved: "set") -> "PGLog":
        """PG split (reference PGLog::split_out_child): return a child
        log holding this log's entries for ``moved`` oids and strip
        them here.  Both logs keep the SAME head/tail so every replica
        of the parent produces identical child logs, making child
        peering elections trivial, and reqid dup-detection for recent
        writes to moved objects survives the split."""
        child = PGLog(self.max_entries)
        child.last_update = self.last_update
        child.tail = self.tail
        child.entries = [e for e in self.entries if e.oid in moved]
        for e in child.entries:
            if e.reqid is not None:
                child.reqids[e.reqid] = e.version
                self.reqids.pop(e.reqid, None)
        self.entries = [e for e in self.entries if e.oid not in moved]
        return child

    def object_versions(self) -> Dict[str, Eversion]:
        """Latest in-log version per live object (deletes excluded)."""
        out: Dict[str, Eversion] = {}
        for e in self.entries:
            if e.is_error():
                continue
            if e.is_delete():
                out.pop(e.oid, None)
            else:
                out[e.oid] = e.version
        return out

    # -- persistence (reference: pgmeta object omap) ----------------------
    def to_dict(self) -> dict:
        return {"last_update": list(self.last_update),
                "tail": list(self.tail),
                "entries": [e.to_dict() for e in self.entries]}

    @classmethod
    def from_dict(cls, d: dict,
                  max_entries: int = DEFAULT_MAX_ENTRIES) -> "PGLog":
        log = cls(max_entries)
        log.last_update = tuple(d["last_update"])
        log.tail = tuple(d["tail"])
        log.entries = [LogEntry.from_dict(e) for e in d["entries"]]
        for e in log.entries:
            if e.reqid is not None:
                log.reqids[e.reqid] = e.version
        return log

    def encode(self) -> bytes:
        return json.dumps(self.to_dict()).encode()

    @classmethod
    def decode(cls, buf: bytes) -> "PGLog":
        return cls.from_dict(json.loads(buf.decode()))
