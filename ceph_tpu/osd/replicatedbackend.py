"""Replicated PG backend.

Python-native equivalent of the reference's ReplicatedBackend
(reference src/osd/ReplicatedBackend.{h,cc}, 2.4k LoC), the EC
backend's twin for ``TYPE_REPLICATED`` pools: the primary lowers the
logical mutation to ONE store transaction, applies it locally and ships
the identical transaction to every replica inside an MOSDRepOp
(reference ReplicatedBackend::submit_transaction -> issue_op); commit
replies gather into on_all_commit.  Reads are plain local reads on the
primary; recovery pushes the whole object (data + attrs + omap) with
MOSDPGPush (reference prep_push / handle_push).

Replicated pools support the full mutation vocabulary including omap
and truncate (contrast ECBackend's restrictions).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

from ..msg.messages import (MOSDPGPull, MOSDPGPush, MOSDPGPushReply,
                            MOSDRepOp, MOSDRepOpReply, PushOp)
from ..store.objectstore import GHObject, Transaction
from .backend import OI_ATTR, Mutation, ObjectInfo, PGBackend, PGHost
from .pglog import Eversion, LogEntry


class _RepOp:
    def __init__(self, tid: int, on_all_commit: Callable[[int], None]):
        self.tid = tid
        self.on_all_commit = on_all_commit
        self.pending: Set[int] = set()       # osd ids awaiting commit


class _RecOp:
    def __init__(self, oid: str, cb: Callable[[int], None]):
        self.oid = oid
        self.cb = cb
        self.pending: Set[int] = set()
        self.version: Eversion = (0, 0)
        self.push_after_pull: List[Tuple[int, int]] = []


class ReplicatedBackend(PGBackend):
    def __init__(self, host: PGHost):
        super().__init__(host)
        self.in_flight: Dict[int, _RepOp] = {}
        self.recovery_ops: Dict[str, _RecOp] = {}
        self._pull_attempts: Dict[str, int] = {}  # holder rotation

    # ------------------------------------------------------------------
    # write path
    # ------------------------------------------------------------------
    def submit_transaction(self, oid: str, mutation: Mutation,
                           at_version: Eversion,
                           log_entries: List[LogEntry],
                           on_all_commit: Callable[[int], None]) -> None:
        # object info read once; stores apply mutations synchronously at
        # queue time, so this reflects every previously submitted op
        info = self.get_object_info(oid)
        if mutation.create and info is not None:
            on_all_commit(-17)           # -EEXIST: exclusive create
            return
        txn = self._lower(oid, mutation, at_version, info)
        wire_entries = [e.to_dict() for e in log_entries]
        op = _RepOp(self.new_tid(), on_all_commit)
        self.in_flight[op.tid] = op
        replicas = [(s, o) for s, o in self.host.acting_shards()
                    if o is not None]
        for shard, osd in replicas:
            op.pending.add(osd)
        enc = txn.encode()
        for shard, osd in replicas:
            if osd == self.host.whoami:
                continue
            rep = MOSDRepOp(
                pgid=self.host.pgid_str, from_osd=self.host.whoami,
                tid=op.tid, epoch=self.host.epoch, txn=enc,
                log_entries=wire_entries, at_version=at_version,
                trace_id=mutation.trace_id,
                parent_span_id=mutation.parent_span_id)
            rep.stamp_hop("client_send")
            self.host.send_shard(osd, rep)
        tid = op.tid
        cmsg = mutation.client_msg

        def _local_committed(t=tid, m=cmsg):
            if m is not None:
                # local store commit: the client waterfall's
                # store_apply ends here; the replica ack wait that
                # follows charges to peer_ack_wait
                m.stamp_hop("store_apply")
            self._committed(t, self.host.whoami)
        self._apply_local(txn, wire_entries, _local_committed)

    def _lower(self, oid: str, mut: Mutation, at_version: Eversion,
               info: Optional[ObjectInfo]) -> Transaction:
        """Logical mutation -> one store transaction, applied identically
        on every replica (collection names match on all OSDs)."""
        from .snaps import SS_ATTR
        coll = self.host.coll
        obj = GHObject(oid, -1)
        txn = Transaction()
        if mut.clone_to is not None:
            # COW the pre-write head into the snapshot clone (reference
            # make_writeable's clone step) — store-level clone, the
            # store's COW machinery does the copying
            cobj = GHObject(mut.clone_to, -1)
            txn.clone(coll, obj, cobj)
            txn.rmattr(coll, cobj, SS_ATTR)   # clones carry no SnapSet
            if mut.clone_attrs:
                txn.setattrs(coll, cobj, mut.clone_attrs)
        for aux in mut.aux_remove:
            txn.remove(coll, GHObject(aux, -1))
        if mut.delete:
            txn.remove(coll, obj)
            if mut.snapdir_set is not None:
                # clones survive the head: SnapSet moves to the snapdir
                # companion (reference pre-octopus snapdir objects)
                sd_oid, ss, sd_oi = mut.snapdir_set
                sd = GHObject(sd_oid, -1)
                txn.touch(coll, sd)
                txn.setattr(coll, sd, SS_ATTR, ss)
                txn.setattr(coll, sd, OI_ATTR, sd_oi)
            return txn
        info = info or ObjectInfo()
        new_size = info.size
        if mut.rollback_from is not None:
            # head becomes the clone's content (reference rollback's
            # _rollback_to): wipe, then store-clone back
            txn.remove(coll, obj)
            txn.clone(coll, GHObject(mut.rollback_from, -1), obj)
            new_size = mut.rollback_size
        txn.touch(coll, obj)
        for off, data in mut.writes:
            txn.write(coll, obj, off, data)
            new_size = max(new_size, off + len(data))
        if mut.truncate is not None:
            txn.truncate(coll, obj, mut.truncate)
            new_size = mut.truncate
        if mut.snapset is not None:
            txn.setattr(coll, obj, SS_ATTR, mut.snapset)
        txn.setattr(coll, obj, OI_ATTR,
                    ObjectInfo(size=new_size,
                               version=at_version).encode())
        for name, value in mut.attrs.items():
            if value is None:
                txn.rmattr(coll, obj, "u_" + name)
            else:
                txn.setattr(coll, obj, "u_" + name, value)
        if mut.omap_clear:
            txn.omap_clear(coll, obj)
        if mut.omap_set:
            txn.omap_setkeys(coll, obj, mut.omap_set)
        if mut.omap_rm:
            txn.omap_rmkeys(coll, obj, mut.omap_rm)
        return txn

    def _apply_local(self, txn: Transaction, wire_entries: List[dict],
                     on_commit: Callable[[], None]) -> None:
        self.host.prepare_log_txn(txn, wire_entries)
        txn.register_on_commit(
            lambda: self.host.on_local_commit(on_commit))
        self.host.store.queue_transactions([txn], op="client_write")

    def _committed(self, tid: int, osd: int) -> None:
        op = self.in_flight.get(tid)
        if op is None:
            return
        op.pending.discard(osd)
        if not op.pending:
            del self.in_flight[tid]
            op.on_all_commit(0)

    # ------------------------------------------------------------------
    # read path: local, the primary holds a full copy
    # ------------------------------------------------------------------
    def objects_read(self, oid: str, offset: int, length: int,
                     cb: Callable[[int, bytes], None],
                     trace=(0, 0), hop_msg=None) -> None:
        if hop_msg is not None:
            hop_msg.stamp_hop("read_queued")
        obj = GHObject(oid, -1)
        try:
            data = self.host.store.read(self.host.coll, obj, offset,
                                        length)
        except FileNotFoundError:
            cb(-2, b"")
            return
        except OSError:
            # store-level csum mismatch (BlockStore EIO): surface it
            # — scrub repair-via-recovery re-homes a good replica
            cb(-5, b"")
            return
        if hop_msg is not None:
            # replicated reads are local: the store call above IS the
            # shard read (no sub-op round trip, no decode window)
            hop_msg.stamp_hop("shard_read")
        cb(0, data)

    # ------------------------------------------------------------------
    # recovery: push the full object
    # ------------------------------------------------------------------
    def recover_object(self, oid: str, version: Eversion,
                       missing_on: List[Tuple[int, int]],
                       cb: Callable[[int], None]) -> None:
        if oid in self.recovery_ops:
            cb(-16)
            return
        rec = _RecOp(oid, cb)
        rec.version = version
        obj = GHObject(oid, -1)
        # a primary that ITSELF needs the object must not source from
        # its own store — any local copy is a stale prior version and
        # self-"recovery" from it would silently resurrect old bytes
        self_missing = any(o == self.host.whoami
                           for _, o in missing_on)
        have_local = False
        if not self_missing:
            try:
                data = self.host.store.read(self.host.coll, obj)
                attrs = self.host.store.getattrs(self.host.coll, obj)
                omap = self.host.store.omap_get(self.host.coll, obj)
                have_local = True
            except OSError:
                # missing OR store-csum EIO (BlockStore bitrot): our
                # copy cannot source the push — pull from a holder
                pass
        if not have_local:
            # pull from a surviving holder (reference
            # prep_object_replica_pushes -> recover_primary pull path,
            # MOSDPGPull).  Rotate holders across retries: a holder
            # that silently lacks the data (lost disk) never answers,
            # and re-asking it forever wedges recovery.
            missing_osds = {o for _, o in missing_on}
            holders = [(s, o) for s, o in self.host.acting_shards()
                       if o is not None and o != self.host.whoami
                       and o not in missing_osds]
            # post-split strays / migrated-away copies can serve too
            for s, o in self.host.extra_recovery_sources(oid):
                if o != self.host.whoami and o not in missing_osds \
                        and all(o != ho for _, ho in holders):
                    holders.append((s, o))
            if not holders:
                self._pull_attempts.pop(oid, None)
                cb(-5)                   # nobody has it
                return
            attempt = self._pull_attempts.get(oid, 0)
            self._pull_attempts[oid] = attempt + 1
            self.recovery_ops[oid] = rec
            rec.push_after_pull = [
                (s, o) for s, o in missing_on
                if o is not None and o != self.host.whoami]
            shard, osd = holders[attempt % len(holders)]
            self.host.send_shard(osd, MOSDPGPull(
                pgid=self.host.pgid_str, shard=shard,
                from_osd=self.host.whoami, epoch=self.host.epoch,
                oids=[oid]))
            return
        self.recovery_ops[oid] = rec
        self._pull_attempts.pop(oid, None)   # completed via local copy
        self._push_to(rec, data, attrs, omap,
                      [(s, o) for s, o in missing_on
                       if o is not None and o != self.host.whoami])

    def _push_to(self, rec: _RecOp, data: bytes,
                 attrs: Dict[str, bytes], omap: Dict[str, bytes],
                 targets: List[Tuple[int, int]]) -> None:
        if not targets:
            self.recovery_ops.pop(rec.oid, None)
            rec.cb(0)
            return
        for shard, osd in targets:
            rec.pending.add(osd)
        for shard, osd in targets:
            self.host.send_shard(osd, MOSDPGPush(
                pgid=self.host.pgid_str, shard=shard,
                from_osd=self.host.whoami, epoch=self.host.epoch,
                pushes=[PushOp(oid=rec.oid, data=data, attrs=attrs,
                               omap=omap, version=rec.version)]))

    def _pulled(self, push: PushOp) -> None:
        """A pull answer landed and committed locally: forward the
        object to the remaining missing replicas."""
        rec = self.recovery_ops.get(push.oid)
        if rec is None:
            return
        self._pull_attempts.pop(push.oid, None)
        self._push_to(rec, push.data, dict(push.attrs),
                      dict(push.omap), rec.push_after_pull)

    def _apply_push(self, push: PushOp,
                    on_commit: Callable[[], None]) -> None:
        coll = self.host.coll
        obj = GHObject(push.oid, -1)
        # a LATE answer to an abandoned/rotated pull can arrive after
        # the object already advanced: never let an older version
        # overwrite newer bytes (strictly-newer check only — an
        # equal-version push is a scrub repair of corrupt data and
        # must apply)
        info = self.get_object_info(push.oid)
        if info is not None and \
                tuple(info.version) > tuple(push.version):
            on_commit()
            return
        txn = Transaction()
        # remove-then-recreate so stale attrs/omap don't survive
        txn.remove(coll, obj)
        txn.touch(coll, obj)
        if push.data:
            txn.write(coll, obj, 0, push.data)
        if push.attrs:
            txn.setattrs(coll, obj, push.attrs)
        if push.omap:
            txn.omap_setkeys(coll, obj, push.omap)

        def committed() -> None:
            self.host.note_object_recovered(push.oid, push.version)
            on_commit()
        txn.register_on_commit(
            lambda: self.host.on_local_commit(committed))
        self.host.store.queue_transactions([txn], op="recovery_push")

    def _push_acked(self, oid: str, osd: int) -> None:
        rec = self.recovery_ops.get(oid)
        if rec is None:
            return
        rec.pending.discard(osd)
        if not rec.pending:
            del self.recovery_ops[oid]
            rec.cb(0)

    # ------------------------------------------------------------------
    # message dispatch
    # ------------------------------------------------------------------
    def handle_message(self, msg) -> bool:
        if isinstance(msg, MOSDRepOp):
            span = self.host.trace_span(
                "rep_sub_write", msg.trace_id,
                getattr(msg, "parent_span_id", 0))
            if span is not None:
                span.tag("pgid", msg.pgid).tag("from",
                                               msg.from_osd).finish()
            txn = Transaction.decode(msg.txn)

            def _applied(m=msg):
                m.stamp_hop("store_apply")
                reply = MOSDRepOpReply(
                    pgid=self.host.pgid_str,
                    from_osd=self.host.whoami, tid=m.tid,
                    epoch=self.host.epoch)
                # ledger rides the round trip back to the primary
                if m.hops:
                    reply.hops = dict(m.hops)
                reply.stamp_hop("commit_sent")
                self.host.send_shard(m.from_osd, reply)
            self._apply_local(txn, msg.log_entries, _applied)
            return True
        if isinstance(msg, MOSDRepOpReply):
            # replica round-trip waterfall closes at the primary
            msg.stamp_hop("client_complete")
            _obs = getattr(self.host, "observe_hops", None)
            if _obs is not None:
                _obs(msg.hops)
            self._committed(msg.tid, msg.from_osd)
            return True
        if isinstance(msg, MOSDPGPush):
            for push in msg.pushes:
                rec = self.recovery_ops.get(push.oid)
                if rec is not None and not rec.pending:
                    # answer to our pull: apply locally, then fan out
                    self._apply_push(
                        push, lambda p=push: self._pulled(p))
                else:
                    self._apply_push(
                        push,
                        lambda p=push: self.host.send_shard(
                            msg.from_osd, MOSDPGPushReply(
                                pgid=self.host.pgid_str,
                                shard=msg.shard,
                                from_osd=self.host.whoami,
                                epoch=self.host.epoch, oids=[p.oid])))
            return True
        if isinstance(msg, MOSDPGPushReply):
            for oid in msg.oids:
                self._push_acked(oid, msg.from_osd)
            return True
        if isinstance(msg, MOSDPGPull):
            for oid in msg.oids:
                obj = GHObject(oid, -1)
                try:
                    data = self.host.store.read(self.host.coll, obj)
                    attrs = self.host.store.getattrs(self.host.coll,
                                                     obj)
                    omap = self.host.store.omap_get(self.host.coll,
                                                    obj)
                    info = self.get_object_info(oid)
                    ver = info.version if info else (0, 0)
                except OSError:
                    # missing or csum-EIO copy: either way we cannot
                    # serve it; silence lets the puller rotate to
                    # another holder
                    continue
                self.host.send_shard(msg.from_osd, MOSDPGPush(
                    pgid=self.host.pgid_str, shard=msg.shard,
                    from_osd=self.host.whoami, epoch=self.host.epoch,
                    pushes=[PushOp(oid=oid, data=data, attrs=attrs,
                                   omap=omap, version=ver)]))
            return True
        return False

    def inflight_writes(self) -> int:
        return len(self.in_flight)

    def build_scrub_map(self, deep: bool) -> Dict[str, dict]:
        """Full-object snapshot (reference be_scan_list; deep CRCs per
        ReplicatedBackend::be_deep_scrub, ReplicatedBackend.cc:614 —
        whole-object data hash, omap hash, attr hash)."""
        from ..utils.crc import crc32c
        out: Dict[str, dict] = {}
        store = self.host.store
        coll = self.host.coll
        conf = getattr(self.host, "conf", None)
        stride = conf["osd_deep_scrub_stride"] if conf else 512 << 10
        for obj in store.collection_list(coll):
            if obj.oid.startswith("_pgmeta"):
                continue
            try:
                st = store.stat(coll, obj)
                entry: Dict[str, object] = {"size": st.size}
                info = self.get_object_info(obj.oid)
                entry["oi_version"] = list(info.version) if info else None
                if deep:
                    # stride-wise CRC: bounded read buffer on huge
                    # objects (reference osd_deep_scrub_stride)
                    dc = 0
                    off = 0
                    while off < st.size:
                        dc = crc32c(store.read(coll, obj, off,
                                               stride), dc)
                        off += stride
                    entry["data_crc"] = dc
                    oc = 0
                    omap = store.omap_get(coll, obj)
                    for k in sorted(omap):
                        oc = crc32c(k.encode() + b"\0" + omap[k],
                                    oc)
                    entry["omap_crc"] = oc
                    ac = 0
                    attrs = store.getattrs(coll, obj)
                    for k in sorted(attrs):
                        ac = crc32c(k.encode() + b"\0" + attrs[k],
                                    ac)
                    entry["attrs_crc"] = ac
            except OSError:
                # missing OR store-csum EIO (BlockStore verify): both
                # scrub as read_error and repair via recovery
                entry = {"error": "read_error"}
            out[obj.oid] = entry
        return out

    def on_change(self) -> None:
        self.in_flight.clear()
        self.recovery_ops.clear()
        self._pull_attempts.clear()
