"""Op scheduler: mClock-style QoS over the OSD's sharded op queues.

Python-native equivalent of the reference's OpScheduler seam
(reference src/osd/scheduler/OpScheduler.{h,cc} +
mClockScheduler.{h,cc}): client, recovery and scrub work stop sharing
a plain FIFO — each class gets a *reservation* (tokens/sec it is
guaranteed), a *weight* (how spare capacity is split) and a *limit*
(tokens/sec it may never exceed), the dmClock triple.

The implementation is a token-bucket reduction of dmClock that keeps
its observable scheduling behavior at OSD scale:

* a class below its reservation is served FIRST (reservation phase);
* among classes past reservation but under limit, spare capacity is
  split by weight (largest deficit first — weighted round robin);
* a class at its limit waits even if the queue is otherwise idle only
  when ``hard_limits`` is set (the reference's mClock profiles
  likewise treat limits as soft for background classes by default:
  idle capacity may be used).

Classes mirror the reference's op classes (osd_op_queue mclock_scheduler,
common/options.cc osd_mclock_scheduler_client_res/wgt/lim etc.):
``client`` > ``recovery`` > ``scrub`` by default weights, with a
client reservation so recovery storms cannot starve foreground IO.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional, Tuple

# (reservation tokens/s, weight, limit tokens/s; 0 = unlimited)
DEFAULT_QOS: Dict[str, Tuple[float, float, float]] = {
    "client": (100.0, 100.0, 0.0),
    "peering": (50.0, 50.0, 0.0),
    "recovery": (0.0, 10.0, 0.0),
    "scrub": (0.0, 5.0, 0.0),
}


class _ClassQueue:
    def __init__(self, res: float, wgt: float, lim: float):
        self.q: deque = deque()
        self.res = res
        self.wgt = wgt
        self.lim = lim
        self.res_tokens = 0.0        # reservation bucket
        self.lim_tokens = 0.0        # limit bucket (when lim > 0)
        self.vdeficit = 0.0          # weighted-fair deficit counter
        self.served = 0
        self.depth_hwm = 0           # max queued depth ever observed


class OpScheduler:
    """One scheduler per op-queue shard (reference op_shardedwq +
    OpSchedulerItem).  ``enqueue(cls, item)``; ``dequeue(timeout)``
    -> (cls, item) | None; ``close()`` wakes everyone with None."""

    def __init__(self, qos: Optional[Dict] = None,
                 hard_limits: bool = False, fifo: bool = False):
        self._lock = threading.Condition()
        self._classes: Dict[str, _ClassQueue] = {}
        self._qos = dict(DEFAULT_QOS)
        if qos:
            self._qos.update(qos)
        self.hard_limits = hard_limits
        # fifo mode (reference osd_op_queue=fifo): plain arrival order,
        # no QoS — one queue, classes ignored
        self.fifo = fifo
        self._fifo_q: deque = deque()
        self._last_refill = time.monotonic()
        self._closed = False
        for name, (res, wgt, lim) in self._qos.items():
            self._classes[name] = _ClassQueue(res, wgt, lim)

    def _class(self, name: str) -> _ClassQueue:
        cq = self._classes.get(name)
        if cq is None:
            cq = self._classes[name] = _ClassQueue(0.0, 1.0, 0.0)
        return cq

    def enqueue(self, cls: str, item) -> None:
        with self._lock:
            if self._closed:
                return
            if self.fifo:
                self._fifo_q.append((cls, item))
            else:
                cq = self._class(cls)
                cq.q.append(item)
                if len(cq.q) > cq.depth_hwm:
                    cq.depth_hwm = len(cq.q)
            self._lock.notify()

    def enqueue_front(self, cls: str, item) -> None:
        """Requeue at the head (reference requeue_front for retried
        items)."""
        with self._lock:
            if self._closed:
                return
            if self.fifo:
                self._fifo_q.appendleft((cls, item))
            else:
                self._class(cls).q.appendleft(item)
            self._lock.notify()

    def _refill(self) -> None:
        now = time.monotonic()
        dt = now - self._last_refill
        if dt <= 0:
            return
        self._last_refill = now
        for cq in self._classes.values():
            if cq.res > 0:
                cq.res_tokens = min(cq.res_tokens + cq.res * dt,
                                    cq.res)      # burst <= 1s worth
            if cq.lim > 0:
                cq.lim_tokens = min(cq.lim_tokens + cq.lim * dt,
                                    cq.lim)

    def _pick(self) -> Optional[str]:
        """Scheduling decision over non-empty classes."""
        ready = [(n, cq) for n, cq in self._classes.items() if cq.q]
        if not ready:
            return None
        # phase 1: reservations — a class holding reservation tokens
        # is owed service regardless of weights
        best = None
        for n, cq in ready:
            if cq.res > 0 and cq.res_tokens >= 1.0:
                if best is None or cq.res_tokens > \
                        self._classes[best].res_tokens:
                    best = n
        if best is not None:
            return best
        # phase 2: weighted fair over classes under their limit
        candidates = []
        for n, cq in ready:
            if self.hard_limits and cq.lim > 0 and cq.lim_tokens < 1.0:
                continue             # at limit: hold back
            candidates.append((n, cq))
        if not candidates:
            return None
        total_w = sum(cq.wgt for _, cq in candidates) or 1.0
        for n, cq in candidates:
            cq.vdeficit += cq.wgt / total_w
        best, best_cq = max(candidates,
                            key=lambda nc: nc[1].vdeficit)
        return best

    def _serve(self, name: str):
        """Pop + token/deficit bookkeeping for a picked class.
        Caller holds the lock."""
        cq = self._classes[name]
        item = cq.q.popleft()
        cq.served += 1
        if cq.res > 0:
            cq.res_tokens = max(0.0, cq.res_tokens - 1.0)
        if cq.lim > 0:
            cq.lim_tokens = max(0.0, cq.lim_tokens - 1.0)
        cq.vdeficit = max(0.0, cq.vdeficit - 1.0)
        return name, item

    def dequeue(self, timeout: Optional[float] = None):
        """-> (cls, item), or None on close/timeout."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._lock:
            while True:
                if self._closed:
                    return None
                if self.fifo:
                    if self._fifo_q:
                        return self._fifo_q.popleft()
                    if deadline is not None and \
                            time.monotonic() >= deadline:
                        return None
                    self._lock.wait(timeout)
                    continue
                self._refill()
                name = self._pick()
                if name is not None:
                    return self._serve(name)
                if any(cq.q for cq in self._classes.values()):
                    wait = 0.05      # token-gated work: refill tick
                else:
                    wait = None      # idle: sleep until an enqueue
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None \
                        else min(wait, remaining)
                self._lock.wait(wait)

    def dequeue_nowait(self):
        """Single-poll dequeue for reactor-tick draining (crimson):
        -> (cls, item) or None, never blocks.  Token-gated work stays
        queued; the caller's next tick retries after refill."""
        with self._lock:
            if self._closed:
                return None
            if self.fifo:
                return self._fifo_q.popleft() if self._fifo_q else None
            self._refill()
            name = self._pick()
            if name is None:
                return None
            return self._serve(name)

    def set_qos(self, qos: Dict[str, Tuple[float, float, float]]
                ) -> bool:
        """Live mClock retune (the mgr tuner module's actuation seam):
        update class (res, wgt, lim) triples on the RUNNING shard
        queues without a restart or queue drain.  Queued items, token
        buckets and deficit counters are preserved — only the rates
        change, so the next ``_refill``/``_pick`` already schedules
        under the new triples.  Returns True when anything changed.
        No-op in fifo mode (QoS is ignored there anyway)."""
        changed = False
        with self._lock:
            if self.fifo:
                return False
            for name, (res, wgt, lim) in qos.items():
                self._qos[name] = (res, wgt, lim)
                cq = self._classes.get(name)
                if cq is None:
                    cq = self._classes[name] = _ClassQueue(
                        res, wgt, lim)
                    changed = True
                    continue
                if (cq.res, cq.wgt, cq.lim) != (res, wgt, lim):
                    cq.res = res
                    cq.wgt = wgt
                    cq.lim = lim
                    # clamp stale burst credit to the new rates so a
                    # demoted class cannot coast on old tokens
                    cq.res_tokens = min(cq.res_tokens, res) \
                        if res > 0 else 0.0
                    if lim > 0:
                        cq.lim_tokens = min(cq.lim_tokens, lim)
                    changed = True
            if changed:
                self._lock.notify_all()
        return changed

    def queued(self) -> int:
        """Total items queued across all classes (admission
        backpressure reads this without touching per-class detail)."""
        with self._lock:
            if self.fifo:
                return len(self._fifo_q)
            return sum(len(cq.q) for cq in self._classes.values())

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    def stats(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {n: {"queued": len(cq.q), "served": cq.served,
                        "deficit": round(cq.vdeficit, 4),
                        "depth_hwm": cq.depth_hwm}
                    for n, cq in self._classes.items()}


def qos_from_conf(conf) -> Dict[str, Tuple[float, float, float]]:
    """Read the reference-style mclock knobs
    (osd_mclock_scheduler_<class>_{res,wgt,lim})."""
    out = {}
    for cls in ("client", "recovery", "scrub", "peering"):
        try:
            out[cls] = (
                float(conf[f"osd_mclock_scheduler_{cls}_res"]),
                float(conf[f"osd_mclock_scheduler_{cls}_wgt"]),
                float(conf[f"osd_mclock_scheduler_{cls}_lim"]))
        except KeyError:
            pass
    return out
