"""PG scrub: cross-shard consistency checking + repair.

Python-native equivalent of the reference's scrub machinery (reference
``src/osd/PG.cc`` chunky_scrub, ``src/osd/ScrubStore.cc``, and the
backend comparison hooks ``be_compare_scrubmaps`` /
``ReplicatedBackend::be_deep_scrub`` :614 / ``ECBackend::be_deep_scrub``
:2475): the primary gathers a ScrubMap from every acting shard
(``MRepScrub`` → ``MRepScrubMap``, reference MOSDRepScrub.h), compares,
records inconsistencies, and — on ``repair`` — marks the bad copies
missing and lets the normal recovery path rebuild them (reference
repair_object, PrimaryLogPG.cc).

Comparison rules:
- replicated: the authoritative copy is the majority by (size,
  data_crc, omap_crc, attrs_crc); shards disagreeing with it (or
  missing the object) are inconsistent.  Ties break toward the
  primary, like the reference's be_select_auth_object preference.
- EC: every shard self-checks its bytes against the HashInfo CRC
  (``hinfo_ok``); a False means that shard is corrupt.  Shard sizes
  must also match ``object_size_to_shard_size`` of the object size.

Scrub runs whole-PG in one pass (our PGs are test-scale; the
reference chunks the object range with scrubber.start/end and blocks
writes per chunk).  Write exclusion: new client writes queue on the
primary for the duration of the round and the snapshot waits for the
in-flight pipeline to drain (``kick``), so every shard's map
describes the same committed state."""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ..msg.messages import MRepScrub, MRepScrubMap
from ..utils.log import Dout

logger = Dout("scrub")


class Scrubber:
    """Per-PG scrub state machine (reference PG::Scrubber struct)."""

    def __init__(self, pg) -> None:
        self.pg = pg
        self.active = False
        self.started_at = 0.0
        self.deep = False
        self.repair = False
        self.tid = 0
        self._collected = False
        self.waiting_on: Dict[int, int] = {}     # shard -> osd
        self.maps: Dict[int, Dict[str, dict]] = {}   # shard -> scrub map
        # results of the last completed scrub
        self.last_scrub: float = 0.0
        self.last_deep_scrub: float = 0.0
        self.errors = 0
        self.inconsistent: Dict[str, List[int]] = {}  # oid -> bad shards

    # ---------------------------------------------------------------- #
    # primary side
    # ---------------------------------------------------------------- #
    def start(self, deep: bool, repair: bool) -> bool:
        """Kick off a scrub round (primary only, PG lock held).
        Refuses while the PG is degraded or recovering: a shard that
        hasn't been pushed its objects yet would read as inconsistent
        (the reference queues scrub behind recovery the same way)."""
        pg = self.pg
        if self.active or not pg.is_primary():
            return False
        if pg.num_missing() > 0 or None in pg.acting or \
                len([o for o in pg.acting if o is not None]) < \
                pg.pool.min_size:
            return False
        self.active = True
        self.started_at = time.monotonic()
        # wall-clock twin of started_at: ledger stamps are absolute
        # wall time (cross-daemon alignable), monotonic is not
        self._started_wall = time.time()
        self.deep = deep
        self.repair = repair
        self.tid += 1
        self.maps = {}
        self.waiting_on = {}
        self._collected = False
        # snapshots must all describe the same committed state: new
        # writes are blocked (write_blocked -> PG queues them) and the
        # map collection waits until in-flight writes drain (the
        # reference blocks writes on the scrubbed chunk range)
        self.kick()
        return True

    def write_blocked(self) -> bool:
        """Client writes queue while a scrub round is running."""
        return self.active

    def kick(self) -> None:
        """Collect the maps once the write pipeline is empty (called
        from start, write completions, and the OSD tick)."""
        pg = self.pg
        if not self.active or self._collected:
            return
        if pg.backend.inflight_writes() > 0:
            return
        self._collected = True
        # replicated PGs carry own_shard=-1 but appear in acting_shards
        # under their acting index — key the local map consistently so
        # compare/repair can resolve it back to an OSD
        own = pg.own_shard
        if own < 0:
            for shard, osd in pg.acting_shards():
                if osd == pg.whoami:
                    own = shard
                    break
        self._own_key = own
        self.maps[own] = pg.backend.build_scrub_map(self.deep)
        for shard, osd in pg.acting_shards():
            if osd is None or osd == pg.whoami:
                continue
            self.waiting_on[shard] = osd
            pg.send_shard(osd, MRepScrub(
                pgid=pg.pgid_str, shard=shard, from_osd=pg.whoami,
                tid=self.tid, epoch=pg.epoch, deep=self.deep))
        if not self.waiting_on:
            self._finish()

    def reset(self) -> None:
        """Abort an in-flight round (interval change / peer loss);
        results of completed rounds are kept."""
        self.active = False
        self.waiting_on = {}
        self.maps = {}
        self.pg.requeue_scrub_waiters()

    def maybe_abort_stuck(self, timeout: float = 30.0) -> bool:
        """A replica that died mid-round never sends its map; without
        this the scrubber would stay active forever and block every
        future scrub (reference scrub_reserve timeouts)."""
        if self.active and \
                time.monotonic() - self.started_at > timeout:
            logger.dwarn("%s scrub round timed out waiting on %s",
                         self.pg.pgid_str, dict(self.waiting_on))
            self.reset()
            return True
        return False

    def handle_rep_scrub_map(self, msg: MRepScrubMap) -> None:
        """A shard's map arrived (primary side, PG lock held)."""
        if not self.active or msg.tid != self.tid:
            return
        if msg.shard in self.waiting_on:
            del self.waiting_on[msg.shard]
            self.maps[msg.shard] = msg.scrub_map
        if not self.waiting_on:
            self._finish()

    # ---------------------------------------------------------------- #
    # replica side
    # ---------------------------------------------------------------- #
    def handle_rep_scrub(self, msg: MRepScrub) -> None:
        """Build and return the local map (replica, PG lock held)."""
        pg = self.pg
        smap = pg.backend.build_scrub_map(msg.deep)
        pg.send_shard(msg.from_osd, MRepScrubMap(
            pgid=pg.pgid_str, shard=msg.shard, from_osd=pg.whoami,
            tid=msg.tid, scrub_map=smap))

    # ---------------------------------------------------------------- #
    # compare + repair
    # ---------------------------------------------------------------- #
    def _finish(self) -> None:
        pg = self.pg
        inconsistent: Dict[str, List[int]] = {}
        self.syndrome_errors = 0     # per-round (see _compare_ec)
        if pg.pool.is_erasure():
            self._compare_ec(inconsistent)
        else:
            self._compare_replicated(inconsistent)
        self.inconsistent = inconsistent
        self.errors = sum(len(v) for v in inconsistent.values()) \
            + self.syndrome_errors
        now = time.time()
        self.last_scrub = now
        if self.deep:
            self.last_deep_scrub = now
        self.active = False
        if inconsistent:
            logger.dwarn("%s scrub found %d errors on %d objects",
                         pg.pgid_str, self.errors, len(inconsistent))
        auto = False
        try:
            auto = bool(pg.conf["osd_scrub_auto_repair"])
        except Exception:
            pass
        if (self.repair or auto) and inconsistent:
            # reference osd_scrub_auto_repair: scrub-found errors go
            # straight to repair without an operator `pg repair`
            self._repair(inconsistent)
        # the whole round as one synthetic ledger interval: pg_locked
        # (round start) -> scrub_window (compare done), charged to the
        # recovery-class accumulator + scrub SLO class
        t0 = getattr(self, "_started_wall", 0.0)
        if t0:
            obs = getattr(pg, "observe_hops", None)
            if obs is not None:
                obs({"pg_locked": t0, "scrub_window": now},
                    kind="recovery")
            slo = getattr(pg.service, "slo", None)
            if slo is not None:
                slo.observe("scrub", max(0.0, now - t0),
                            ok=(self.errors == 0))
        pg.requeue_scrub_waiters()
        pg.service.kick_recovery(pg)

    def _all_oids(self) -> List[str]:
        oids = set()
        for smap in self.maps.values():
            oids.update(smap)
        return sorted(oids)

    def _compare_replicated(self, out: Dict[str, List[int]]) -> None:
        """Majority-authoritative compare (reference
        be_compare_scrubmaps; keys mirror be_select_auth_object)."""
        keys = ["size"]
        if self.deep:
            keys += ["data_crc", "omap_crc", "attrs_crc"]
        own = getattr(self, "_own_key", self.pg.own_shard)
        for oid in self._all_oids():
            sigs: Dict[int, Optional[Tuple]] = {}
            for shard, smap in self.maps.items():
                e = smap.get(oid)
                if e is None or "error" in e:
                    sigs[shard] = None
                else:
                    sigs[shard] = tuple(e.get(k) for k in keys)
            # majority signature; primary wins ties
            counts: Dict[Tuple, int] = {}
            for s in sigs.values():
                if s is not None:
                    counts[s] = counts.get(s, 0) + 1
            if not counts:
                continue
            best = max(counts.items(),
                       key=lambda kv: (kv[1], kv[0] == sigs.get(own)))[0]
            bad = [sh for sh, s in sigs.items() if s != best]
            if bad:
                out[oid] = sorted(bad)

    def _compare_ec(self, out: Dict[str, List[int]]) -> None:
        """EC shards self-check vs HashInfo; sizes must match the
        object size's shard footprint (reference ECBackend.cc:2475).

        With ``osd_deep_scrub_syndrome`` each deep map also carries
        per-object GF-syndrome CRC partials (ecbackend
        _scrub_fill_crcs): XORing them across the full shard set is
        the linear CRC of the whole code word's syndrome vector —
        nonzero means the stripe is inconsistent even when every
        shard's own CRC matches its HashInfo (e.g. a stale-but-
        self-consistent shard).  The check cannot LOCALIZE the bad
        shard, so a syndrome hit on an object with no per-shard
        culprits counts as an error without scheduling repair."""
        for oid in self._all_oids():
            bad: List[int] = []
            syn: Optional[List[int]] = None
            nsyn = 0
            for shard, smap in self.maps.items():
                e = smap.get(oid)
                if e is None or "error" in e:
                    bad.append(shard)
                    continue
                if e.get("hinfo_ok") is False:
                    bad.append(shard)
                    continue
                expect = e.get("expect_size")
                if expect is not None and e.get("size") != expect:
                    bad.append(shard)
                    continue
                parts = e.get("syndrome_partials")
                if parts:
                    nsyn += 1
                    if syn is None:
                        syn = list(parts)
                    else:
                        syn = [a ^ b for a, b in zip(syn, parts)]
            if bad:
                out[oid] = sorted(bad)
            elif syn is not None and nsyn == len(self.maps) and \
                    any(syn):
                # full shard set, every per-shard check clean, but
                # the whole-code-word syndrome is nonzero: count the
                # inconsistency (unlocalizable -> no shard listed,
                # no auto-repair)
                self.syndrome_errors = getattr(
                    self, "syndrome_errors", 0) + 1

    def _repair(self, inconsistent: Dict[str, List[int]]) -> None:
        """Mark bad copies missing so recovery rebuilds them from the
        authoritative/surviving copies (reference repair_object +
        recovery)."""
        pg = self.pg
        shard_osd = dict(pg.acting_shards())
        for oid, bad_shards in inconsistent.items():
            # version to recover to: any good shard's oi_version
            version = None
            for shard, smap in self.maps.items():
                if shard in bad_shards:
                    continue
                e = smap.get(oid)
                if e and e.get("oi_version"):
                    version = tuple(e["oi_version"])
                    break
            if version is None:
                logger.dwarn("%s repair: no authoritative copy of %s",
                             pg.pgid_str, oid)
                continue
            for shard in bad_shards:
                osd = shard_osd.get(shard)
                if osd is None:
                    continue
                if osd == pg.whoami and not pg.pool.is_erasure():
                    # the primary's own replica is the corrupt one:
                    # drop it so recovery takes the pull path from a
                    # good replica instead of re-pushing bad bytes
                    # (reference recover_primary pull)
                    from ..store.objectstore import GHObject, Transaction
                    obj = GHObject(oid, pg.own_shard)
                    if pg.store.exists(pg.coll, obj):
                        txn = Transaction()
                        txn.remove(pg.coll, obj)
                        pg.store.queue_transactions([txn],
                                                    op="scrub_repair")
                pg.mark_shard_missing(oid, version, shard, osd)

    def dump(self) -> Dict:
        return {
            "active": self.active,
            "errors": self.errors,
            "syndrome_errors": getattr(self, "syndrome_errors", 0),
            "inconsistent": dict(self.inconsistent),
            "last_scrub": self.last_scrub,
            "last_deep_scrub": self.last_deep_scrub,
        }
