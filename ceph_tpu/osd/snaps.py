"""RADOS object snapshots: SnapSet, clone naming, read resolution.

Python-native equivalent of the reference's snapshot metadata
(reference src/osd/osd_types.h ``SnapSet`` + the clone machinery of
``PrimaryLogPG::make_writeable``, src/osd/PrimaryLogPG.cc): every
logical object ("head") carries a SnapSet xattr describing which
snapshot-era clones exist; a write whose SnapContext seq is newer than
the SnapSet's clones the head first (COW at object granularity), then
mutates.  Clones are ordinary objects named ``oid@<snapid-hex>`` —
on EC pools the clone lowers to a per-shard store clone of each chunk
object, so snapshotting never re-encodes (zero device work; the
store's COW does the rest).

Read resolution (reference PrimaryLogPG::find_object_context):

* snapid covered by a clone's ``clone_snaps`` -> read that clone;
* snapid >= SnapSet.seq -> the head is unchanged since the snap, read
  head;
* otherwise the object did not exist at that snap (the first write
  after the snap would have cloned and covered it) -> ENOENT.

When the head is deleted while clones remain, its SnapSet moves to a
"snapdir" companion object (reference pre-octopus snapdir design) and
moves back on recreate.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

# SnapSet xattr key (reference SS_ATTR "snapset")
SS_ATTR = "ss_"
# head read / no snap context (reference CEPH_NOSNAP); 0 = head here
HEAD_SNAP = 0


def clone_oid(oid: str, snapid: int) -> str:
    return f"{oid}@{snapid:x}"


def snapdir_oid(oid: str) -> str:
    return f"{oid}@snapdir"


def is_snap_oid(oid: str) -> bool:
    return "@" in oid


def head_of(oid: str) -> str:
    """head oid of a clone/snapdir oid (identity for heads)."""
    return oid.split("@", 1)[0]


class SnapContext:
    """Client-provided write context (reference SnapContext): the
    newest snap id the writer has seen plus the still-live snap ids,
    newest first."""

    def __init__(self, seq: int = 0, snaps: Optional[List[int]] = None):
        self.seq = seq
        self.snaps = list(snaps or [])

    def __bool__(self) -> bool:
        return self.seq > 0


class SnapSet:
    """Per-object snapshot metadata xattr (reference SnapSet)."""

    def __init__(self) -> None:
        self.seq = 0                     # newest snapc seq seen at write
        self.clones: List[int] = []      # clone ids, ascending
        self.clone_snaps: Dict[int, List[int]] = {}
        self.clone_size: Dict[int, int] = {}

    # -- write-side (make_writeable) ----------------------------------
    def needs_clone(self, snapc: SnapContext) -> bool:
        """A head that exists must be cloned before this write mutates
        it iff the writer has seen a snap newer than our last clone
        era (reference make_writeable's snapc.seq > snapset.seq) AND
        some LIVE snap would actually be covered — a stale context
        whose newer snaps were all removed must not cut an orphan
        clone covering nothing (it could never be trimmed)."""
        return snapc.seq > self.seq and \
            any(s > self.seq for s in snapc.snaps)

    def add_clone(self, snapc: SnapContext, head_size: int) -> int:
        """Record the COW clone for this write; returns the clone id
        (the snapc seq, like the reference's coid snap)."""
        cid = snapc.seq
        covered = sorted(s for s in snapc.snaps if s > self.seq)
        self.clones.append(cid)
        self.clones.sort()
        self.clone_snaps[cid] = covered
        self.clone_size[cid] = head_size
        self.seq = snapc.seq
        return cid

    def advance_seq(self, snapc: SnapContext) -> None:
        """Write over a non-existent/new head: no clone, but the era
        still advances so later snap reads resolve existence right."""
        self.seq = max(self.seq, snapc.seq)

    # -- read-side (find_object_context) ------------------------------
    def resolve_read(self, snapid: int) -> Tuple[str, Optional[int]]:
        """-> ("head", None) | ("clone", clone_id) | ("enoent", None).
        Strictly ``snapid > seq`` serves the head (reference
        find_object_context): an uncovered snapid <= seq means the
        object did not exist when that snap was taken (its creating
        write already carried a snapc at least that new, and a
        surviving pre-snap state would have been cloned)."""
        for cid in self.clones:
            if snapid in self.clone_snaps.get(cid, ()):
                return "clone", cid
        if snapid > self.seq:
            return "head", None
        return "enoent", None

    # -- trim ----------------------------------------------------------
    def trim(self, removed: set) -> List[int]:
        """Drop removed snap ids; returns clone ids left covering
        nothing (to be deleted by the trimmer)."""
        gone: List[int] = []
        for cid in list(self.clones):
            kept = [s for s in self.clone_snaps.get(cid, [])
                    if s not in removed]
            if kept:
                self.clone_snaps[cid] = kept
            else:
                self.clones.remove(cid)
                self.clone_snaps.pop(cid, None)
                self.clone_size.pop(cid, None)
                gone.append(cid)
        return gone

    @property
    def empty(self) -> bool:
        return not self.clones

    # -- wire ----------------------------------------------------------
    def encode(self) -> bytes:
        return json.dumps({
            "seq": self.seq, "clones": self.clones,
            "clone_snaps": {str(c): s
                            for c, s in self.clone_snaps.items()},
            "clone_size": {str(c): s
                           for c, s in self.clone_size.items()},
        }).encode()

    @classmethod
    def decode(cls, buf: bytes) -> "SnapSet":
        d = json.loads(buf.decode())
        ss = cls()
        ss.seq = d["seq"]
        ss.clones = list(d["clones"])
        ss.clone_snaps = {int(c): list(s)
                          for c, s in d["clone_snaps"].items()}
        ss.clone_size = {int(c): int(s)
                         for c, s in d["clone_size"].items()}
        return ss
