"""Device-mesh sharding for the batched erasure-code engine.

TPU-native replacement for the reference's intra-daemon parallelism
(sharded op queues + ShardedThreadPool, reference osd/OSD.h:1287) on the
device side: stripe batches from the PG write queue are sharded over a
2-D mesh —

  * ``dp`` (data-parallel) shards the stripe-batch axis, the analog of
    the sharded PG queue fan-out;
  * ``sp`` (sequence-parallel) shards the chunk-width axis, the analog of
    the stripe/Striper tiling of large objects (reference osdc/Striper.h:26,
    osd/ECUtil.h:27) — GF codes act per byte position, so width splits
    need no halo exchange.

Encode itself needs no collectives (placement is deliberate, like CRUSH);
the cluster step folds a per-shard digest with ``psum`` over both axes so
scrub-style integrity checks ride the ICI instead of the host network.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
try:                                    # jax >= 0.5
    from jax import shard_map
except ImportError:                     # jax 0.4.x experimental home
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.jax_engine import _matmul_mod2


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Sequence[str] = ("dp", "sp"),
              sp: Optional[int] = None) -> Mesh:
    """Build a 2-D mesh over the available devices.

    ``sp`` (intra-chunk width axis) defaults to the largest factor of
    n that keeps ``dp >= sp`` — the dp axis (stripe batching) carries
    the bigger fan-out because stripe counts dwarf per-chunk width in
    the OSD workload, but a 16-chip mesh now gets sp=4 (not the old
    hardcoded 2) and odd counts get their true largest small factor.
    Pass ``sp`` explicitly to override (must divide n)."""
    devices = jax.devices()
    n = n_devices or len(devices)
    n = min(n, len(devices))
    devices = devices[:n]
    if sp is None:
        sp = 1
        f = 1
        while f * f <= n:
            if n % f == 0:
                sp = f               # largest factor with dp >= sp
            f += 1
    if n % sp != 0:
        raise ValueError(f"sp={sp} does not divide {n} devices")
    dp = n // sp
    arr = np.array(devices).reshape(dp, sp)
    return Mesh(arr, axis_names=tuple(axis_names))


def resolve_mesh(n_devices: int = 0, sp: int = 0) -> Optional[Mesh]:
    """Resolve the production mesh from conf-style knobs (0 = auto).

    Returns ``None`` when the effective device count is 1 — a 1x1 mesh
    buys nothing and the backend must treat it as "no mesh" so the
    single-chip path stays byte-identical with zero overhead (ISSUE 12
    satellite: make_mesh single-device edge)."""
    try:
        avail = len(jax.devices())
    except Exception:
        return None
    n = n_devices or avail
    n = min(n, avail)
    if n <= 1:
        return None
    return make_mesh(n_devices=n, sp=sp or None)


def mesh_info(mesh: Optional[Mesh]) -> Optional[dict]:
    """JSON-able mesh shape summary for dump_device / bench records."""
    if mesh is None:
        return None
    dp = int(mesh.shape["dp"])
    sp = int(mesh.shape["sp"])
    return {
        "dp": dp,
        "sp": sp,
        "n_devices": dp * sp,
        "device_ids": [int(d.id) for d in mesh.devices.flat],
    }


def _fold_digest(parity_bits_sum: jnp.ndarray) -> jnp.ndarray:
    """Cheap device-side integrity digest of a parity block (scrub analog,
    reference ECBackend.cc:2475 per-shard CRC): xor-fold is replaced by a
    modular sum so it can ride an XLA psum."""
    return jnp.sum(parity_bits_sum.astype(jnp.uint32) * jnp.uint32(2654435761))


def sharded_encode_fn(mesh: Mesh, w: int):
    """Returns jit(fn)(B, data) with data [batch, k, L] sharded
    (dp, None, sp) and the bitmatrix replicated; output parity sharded the
    same way.  Per-shard work is the same bit-plane MXU matmul as
    single-chip, so chunks stay bit-exact."""

    def local_encode(B, data):
        # data: local shard [b_loc, k, l_loc] with l_loc byte-aligned
        batch, k, L = data.shape
        wbytes = max(1, w // 8)
        if wbytes == 1:
            words = data
        else:
            dt = {2: jnp.uint16, 4: jnp.uint32}[wbytes]
            parts = [data[..., i::wbytes].astype(dt) << (8 * i)
                     for i in range(wbytes)]
            words = functools.reduce(jnp.bitwise_or, parts)
        shifts = jnp.arange(w, dtype=words.dtype)
        bits = ((words[..., None, :] >> shifts[:, None]) & 1).astype(jnp.int8)
        bits = bits.reshape(batch, k * w, -1)
        out_bits = _matmul_mod2(B, bits)
        R = out_bits.shape[1]
        out_bits = out_bits.reshape(batch, R // w, w, -1)
        weights = (jnp.uint32(1) << jnp.arange(w, dtype=jnp.uint32))
        out_words = jnp.sum(out_bits.astype(jnp.uint32) * weights[:, None],
                            axis=-2)
        if wbytes == 1:
            parity = out_words.astype(jnp.uint8)
        else:
            parts = [((out_words >> (8 * i)) & 0xFF).astype(jnp.uint8)
                     for i in range(wbytes)]
            parity = jnp.stack(parts, axis=-1).reshape(
                out_words.shape[:-1] + (-1,))
        digest = _fold_digest(jnp.sum(out_bits.astype(jnp.uint32)))
        digest = jax.lax.psum(jax.lax.psum(digest, "dp"), "sp")
        return parity, digest

    fn = shard_map(
        local_encode, mesh=mesh,
        in_specs=(P(None, None), P("dp", None, "sp")),
        out_specs=(P("dp", None, "sp"), P()))
    return jax.jit(fn)


def sharded_encode_gf8_fn(mesh: Mesh, coding_matrix: np.ndarray,
                          with_digest: bool = True):
    """Sharded w=8 fast path: the per-shard kernel is the SAME one the
    single-chip backend routes to (fused bit-plane MXU pallas kernel on
    TPU, XOR/xtime chain elsewhere — ops.jax_engine.gf8_fn routing)
    under a (dp, sp) sharding — GF(2^8) math is per byte position, so
    width shards need no halo and the only collective remains the
    integrity-digest psum.  ``coding_matrix`` is static (per-pool),
    like the single-chip fast path."""
    from ..ops import jax_engine as je
    inner = je.gf8_inner(coding_matrix)

    if not with_digest:
        # production path (ShardedEncoder): no collective at all —
        # the integrity digest (and its two psums) is a scrub/dryrun
        # feature, not a per-write cost
        fn = shard_map(inner, mesh=mesh,
                       in_specs=(P("dp", None, "sp"),),
                       out_specs=P("dp", None, "sp"))
        return jax.jit(fn)

    def local_encode(data):
        parity = inner(data)
        digest = _fold_digest(jnp.sum(parity.astype(jnp.uint32)))
        digest = jax.lax.psum(jax.lax.psum(digest, "dp"), "sp")
        return parity, digest

    fn = shard_map(
        local_encode, mesh=mesh,
        in_specs=(P("dp", None, "sp"),),
        out_specs=(P("dp", None, "sp"), P()))
    return jax.jit(fn)


def sharded_rows_fn(mesh: Mesh, rows: np.ndarray, donate: bool = False):
    """Sharded w=8 GF row apply for the PRODUCTION dispatch path: the
    per-shard kernel is ``jax_engine.gf8_inner(rows)`` — the exact
    function the single-chip backend jits — wrapped in a no-collective
    ``shard_map`` over (dp, None, sp).  Serves both encode (rows = the
    coding matrix) and the PR 11 ``decode_batch_async`` recovery-row
    apply (rows = stacked recovery rows); per-shard math is the same
    kernel, so chunks stay bit-exact vs single-chip.  ``donate`` is
    only legal for square row sets (output bytes == input bytes)."""
    from ..ops import jax_engine as je
    fn = shard_map(je.gf8_inner(rows), mesh=mesh,
                   in_specs=(P("dp", None, "sp"),),
                   out_specs=P("dp", None, "sp"))
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def sharded_apply_fn(mesh: Mesh, w: int):
    """Sharded generic-w bitmatrix apply: jit(fn)(B, data) with the
    bitmatrix replicated and data sharded (dp, None, sp) — the mesh
    twin of ``jax_engine._apply_byte_domain`` (the path every encode
    rides on non-TPU backends, where the w=8 pallas fast path is off).
    No digest, no collectives: the per-shard body is the
    ``sharded_encode_fn`` word-pack -> ``_matmul_mod2`` -> repack
    pipeline, bit-exact by GF-linearity."""

    def local_apply(B, data):
        batch, k, L = data.shape
        wbytes = max(1, w // 8)
        if wbytes == 1:
            words = data
        else:
            dt = {2: jnp.uint16, 4: jnp.uint32}[wbytes]
            parts = [data[..., i::wbytes].astype(dt) << (8 * i)
                     for i in range(wbytes)]
            words = functools.reduce(jnp.bitwise_or, parts)
        shifts = jnp.arange(w, dtype=words.dtype)
        bits = ((words[..., None, :] >> shifts[:, None]) & 1).astype(jnp.int8)
        bits = bits.reshape(batch, k * w, -1)
        out_bits = _matmul_mod2(B, bits)
        R = out_bits.shape[1]
        out_bits = out_bits.reshape(batch, R // w, w, -1)
        weights = (jnp.uint32(1) << jnp.arange(w, dtype=jnp.uint32))
        out_words = jnp.sum(out_bits.astype(jnp.uint32) * weights[:, None],
                            axis=-2)
        if wbytes == 1:
            return out_words.astype(jnp.uint8)
        parts = [((out_words >> (8 * i)) & 0xFF).astype(jnp.uint8)
                 for i in range(wbytes)]
        return jnp.stack(parts, axis=-1).reshape(
            out_words.shape[:-1] + (-1,))

    fn = shard_map(local_apply, mesh=mesh,
                   in_specs=(P(None, None), P("dp", None, "sp")),
                   out_specs=P("dp", None, "sp"))
    return jax.jit(fn)


def shard_batch(mesh: Mesh, data: np.ndarray) -> jax.Array:
    """Place a host batch [batch, k, L] onto the mesh (dp, None, sp)."""
    sharding = NamedSharding(mesh, P("dp", None, "sp"))
    return jax.device_put(data, sharding)


# ---------------------------------------------------------------------------
# production wiring: the OSD batcher dispatches through this when the
# host has more than one device (VERDICT r2 Missing #5 — the mesh must
# be the data plane, not just the dryrun)
# ---------------------------------------------------------------------------

_DEFAULT_MESH = {"mesh": None, "checked": False}
_ENCODERS: dict = {}


def default_mesh() -> Optional[Mesh]:
    """Process-wide mesh over all local devices; None on single-device
    hosts (the common bench/test case), cached after first probe."""
    if not _DEFAULT_MESH["checked"]:
        _DEFAULT_MESH["checked"] = True
        try:
            if len(jax.devices()) > 1:
                _DEFAULT_MESH["mesh"] = make_mesh()
        except Exception:
            _DEFAULT_MESH["mesh"] = None
    return _DEFAULT_MESH["mesh"]


class _ShardedAsync:
    """AsyncBatch-shaped handle for a mesh-sharded encode (the batcher
    completion path calls wait() -> parity [B, m, L])."""

    def __init__(self, dev_parity, batch: int, L: int):
        self._dev = dev_parity
        self._batch = batch
        self._L = L

    def wait(self) -> np.ndarray:
        return np.asarray(self._dev)[:self._batch, :, :self._L]


class ShardedEncoder:
    """Mesh-wide encode with the single-chip async API shape.  Pads the
    stripe-batch axis to a dp multiple (zero stripes are harmless: the
    code is GF-linear); requires chunk length divisible by sp."""

    def __init__(self, mesh: Mesh, coding_matrix: np.ndarray):
        self.mesh = mesh
        self.dp = mesh.shape["dp"]
        self.sp = mesh.shape["sp"]
        self._fn = sharded_encode_gf8_fn(mesh, coding_matrix,
                                         with_digest=False)

    def encode_async(self, data: np.ndarray) -> Optional[_ShardedAsync]:
        B, k, L = data.shape
        if L % self.sp:
            return None
        Bp = -(-B // self.dp) * self.dp
        if Bp != B:
            data = np.concatenate(
                [data, np.zeros((Bp - B, k, L), np.uint8)], axis=0)
        parity = self._fn(shard_batch(self.mesh, data))
        return _ShardedAsync(parity, B, L)


def shared_encoder(ec_impl) -> Optional[ShardedEncoder]:
    """The process-cached mesh encoder for a codec, or None when the
    host is single-device or the codec isn't the w=8 byte-domain fast
    family (packet codes keep the single-device pallas path)."""
    mesh = default_mesh()
    if mesh is None:
        return None
    core = getattr(ec_impl, "core", None)
    if core is None or core.layout != "byte" or core.w != 8 \
            or core.coding_matrix is None:
        return None
    key = tuple(tuple(int(v) for v in row) for row in core.coding_matrix)
    enc = _ENCODERS.get(key)
    if enc is None:
        enc = ShardedEncoder(mesh, core.coding_matrix)
        _ENCODERS[key] = enc
    return enc
