"""RBD: block images on RADOS (reference src/librbd/, SURVEY §2.6)."""
from .image import RBD, Image, ImageNotFound  # noqa: F401
