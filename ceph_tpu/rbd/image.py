"""Block images over RADOS objects: striping, snapshots, clones.

Python-native equivalent of the reference's librbd core (reference
``src/librbd/`` 85.7k LoC): images are a header object plus data
objects of ``2^order`` bytes (reference rbd_header.<id> +
rbd_data.<id>.<objectno>, ImageCtx::get_object_name), with snapshots
and copy-on-write clones.

Where the reference builds snapshots on RADOS self-managed snaps
(librados snap contexts resolved inside the OSD), this implementation
keeps the OSD snapshot-free and does **generation-based client-side
COW**: every snapshot bumps the image generation; data object
``<img>.g<gen>.<objno>`` holds object ``objno``'s content as of
generation ``gen``.  Writes land in the current generation (copying
the newest older generation forward first — COW); reads resolve each
object to its newest generation ≤ the view's generation.  A clone
records (parent image, snap); unwritten extents fall through to the
parent's snapshot view exactly like the reference's parent overlap
reads (librbd/io/ReadResult parent fallback), and ``flatten`` copies
the parent data in and severs the link.

Header: ``rbd_header.<name>`` holds a JSON body (works on EC pools,
which have no omap) with size/order/generation/snaps/parent.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ..client.rados import IoCtx, RadosError

DEFAULT_ORDER = 22                    # 4 MiB objects, reference default
RBD_DIRECTORY = "rbd_directory"       # reference rbd_directory object


class ImageNotFound(RadosError):
    def __init__(self, name: str):
        super().__init__(2, f"image {name!r} not found")


def _header_oid(name: str) -> str:
    return f"rbd_header.{name}"


def _data_oid(name: str, gen: int, objectno: int) -> str:
    return f"rbd_data.{name}.g{gen}.{objectno:016x}"


class RBD:
    """Pool-level image operations (reference librbd.h RBD class)."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    # -- directory (reference cls_rbd rbd_directory) -------------------
    def _dir(self) -> List[str]:
        try:
            raw = self.ioctx.read(RBD_DIRECTORY)
            return json.loads(raw.decode()) if raw else []
        except RadosError:
            return []

    def _dir_update(self, names: List[str]) -> None:
        self.ioctx.write_full(RBD_DIRECTORY,
                              json.dumps(sorted(names)).encode())

    def list(self) -> List[str]:
        return self._dir()

    def create(self, name: str, size: int,
               order: int = DEFAULT_ORDER) -> None:
        if not 12 <= order <= 26:
            raise ValueError("order must be in [12, 26]")
        names = self._dir()
        if name in names:
            raise RadosError(17, f"image {name!r} exists")  # EEXIST
        header = {"size": size, "order": order, "gen": 0,
                  "snap_seq": 0, "snaps": {}, "parent": None,
                  "hwm": size}   # high-water size: bounds object scans
        self.ioctx.write_full(_header_oid(name),
                              json.dumps(header).encode())
        self._dir_update(names + [name])

    def remove(self, name: str) -> None:
        img = Image(self.ioctx, name)
        if img.header["snaps"]:
            raise RadosError(39, "image has snapshots")  # ENOTEMPTY
        img._remove_all_data()
        self.ioctx.remove(_header_oid(name))
        self._dir_update([n for n in self._dir() if n != name])

    def clone(self, parent_name: str, snap_name: str,
              child_name: str) -> None:
        """COW child of parent@snap (reference librbd clone: requires
        a protected snapshot; 'protected' here = we refuse snap
        removal while children exist, checked at snap_rm)."""
        parent = Image(self.ioctx, parent_name)
        snap = parent.header["snaps"].get(snap_name)
        if snap is None:
            raise RadosError(2, f"no snap {snap_name!r}")
        names = self._dir()
        if child_name in names:
            raise RadosError(17, f"image {child_name!r} exists")
        header = {"size": snap["size"], "order": parent.header["order"],
                  "gen": 0, "snap_seq": 0, "snaps": {},
                  "parent": {"image": parent_name, "snap": snap_name},
                  "hwm": snap["size"]}
        self.ioctx.write_full(_header_oid(child_name),
                              json.dumps(header).encode())
        self._dir_update(names + [child_name])

    def children(self, parent_name: str, snap_name: str) -> List[str]:
        out = []
        for name in self._dir():
            try:
                p = Image(self.ioctx, name).header.get("parent")
            except ImageNotFound:
                continue
            if p and p["image"] == parent_name \
                    and p["snap"] == snap_name:
                out.append(name)
        return out


class Image:
    """One open image (reference librbd::Image / ImageCtx).
    ``snap_name`` opens a read-only snapshot view."""

    def __init__(self, ioctx: IoCtx, name: str,
                 snap_name: Optional[str] = None):
        self.ioctx = ioctx
        self.name = name
        self.snap_name = snap_name
        self.header = self._load_header()
        if snap_name is not None and \
                snap_name not in self.header["snaps"]:
            raise RadosError(2, f"no snap {snap_name!r}")

    # -- header --------------------------------------------------------
    def _load_header(self) -> Dict:
        try:
            return json.loads(self.ioctx.read(
                _header_oid(self.name)).decode())
        except RadosError:
            raise ImageNotFound(self.name)

    def _save_header(self) -> None:
        self.ioctx.write_full(_header_oid(self.name),
                              json.dumps(self.header).encode())

    @property
    def object_size(self) -> int:
        return 1 << self.header["order"]

    def size(self) -> int:
        if self.snap_name is not None:
            return self.header["snaps"][self.snap_name]["size"]
        return self.header["size"]

    def stat(self) -> Dict:
        return {"size": self.size(), "order": self.header["order"],
                "object_size": self.object_size,
                "num_objs": (self.size() + self.object_size - 1)
                // self.object_size,
                "snapshot_count": len(self.header["snaps"]),
                "parent": self.header.get("parent")}

    # -- object resolution ---------------------------------------------
    def _view_gen(self) -> int:
        if self.snap_name is not None:
            return self.header["snaps"][self.snap_name]["gen"]
        return self.header["gen"]

    def _read_object(self, objectno: int, gen_limit: int) -> bytes:
        """Newest generation <= gen_limit holding this object; falls
        through to the parent snapshot view when cloned (reference
        parent overlap read)."""
        for gen in range(gen_limit, -1, -1):
            try:
                return self.ioctx.read(
                    _data_oid(self.name, gen, objectno))
            except RadosError:
                continue
        parent = self.header.get("parent")
        if parent is not None:
            try:
                pimg = Image(self.ioctx, parent["image"],
                             snap_name=parent["snap"])
            except RadosError:
                return b""
            # parent may use a different order; translate extents
            off = objectno * self.object_size
            plen = min(self.object_size,
                       max(0, pimg.size() - off))
            if plen <= 0:
                return b""
            return pimg.read(off, plen)
        return b""

    # -- IO ------------------------------------------------------------
    def read(self, offset: int, length: int) -> bytes:
        size = self.size()
        if offset >= size:
            return b""
        length = min(length, size - offset)
        out = bytearray(length)
        osize = self.object_size
        gen = self._view_gen()
        pos = offset
        while pos < offset + length:
            objectno = pos // osize
            o_off = pos % osize
            run = min(osize - o_off, offset + length - pos)
            data = self._read_object(objectno, gen)
            chunk = data[o_off:o_off + run]
            out[pos - offset:pos - offset + len(chunk)] = chunk
            pos += run
        return bytes(out)

    def write(self, offset: int, data: bytes) -> None:
        if self.snap_name is not None:
            raise RadosError(30, "snapshot views are read-only")
        size = self.header["size"]
        if offset + len(data) > size:
            raise RadosError(27, "write past image end")  # EFBIG
        osize = self.object_size
        gen = self.header["gen"]
        pos = offset
        while pos < offset + len(data):
            objectno = pos // osize
            o_off = pos % osize
            run = min(osize - o_off, offset + len(data) - pos)
            oid = _data_oid(self.name, gen, objectno)
            if not self._object_exists(oid):
                # COW: promote the newest older generation (or parent
                # content) into the current generation first
                base = self._read_object(objectno, gen - 1) \
                    if gen > 0 or self.header.get("parent") else b""
                if base:
                    self.ioctx.write_full(oid, base)
            self.ioctx.write(oid, data[pos - offset:pos - offset + run],
                             o_off)
            pos += run

    def _object_exists(self, oid: str) -> bool:
        try:
            self.ioctx.stat(oid)
            return True
        except RadosError:
            return False

    def _underlying_holds(self, objectno: int, gen: int) -> bool:
        """Would a read of this object at head still find content
        below ``gen`` (an older generation, or the clone parent)?
        Stat/header-only — no data transfer."""
        if any(self._object_exists(_data_oid(self.name, g, objectno))
               for g in range(gen - 1, -1, -1)):
            return True
        parent = self.header.get("parent")
        if parent is None:
            return False
        psize = getattr(self, "_parent_size_cache", None)
        if psize is None:
            try:
                psize = Image(self.ioctx, parent["image"],
                              snap_name=parent["snap"]).size()
            except RadosError:
                psize = 0
            self._parent_size_cache = psize
        return objectno * self.object_size < psize

    def resize(self, new_size: int) -> None:
        if self.snap_name is not None:
            raise RadosError(30, "snapshot views are read-only")
        old = self.header["size"]
        self.header["size"] = new_size
        self.header["hwm"] = max(self._hwm(), new_size)
        self._save_header()
        if new_size < old:
            # Drop whole current-gen objects past the end; older
            # generations keep their data for snapshots, so where an
            # older gen (or a clone parent) still holds content, leave
            # an empty tombstone at the current gen — otherwise a
            # later grow would re-expose the stale bytes instead of
            # zeros.
            osize = self.object_size
            gen = self.header["gen"]
            first_gone = (new_size + osize - 1) // osize
            for objectno in range(first_gone,
                                  (old + osize - 1) // osize):
                oid = _data_oid(self.name, gen, objectno)
                try:
                    self.ioctx.remove(oid)
                except RadosError:
                    pass
                if self._underlying_holds(objectno, gen):
                    self.ioctx.write_full(oid, b"")
            if new_size % osize:
                # boundary object: truncate in place when it exists at
                # the current generation (metadata-only); otherwise
                # promote a clamped copy of the resolved content
                # (current gen is always strictly newer than every
                # snap gen, so this never corrupts a snapshot view)
                objectno = new_size // osize
                oid = _data_oid(self.name, gen, objectno)
                if self._object_exists(oid):
                    try:
                        self.ioctx.truncate(oid, new_size % osize)
                    except RadosError:
                        pass
                elif self._underlying_holds(objectno, gen):
                    data = self._read_object(objectno, gen)
                    if len(data) > new_size % osize:
                        self.ioctx.write_full(
                            oid, data[:new_size % osize])

    # -- snapshots (reference librbd snap_create/rollback/remove) ------
    def snap_create(self, snap_name: str) -> None:
        if snap_name in self.header["snaps"]:
            raise RadosError(17, f"snap {snap_name!r} exists")
        self.header["snap_seq"] += 1
        self.header["snaps"][snap_name] = {
            "id": self.header["snap_seq"],
            "gen": self.header["gen"],
            "size": self.header["size"],
        }
        self.header["gen"] += 1        # writes COW from here on
        self._save_header()

    def snap_list(self) -> List[Dict]:
        return [{"name": n, **meta} for n, meta in
                sorted(self.header["snaps"].items(),
                       key=lambda kv: kv[1]["id"])]

    def snap_rm(self, snap_name: str) -> None:
        if snap_name not in self.header["snaps"]:
            raise RadosError(2, f"no snap {snap_name!r}")
        children = RBD(self.ioctx).children(self.name, snap_name)
        if children:
            raise RadosError(16, f"snap in use by clones {children}")
        del self.header["snaps"][snap_name]
        self._save_header()
        self._gc_generations()

    def snap_rollback(self, snap_name: str) -> None:
        """Make the head view equal the snapshot (reference
        snap_rollback): bump the generation and promote the snap's
        objects into it."""
        snap = self.header["snaps"].get(snap_name)
        if snap is None:
            raise RadosError(2, f"no snap {snap_name!r}")
        src_gen = snap["gen"]
        old_size = self.header["size"]
        self.header["gen"] += 1
        new_gen = self.header["gen"]
        self.header["size"] = snap["size"]
        osize = self.object_size
        # Cover every object either view may have touched.  An object
        # written after the snapshot must come back as the snap's
        # content — or, where the snap view is empty, as an explicit
        # empty object at the new generation: a tombstone that stops
        # _read_object falling through to the intermediate (post-snap)
        # generations.  Objects no intermediate generation touched
        # already resolve to the snap's content through <=src_gen, so
        # a sparse or unchanged image rolls back in O(dirty objects),
        # not O(image size).
        max_objs = (max(snap["size"], old_size) + osize - 1) // osize
        for objectno in range(max_objs):
            keep = max(0, min(osize, snap["size"] - objectno * osize))
            dirty = any(
                self._object_exists(_data_oid(self.name, g, objectno))
                for g in range(src_gen + 1, new_gen))
            if dirty:
                data = self._read_object(objectno, src_gen)[:keep] \
                    if keep else b""
                self.ioctx.write_full(
                    _data_oid(self.name, new_gen, objectno), data)
            elif keep == 0:
                # wholly past the snap's size: a stat-only probe
                # decides whether a tombstone is needed at all
                if self._underlying_holds(objectno, src_gen + 1):
                    self.ioctx.write_full(
                        _data_oid(self.name, new_gen, objectno), b"")
            elif keep < osize:
                # boundary object, clean: promote a clamped copy so a
                # later grow re-exposes zeros, not stale bytes
                data = self._read_object(objectno, src_gen)
                if len(data) > keep:
                    self.ioctx.write_full(
                        _data_oid(self.name, new_gen, objectno),
                        data[:keep])
        self._save_header()

    def _hwm(self) -> int:
        """Largest size this image has ever had: tombstones from
        shrinks can sit past the current and snap sizes, so cleanup
        scans must cover the high-water mark."""
        return max([self.header.get("hwm", 0), self.header["size"]] +
                   [s["size"] for s in self.header["snaps"].values()])

    def _live_gens(self) -> List[int]:
        gens = {self.header["gen"]}
        gens.update(s["gen"] for s in self.header["snaps"].values())
        return sorted(gens)

    def _gc_generations(self) -> None:
        """Remove data objects of generations no view can reach.
        An unreachable gen g's objects are first folded into the next
        live gen if it lacks them (they are its COW base)."""
        live = self._live_gens()
        max_objs = (self._hwm() + self.object_size - 1) \
            // self.object_size
        for gen in range(self.header["gen"] + 1):
            if gen in live:
                continue
            nxt = next((g for g in live if g > gen), None)
            for objectno in range(max_objs):
                oid = _data_oid(self.name, gen, objectno)
                if not self._object_exists(oid):
                    continue
                if nxt is not None:
                    noid = _data_oid(self.name, nxt, objectno)
                    if not self._object_exists(noid):
                        self.ioctx.write_full(
                            noid, self.ioctx.read(oid))
                try:
                    self.ioctx.remove(oid)
                except RadosError:
                    pass

    # -- clones --------------------------------------------------------
    def flatten(self) -> None:
        """Copy all parent-provided data in and sever the parent link
        (reference librbd flatten)."""
        parent = self.header.get("parent")
        if parent is None:
            return
        osize = self.object_size
        gen = self.header["gen"]
        n_objs = (self.header["size"] + osize - 1) // osize
        for objectno in range(n_objs):
            oid = _data_oid(self.name, gen, objectno)
            if self._object_exists(oid):
                continue
            data = self._read_object(objectno, gen)
            if data:
                self.ioctx.write_full(oid, data)
        self.header["parent"] = None
        self._save_header()

    # -- maintenance ---------------------------------------------------
    def _remove_all_data(self) -> None:
        osize = self.object_size
        n_objs = (self._hwm() + osize - 1) // osize
        for gen in range(self.header["gen"] + 1):
            for objectno in range(n_objs):
                try:
                    self.ioctx.remove(_data_oid(self.name, gen,
                                                objectno))
                except RadosError:
                    pass
