"""Block images over RADOS objects: striping, snapshots, clones.

Python-native equivalent of the reference's librbd core (reference
``src/librbd/`` 85.7k LoC): images are a header object plus data
objects of ``2^order`` bytes (reference rbd_header.<id> +
rbd_data.<id>.<objectno>, ImageCtx::get_object_name), with snapshots
and copy-on-write clones.

Snapshots build on RADOS **selfmanaged snaps** exactly like the
reference (librbd snapshots ARE librados snap contexts resolved
inside the OSD): ``snap_create`` allocates a pool snap id and the
image's writes carry a SnapContext of its live snap ids, so the OSD
clones objects copy-on-write; snapshot reads set the read snap;
``snap_rollback`` rolls each data object back through the OSD's
rollback op; ``snap_rm`` releases the id and the OSD trimmer reclaims
the clones.  (An earlier iteration of this file implemented private
generation-based COW client-side; that predated the framework's RADOS
snapshot machinery.)

A clone records (parent image, snap); unwritten extents fall through
to the parent's snapshot view exactly like the reference's parent
overlap reads (librbd/io/ReadResult parent fallback), and ``flatten``
copies the parent data in and severs the link.

Header: ``rbd_header.<name>`` holds a JSON body (works on EC pools,
which have no omap) with size/order/snaps/parent.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ..client.rados import IoCtx, RadosError

DEFAULT_ORDER = 22                    # 4 MiB objects, reference default
RBD_DIRECTORY = "rbd_directory"       # reference rbd_directory object


class ImageNotFound(RadosError):
    def __init__(self, name: str):
        super().__init__(2, f"image {name!r} not found")


def _header_oid(name: str) -> str:
    return f"rbd_header.{name}"


def _journal_oid(name: str) -> str:
    return f"rbd_journal.{name}"


def _journal_head_oid(name: str) -> str:
    return f"rbd_journal.{name}.head"


def _data_oid(name: str, objectno: int) -> str:
    return f"rbd_data.{name}.{objectno:016x}"


def _mirror_peer_oid(name: str) -> str:
    """Peer journal position at the PRIMARY site (reference: the
    rbd-mirror peer registers as a journal client and its committed
    position gates local trimming, journal/JournalTrimmer).  Lives
    outside the header so the mirror daemon's updates never race the
    lock holder's header saves."""
    return f"rbd_mirror.{name}.peer"


def _mirror_pos_oid(name: str) -> str:
    """Sync position at the SECONDARY site: highest primary journal
    seq already applied here."""
    return f"rbd_mirror.{name}.pos"


def _omap_oid(name: str, snap_id: Optional[int] = None) -> str:
    """Object-map object (reference rbd_object_map.<id> and
    rbd_object_map.<id>.<snapid>, librbd/object_map/)."""
    base = f"rbd_object_map.{name}"
    return base if snap_id is None else f"{base}.{snap_id}"


# object-map states, 2 bits per data object (reference
# cls/rbd/cls_rbd_types OBJECT_*): EXISTS means written since the
# last snapshot (the dirty bit fast-diff reads), EXISTS_CLEAN means
# present but untouched since then
OM_NONEXISTENT = 0
OM_EXISTS = 1
OM_EXISTS_CLEAN = 3


class RBD:
    """Pool-level image operations (reference librbd.h RBD class)."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    # -- directory (reference cls_rbd rbd_directory) -------------------
    def _dir(self) -> List[str]:
        try:
            raw = self.ioctx.read(RBD_DIRECTORY)
            return json.loads(raw.decode()) if raw else []
        except RadosError:
            return []

    def _dir_update(self, names: List[str]) -> None:
        self.ioctx.write_full(RBD_DIRECTORY,
                              json.dumps(sorted(names)).encode())

    def list(self) -> List[str]:
        return self._dir()

    def create(self, name: str, size: Optional[int] = None,
               order: Optional[int] = None,
               features: Optional[Tuple[str, ...]] = None) -> None:
        try:
            conf = self.ioctx.rados.conf     # the cluster's config
        except AttributeError:
            from ..utils.config import default_config
            conf = default_config()
        if size is None:
            size = conf["rbd_default_size"]
        if order is None:                # reference rbd_default_order
            order = conf["rbd_default_order"]
        if not 12 <= order <= 26:
            raise ValueError("order must be in [12, 26]")
        if conf["rbd_validate_names"] and (
                not name or any(c in name for c in "/@\0")):
            raise ValueError(f"invalid image name {name!r}")
        feats = set(features or ("layering",))
        if "fast-diff" in feats:
            feats.add("object-map")      # reference: fast-diff is an
                                         # object-map annotation
        if "object-map" in feats:
            # reference requires exclusive-lock under the object map;
            # this implementation additionally requires journaling —
            # the post-crash journal replay is what re-marks the
            # dirty bits an apply crash could lose, keeping fast-diff
            # exact without the reference's detained-update machinery
            if not {"exclusive-lock", "journaling"} <= feats:
                raise ValueError("object-map requires exclusive-lock "
                                 "+ journaling")
        features = tuple(sorted(feats))
        names = self._dir()
        if name in names:
            raise RadosError(17, f"image {name!r} exists")  # EEXIST
        header = {"size": size, "order": order, "snaps": {},
                  "parent": None, "hwm": size,
                  # reference image features (RBD_FEATURE_*):
                  # exclusive-lock gates writers through cls_lock;
                  # journaling WALs every data write for crash-
                  # consistent replay (librbd/exclusive_lock/ +
                  # librbd/journal/)
                  "features": list(features or ("layering",)),
                  "lock_gen": 0}
        self.ioctx.write_full(_header_oid(name),
                              json.dumps(header).encode())
        self._dir_update(names + [name])

    def remove(self, name: str) -> None:
        img = Image(self.ioctx, name)
        if img.header["snaps"]:
            raise RadosError(39, "image has snapshots")  # ENOTEMPTY
        img._remove_all_data()
        for oid in (_journal_oid(name), _journal_head_oid(name),
                    _omap_oid(name), _mirror_peer_oid(name),
                    _mirror_pos_oid(name)):
            try:
                self.ioctx.remove(oid)
            except RadosError:
                pass
        self.ioctx.remove(_header_oid(name))
        self._dir_update([n for n in self._dir() if n != name])

    def clone(self, parent_name: str, snap_name: str,
              child_name: str) -> None:
        """COW child of parent@snap (reference librbd clone: requires
        a protected snapshot; 'protected' here = we refuse snap
        removal while children exist, checked at snap_rm)."""
        parent = Image(self.ioctx, parent_name)
        snap = parent.header["snaps"].get(snap_name)
        if snap is None:
            raise RadosError(2, f"no snap {snap_name!r}")
        names = self._dir()
        if child_name in names:
            raise RadosError(17, f"image {child_name!r} exists")
        header = {"size": snap["size"], "order": parent.header["order"],
                  "snaps": {},
                  "parent": {"image": parent_name, "snap": snap_name},
                  "hwm": snap["size"]}
        self.ioctx.write_full(_header_oid(child_name),
                              json.dumps(header).encode())
        self._dir_update(names + [child_name])

    def children(self, parent_name: str, snap_name: str) -> List[str]:
        out = []
        for name in self._dir():
            try:
                p = Image(self.ioctx, name).header.get("parent")
            except ImageNotFound:
                continue
            if p and p["image"] == parent_name \
                    and p["snap"] == snap_name:
                out.append(name)
        return out


class Image:
    """One open image (reference librbd::Image / ImageCtx).
    ``snap_name`` opens a read-only snapshot view.

    Every image holds its OWN IoCtx (``dup``) so its SnapContext —
    derived from the header's live snaps, exactly the reference's
    ImageCtx::snapc — never races other images on the pool."""

    JOURNAL_TRIM_EVERY = 32

    def __init__(self, ioctx: IoCtx, name: str,
                 snap_name: Optional[str] = None):
        self.ioctx = ioctx.dup()
        self.name = name
        self.snap_name = snap_name
        self.header = self._load_header()
        if snap_name is not None and \
                snap_name not in self.header["snaps"]:
            raise RadosError(2, f"no snap {snap_name!r}")
        self._apply_snap_state()
        # exclusive lock state (reference librbd/exclusive_lock/):
        # acquired lazily on the first write when the feature is on
        import secrets
        self._lock_cookie = f"{secrets.randbits(48):x}"
        self._lock_held = False
        self._lock_gen = 0
        self._journal_seq = 0
        self._journal_uncommitted = 0
        # test hook: crash between the journal append and the data
        # apply (the window the WAL exists for)
        self._inject_crash_after_journal = False

    # -- features / exclusive lock (reference librbd/exclusive_lock/,
    #    built on cls_lock exactly like the reference) ----------------
    def has_feature(self, f: str) -> bool:
        return f in self.header.get("features", [])

    @property
    def _owner(self) -> str:
        return self.ioctx.rados.msgr.name

    def lock_info(self) -> Dict:
        import json as _json
        out = self.ioctx.exec_cls(
            _header_oid(self.name), "lock", "get_info",
            _json.dumps({"name": "rbd_lock"}).encode())
        return _json.loads(out.decode()) if out else {}

    def acquire_lock(self, force: bool = False) -> None:
        """Take the image's exclusive lock (reference
        ExclusiveLock<I>::acquire_lock): bumps the lock GENERATION in
        the header and fences the journal at it, so a previous
        holder's in-flight journal appends are rejected inside the
        OSD (cls_fence — the same primitive that fences a zombie
        MDS).  ``force`` breaks a dead holder's lock first (reference
        break-lock on client eviction), then REPLAYS its journal so
        no acked write is lost."""
        import json as _json
        if self._lock_held:
            return
        hoid = _header_oid(self.name)
        req = {"name": "rbd_lock", "type": "exclusive",
               "owner": self._owner, "cookie": self._lock_cookie,
               "tag": "rbd"}
        try:
            self.ioctx.exec_cls(hoid, "lock", "lock",
                                _json.dumps(req).encode())
        except RadosError as e:
            if e.errno not in (16, 17):  # not a lock conflict:
                raise                    # surface the real error
            if not force:
                raise RadosError(16, f"image {self.name} is locked "
                                 f"by another client")
            info = self.lock_info()
            for locker in list(info.get("lockers", {})):
                owner, _, cookie = locker.partition(" ")
                self.ioctx.exec_cls(
                    hoid, "lock", "break_lock",
                    _json.dumps({"name": "rbd_lock",
                                 "locker_owner": owner,
                                 "locker_cookie": cookie}).encode())
            self.ioctx.exec_cls(hoid, "lock", "lock",
                                _json.dumps(req).encode())
        # generation bump under the lock; persists before any write
        self.header = self._load_header()
        self._lock_gen = self.header.get("lock_gen", 0) + 1
        self.header["lock_gen"] = self._lock_gen
        self._save_header()
        self._lock_held = True
        if self.has_feature("journaling"):
            self.ioctx.exec_cls(
                _journal_oid(self.name), "fence", "set",
                _json.dumps({"epoch": self._lock_gen}).encode())
            self._replay_journal()

    def _assert_lock_owned(self) -> None:
        info = self.lock_info()
        key = f"{self._owner} {self._lock_cookie}"
        if key not in info.get("lockers", {}):
            self._lock_held = False
            raise RadosError(108, f"image {self.name}: exclusive "
                             f"lock lost (another client broke it)")

    def release_lock(self) -> None:
        if not self._lock_held:
            return
        import json as _json
        if self.has_feature("journaling"):
            try:
                self._journal_commit()   # clean handoff: empty journal
            except RadosError:
                pass                     # evicted: successor owns it
        try:
            self.ioctx.exec_cls(
                _header_oid(self.name), "lock", "unlock",
                _json.dumps({"name": "rbd_lock",
                             "owner": self._owner,
                             "cookie": self._lock_cookie}).encode())
        except RadosError:
            pass                         # broken by a successor: fine
        self._lock_held = False

    def close(self) -> None:
        self.release_lock()

    def __enter__(self) -> "Image":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- journaling (reference librbd/journal/: WAL before data) ------
    def _journal_append(self, offset: int, data: bytes) -> None:
        import base64
        self._journal_event({"off": offset,
                             "data": base64.b64encode(data).decode()})

    def _journal_event(self, ev: dict) -> None:
        import json as _json
        self._journal_seq += 1
        line = _json.dumps(dict(ev, seq=self._journal_seq)) + "\n"
        try:
            self.ioctx.exec_cls(
                _journal_oid(self.name), "fence", "guarded_append",
                _json.dumps({"epoch": self._lock_gen,
                             "data": line}).encode())
        except RadosError as e:
            if e.errno == 1:             # EPERM: fenced — lock lost
                self._lock_held = False
                raise RadosError(
                    108, f"image {self.name}: exclusive lock lost "
                    f"(another client acquired it)")
            raise

    def _journal_commit(self) -> None:
        """Data writes up to the current seq are durable: advance the
        committed watermark and trim (reference journal commit +
        trim).  With mirroring enabled, trimming additionally waits
        for the peer's committed position (reference: journal clients
        gate trimming, journal/JournalTrimmer) — the journal IS the
        replication stream, so entries the peer has not consumed are
        retained."""
        import json as _json
        head = _json.dumps({"committed": self._journal_seq})
        mirror = self.header.get("mirror") or {}
        trim = True
        if mirror.get("enabled"):
            try:
                peer = _json.loads(self.ioctx.read(
                    _mirror_peer_oid(self.name)).decode())
            except (RadosError, ValueError):
                peer = {"committed": 0}
            trim = peer.get("committed", 0) >= self._journal_seq
        try:
            self.ioctx.exec_cls(
                _journal_head_oid(self.name), "fence",
                "guarded_write_full",
                _json.dumps({"epoch": self._lock_gen,
                             "data": head}).encode())
            if trim:
                self.ioctx.exec_cls(
                    _journal_oid(self.name), "fence",
                    "guarded_truncate",
                    _json.dumps({"epoch": self._lock_gen,
                                 "size": 0}).encode())
        except RadosError as e:
            if e.errno == 1:
                self._lock_held = False
                raise RadosError(108, "exclusive lock lost")
            if e.errno != 2:
                raise
        self._journal_uncommitted = 0

    def _replay_journal(self) -> None:
        """Apply journal events past the committed watermark to the
        data objects (reference librbd journal replay on open): a
        holder that died between append and apply loses nothing."""
        import base64
        import json as _json
        try:
            head = _json.loads(self.ioctx.read(
                _journal_head_oid(self.name)).decode())
        except (RadosError, ValueError):
            head = {"committed": 0}
        committed = head.get("committed", 0)
        try:
            raw = self.ioctx.read(_journal_oid(self.name))
        except RadosError:
            raw = b""
        replayed = 0
        top = committed
        for line in raw.splitlines():
            if not line.strip():
                continue
            try:
                ev = _json.loads(line.decode())
            except ValueError:
                continue
            top = max(top, ev["seq"])
            if ev["seq"] <= committed:
                continue
            if "resize" in ev:
                self._apply_resize(ev["resize"])
            else:
                self._apply_write(ev["off"],
                                  base64.b64decode(ev["data"]))
            replayed += 1
        self._journal_seq = top
        if replayed:
            self._journal_commit()

    # -- header --------------------------------------------------------
    def _load_header(self) -> Dict:
        try:
            return json.loads(self.ioctx.read(
                _header_oid(self.name)).decode())
        except RadosError:
            raise ImageNotFound(self.name)

    def _save_header(self) -> None:
        self.ioctx.write_full(_header_oid(self.name),
                              json.dumps(self.header).encode())

    def _apply_snap_state(self) -> None:
        """Install the image's write SnapContext + read snap on its
        private ioctx (reference ImageCtx::snapc / snap_id)."""
        sids = sorted((s["id"] for s in
                       self.header["snaps"].values()), reverse=True)
        self.ioctx.set_snap_context(sids[0] if sids else 0, sids)
        if self.snap_name is not None:
            self.ioctx.snap_set_read(
                self.header["snaps"][self.snap_name]["id"])
        else:
            self.ioctx.snap_set_read(0)

    @property
    def object_size(self) -> int:
        return 1 << self.header["order"]

    def size(self) -> int:
        if self.snap_name is not None:
            return self.header["snaps"][self.snap_name]["size"]
        return self.header["size"]

    def stat(self) -> Dict:
        return {"size": self.size(), "order": self.header["order"],
                "object_size": self.object_size,
                "num_objs": (self.size() + self.object_size - 1)
                // self.object_size,
                "snapshot_count": len(self.header["snaps"]),
                "parent": self.header.get("parent")}

    def _n_objs(self, size: Optional[int] = None) -> int:
        s = self.header["size"] if size is None else size
        return (s + self.object_size - 1) // self.object_size

    # -- object resolution ---------------------------------------------
    def _read_object(self, objectno: int) -> bytes:
        """This view's content of one data object; falls through to
        the parent snapshot view when cloned and the child object does
        not exist at this view (reference parent overlap read)."""
        try:
            return self.ioctx.read(_data_oid(self.name, objectno))
        except RadosError:
            pass
        parent = self.header.get("parent")
        if parent is not None:
            try:
                pimg = Image(self.ioctx, parent["image"],
                             snap_name=parent["snap"])
            except RadosError:
                return b""
            off = objectno * self.object_size
            plen = min(self.object_size, max(0, pimg.size() - off))
            if plen <= 0:
                return b""
            return pimg.read(off, plen)
        return b""

    def _object_exists(self, objectno: int) -> bool:
        try:
            self.ioctx.stat(_data_oid(self.name, objectno))
            return True
        except RadosError:
            return False

    # -- mirroring control (reference librbd/mirror/ +
    # cls_rbd mirror_image state; the data path lives in
    # rbd/mirror.py's MirrorDaemon) ------------------------------------
    def mirror_enable(self, primary: bool = True) -> None:
        """Mark the image for journal-based mirroring (reference
        rbd mirror image enable, mode journal): requires the
        journaling feature — the journal is the replication
        stream."""
        if not self.has_feature("journaling"):
            raise RadosError(22, "mirroring needs the journaling "
                             "feature")
        self.header["mirror"] = {"enabled": True, "primary": primary}
        self._save_header()

    def mirror_disable(self) -> None:
        self.header.pop("mirror", None)
        self._save_header()
        try:
            self.ioctx.remove(_mirror_peer_oid(self.name))
        except RadosError:
            pass

    def mirror_promote(self) -> None:
        """Make this site's copy the writable primary (reference rbd
        mirror image promote — failover step 2, after demoting or
        losing the old primary)."""
        m = self.header.get("mirror")
        if not m or not m.get("enabled"):
            raise RadosError(22, "mirroring not enabled")
        m["primary"] = True
        self._save_header()

    def mirror_demote(self) -> None:
        """Primary -> non-primary (failover step 1): further writes
        here are refused until promoted again."""
        m = self.header.get("mirror")
        if not m or not m.get("enabled"):
            raise RadosError(22, "mirroring not enabled")
        m["primary"] = False
        self._save_header()

    def mirror_status(self) -> Dict:
        m = dict(self.header.get("mirror") or {})
        import json as _json
        try:
            m["peer_committed"] = _json.loads(self.ioctx.read(
                _mirror_peer_oid(self.name)).decode()).get(
                    "committed", 0)
        except (RadosError, ValueError):
            pass
        m["journal_seq"] = self._journal_seq
        return m

    def _assert_writable(self) -> None:
        m = self.header.get("mirror") or {}
        if m.get("enabled") and not m.get("primary", True):
            raise RadosError(30, f"image {self.name} is a "
                             f"non-primary mirror (promote first)")

    # -- object map (reference librbd/object_map/: 2-bit per-object
    # state under the exclusive lock; dirty bits power fast-diff,
    # existence bits power fast delete/du) -----------------------------
    def _om_load(self, snap_id: Optional[int] = None) -> bytearray:
        try:
            return bytearray(self.ioctx.read(
                _omap_oid(self.name, snap_id)))
        except RadosError:
            return bytearray()

    def _om_save(self, om: bytearray,
                 snap_id: Optional[int] = None) -> None:
        self.ioctx.write_full(_omap_oid(self.name, snap_id),
                              bytes(om))

    @staticmethod
    def _om_get(om: bytearray, objno: int) -> int:
        byte = objno // 4
        if byte >= len(om):
            return OM_NONEXISTENT
        return (om[byte] >> ((objno % 4) * 2)) & 3

    @staticmethod
    def _om_set(om: bytearray, objno: int, state: int) -> None:
        byte = objno // 4
        while len(om) <= byte:
            om.append(0)
        shift = (objno % 4) * 2
        om[byte] = (om[byte] & ~(3 << shift)) | (state << shift)

    def _om_mark(self, objnos, state: int) -> None:
        """Batch state transition, one read-modify-write (single
        writer: the exclusive lock the feature requires)."""
        if not self.has_feature("object-map") \
                or self.snap_name is not None:
            return
        om = self._om_load()
        for objno in objnos:
            self._om_set(om, objno, state)
        self._om_save(om)

    def rebuild_object_map(self) -> None:
        """Re-derive the map from actual object existence (reference
        object_map_rebuild): recovers from any drift; rebuilt objects
        mark EXISTS (dirty) so the next fast-diff over-reports rather
        than misses."""
        om = bytearray()
        hwm = max(self.header.get("hwm", 0), self.header["size"])
        for objno in range(self._n_objs(hwm)):
            try:
                self.ioctx.stat(_data_oid(self.name, objno))
                self._om_set(om, objno, OM_EXISTS)
            except RadosError:
                pass
        self._om_save(om)

    def fast_diff(self, from_snap: str,
                  to_snap: Optional[str] = None) -> List[int]:
        """Data objects possibly changed between two points in time
        (reference fast-diff / DiffIterate with whole-object=true):
        the union of every intermediate snapshot map's dirty bits
        plus the endpoint's — each snap map's EXISTS bits mean
        "written since the PREVIOUS snap", so the union covers the
        whole interval; deletions show as existence flips."""
        if not self.has_feature("object-map"):
            raise RadosError(95, "fast-diff needs the object-map "
                             "feature")
        snaps = self.snap_list()                 # id-ascending
        from_meta = self.header["snaps"].get(from_snap)
        if from_meta is None:
            raise RadosError(2, f"no snap {from_snap!r}")
        if to_snap is not None and \
                to_snap not in self.header["snaps"]:
            raise RadosError(2, f"no snap {to_snap!r}")
        maps = []
        for s in snaps:
            if s["id"] <= from_meta["id"]:
                continue
            if to_snap is not None and \
                    s["id"] > self.header["snaps"][to_snap]["id"]:
                break
            maps.append(self._om_load(s["id"]))
        if to_snap is None:
            maps.append(self._om_load())         # head
        from_map = self._om_load(from_meta["id"])
        end_map = maps[-1] if maps else from_map
        hwm = max(self.header.get("hwm", 0), self.header["size"])
        changed = []
        for objno in range(self._n_objs(hwm)):
            dirty = any(self._om_get(m, objno) == OM_EXISTS
                        for m in maps)
            flipped = (self._om_get(from_map, objno) == 0) != \
                (self._om_get(end_map, objno) == 0)
            if dirty or flipped:
                changed.append(objno)
        return changed

    # -- IO ------------------------------------------------------------
    def read(self, offset: int, length: int) -> bytes:
        size = self.size()
        if offset >= size:
            return b""
        length = min(length, size - offset)
        out = bytearray(length)
        osize = self.object_size
        pos = offset
        while pos < offset + length:
            objectno = pos // osize
            o_off = pos % osize
            run = min(osize - o_off, offset + length - pos)
            data = self._read_object(objectno)
            chunk = data[o_off:o_off + run]
            out[pos - offset:pos - offset + len(chunk)] = chunk
            pos += run
        return bytes(out)

    def write(self, offset: int, data: bytes) -> None:
        if self.snap_name is not None:
            raise RadosError(30, "snapshot views are read-only")
        self._assert_writable()
        size = self.header["size"]
        if offset + len(data) > size:
            raise RadosError(27, "write past image end")  # EFBIG
        if self.has_feature("exclusive-lock") or \
                self.has_feature("journaling"):
            self.acquire_lock()          # lazy auto-acquire
            if not self.has_feature("journaling"):
                # journaled writes are fenced inside the OSD; without
                # journaling the only zombie defense is verifying
                # ownership (the reference blocklists evicted clients
                # at the OSDMap instead)
                self._assert_lock_owned()
        if self.has_feature("journaling"):
            # WAL: the event is durable (and fenced to our lock
            # generation) BEFORE any data object changes
            self._journal_append(offset, data)
            if self._inject_crash_after_journal:
                return                   # test hook: "crash" here
        self._apply_write(offset, data)
        if self.has_feature("journaling"):
            self._journal_uncommitted += 1
            if self._journal_uncommitted >= self.JOURNAL_TRIM_EVERY:
                self._journal_commit()

    def _apply_write(self, offset: int, data: bytes) -> None:
        osize = self.object_size
        pos = offset
        touched = []
        while pos < offset + len(data):
            objectno = pos // osize
            o_off = pos % osize
            run = min(osize - o_off, offset + len(data) - pos)
            oid = _data_oid(self.name, objectno)
            if self.header.get("parent") is not None \
                    and not self._object_exists(objectno):
                # clone COW: promote the parent's content first
                base = self._read_object(objectno)
                if base:
                    self.ioctx.write_full(oid, base)
            # snapshot COW happens INSIDE the OSD: the write carries
            # the image's SnapContext and the object clones itself
            self.ioctx.write(oid, data[pos - offset:pos - offset
                                       + run], o_off)
            touched.append(objectno)
            pos += run
        # object map AFTER the data (journal replay re-marks across
        # an apply crash, so the dirty bits stay exact — the create-
        # time journaling requirement exists for exactly this)
        self._om_mark(touched, OM_EXISTS)

    def resize(self, new_size: int) -> None:
        if self.snap_name is not None:
            raise RadosError(30, "snapshot views are read-only")
        self._assert_writable()
        if self.has_feature("exclusive-lock") or \
                self.has_feature("journaling"):
            self.acquire_lock()
        if self.has_feature("journaling"):
            # resize rides the journal like writes: replay restores
            # it after a crash, and the mirror peer re-applies it at
            # the OBJECT level (a header-only copy would leave the
            # secondary's truncated objects behind and the sites
            # would silently diverge on a shrink-then-grow)
            self._journal_event({"resize": new_size})
        self._apply_resize(new_size)
        if self.has_feature("journaling"):
            self._journal_uncommitted += 1
            if self._journal_uncommitted >= self.JOURNAL_TRIM_EVERY:
                self._journal_commit()

    def _apply_resize(self, new_size: int) -> None:
        old = self.header["size"]
        self.header["size"] = new_size
        # high-water mark: whiteouts from clone shrinks can sit past
        # the current size; removal must scan that far
        self.header["hwm"] = max(self.header.get("hwm", 0), old,
                                 new_size)
        self._save_header()
        if new_size < old:
            # truncates/removes carry the snap context too, so
            # snapshot views keep their bytes (OSD-side clones) while
            # the head sheds them; a later grow re-exposes zeros.
            # CLONES need whiteouts: removing a never-written child
            # object is a no-op and the parent fallthrough would
            # re-expose the parent's bytes after a grow — an empty
            # head object blocks it.
            osize = self.object_size
            parent = self.header.get("parent")
            first_gone = (new_size + osize - 1) // osize
            for objectno in range(first_gone, self._n_objs(old)):
                oid = _data_oid(self.name, objectno)
                try:
                    self.ioctx.remove(oid)
                except RadosError:
                    pass
                if parent is not None:
                    self.ioctx.write_full(oid, b"")   # whiteout
            self._om_mark(range(first_gone, self._n_objs(old)),
                          OM_EXISTS if parent is not None
                          else OM_NONEXISTENT)
            if new_size % osize:
                objectno = new_size // osize
                oid = _data_oid(self.name, objectno)
                if self._object_exists(objectno):
                    try:
                        self.ioctx.truncate(oid, new_size % osize)
                    except RadosError:
                        pass
                elif parent is not None:
                    # materialize the clamped parent content so the
                    # tail past new_size reads zeros after a grow
                    data = self._read_object(objectno)
                    self.ioctx.write_full(oid,
                                          data[:new_size % osize])

    # -- snapshots (reference librbd snap_create/rollback/remove on
    # selfmanaged snaps) ----------------------------------------------
    def snap_create(self, snap_name: str) -> None:
        self._assert_writable()
        if snap_name in self.header["snaps"]:
            raise RadosError(17, f"snap {snap_name!r} exists")
        sid = self.ioctx.selfmanaged_snap_create()
        self.header["snaps"][snap_name] = {
            "id": sid, "size": self.header["size"]}
        if self.has_feature("object-map"):
            # freeze the map at the snap and reset the head's dirty
            # bits: from here on EXISTS means "written since THIS
            # snap" (reference snapshot object maps)
            om = self._om_load()
            self._om_save(om, sid)
            for objno in range(len(om) * 4):
                if self._om_get(om, objno) == OM_EXISTS:
                    self._om_set(om, objno, OM_EXISTS_CLEAN)
            self._om_save(om)
        self._save_header()
        self._apply_snap_state()

    def snap_list(self) -> List[Dict]:
        return [{"name": n, **meta} for n, meta in
                sorted(self.header["snaps"].items(),
                       key=lambda kv: kv[1]["id"])]

    def snap_rm(self, snap_name: str) -> None:
        self._assert_writable()
        snap = self.header["snaps"].get(snap_name)
        if snap is None:
            raise RadosError(2, f"no snap {snap_name!r}")
        children = RBD(self.ioctx).children(self.name, snap_name)
        if children:
            raise RadosError(16, f"snap in use by clones {children}")
        del self.header["snaps"][snap_name]
        self._save_header()
        self._apply_snap_state()
        try:
            self.ioctx.remove(_omap_oid(self.name, snap["id"]))
        except RadosError:
            pass
        # release the id: the OSD snap trimmer reclaims the clones
        self.ioctx.selfmanaged_snap_remove(snap["id"])

    def snap_rollback(self, snap_name: str) -> None:
        """Roll every data object back to the snapshot through the
        OSD's rollback op (reference librbd snap_rollback ->
        rados selfmanaged_snap_rollback per object)."""
        self._assert_writable()
        snap = self.header["snaps"].get(snap_name)
        if snap is None:
            raise RadosError(2, f"no snap {snap_name!r}")
        old_size = self.header["size"]
        self.header["size"] = snap["size"]
        max_objs = self._n_objs(max(snap["size"], old_size))
        for objectno in range(max_objs):
            try:
                self.ioctx.selfmanaged_snap_rollback(
                    _data_oid(self.name, objectno), snap["id"])
            except RadosError:
                pass
        if self.has_feature("object-map"):
            om = self._om_load(snap["id"])
            for objno in range(len(om) * 4):
                if self._om_get(om, objno) == OM_EXISTS_CLEAN:
                    self._om_set(om, objno, OM_EXISTS)  # content moved
            self._om_save(om)
        self._save_header()

    # -- clones --------------------------------------------------------
    def flatten(self) -> None:
        """Copy all parent-provided data in and sever the parent link
        (reference librbd flatten)."""
        self._assert_writable()
        parent = self.header.get("parent")
        if parent is None:
            return
        copied = []
        for objectno in range(self._n_objs()):
            if self._object_exists(objectno):
                continue
            data = self._read_object(objectno)
            if data:
                self.ioctx.write_full(_data_oid(self.name, objectno),
                                      data)
                copied.append(objectno)
        self._om_mark(copied, OM_EXISTS)
        self.header["parent"] = None
        self._save_header()

    # -- maintenance ---------------------------------------------------
    def _remove_all_data(self) -> None:
        # no live snaps by contract (RBD.remove refuses otherwise),
        # so plain removes reclaim everything; scan to the high-water
        # size so shrink-era whiteouts go too
        hwm = max(self.header.get("hwm", 0), self.header["size"])
        for objectno in range(self._n_objs(hwm)):
            try:
                self.ioctx.remove(_data_oid(self.name, objectno))
            except RadosError:
                pass
