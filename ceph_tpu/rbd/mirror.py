"""RBD mirroring: journal-based one-way image replication.

Python-native equivalent of the reference's rbd-mirror daemon
(reference ``src/tools/rbd_mirror/Mirror.cc`` + ImageReplayer +
ImageSync): images whose header marks them mirroring-enabled PRIMARY
replicate to a peer pool/cluster by

* **bootstrap** (reference ImageSync): first contact creates the
  peer image non-primary and deep-copies the current data objects;
* **journal replay** (reference ImageReplayer): afterwards the
  primary's write journal IS the replication stream — entries past
  the secondary's sync position are re-applied in order (the journal
  events are plain (offset, data) records, idempotent to re-apply),
  and the secondary's position is pushed back to the primary
  (``rbd_mirror.<name>.peer``) where it gates journal trimming
  exactly like a reference journal client's committed position.

Failover is ``mirror_demote()`` at the old primary + ``promote()``
here (reference rbd mirror image promote/demote): non-primary images
refuse ordinary writes, so a split brain needs a forced promote on
both sides — same contract as the reference.

The daemon is site-B-resident and PULLS (like the reference's
rbd-mirror running at the secondary): it needs only read access to
the primary pool plus write access to the two mirror-position
objects.
"""
from __future__ import annotations

import base64
import json
from typing import Dict, Optional

from ..client.rados import IoCtx, RadosError
from .image import (RBD, Image, _header_oid, _journal_oid,
                    _mirror_peer_oid, _mirror_pos_oid)


class MirrorDaemon:
    """Replicates mirroring-enabled primaries from ``src`` to
    ``dst`` (two pools, possibly on two clusters)."""

    def __init__(self, src: IoCtx, dst: IoCtx):
        self.src = src
        self.dst = dst

    # -- positions -----------------------------------------------------
    def _synced_pos(self, name: str) -> int:
        try:
            return json.loads(self.dst.read(
                _mirror_pos_oid(name)).decode()).get("synced", 0)
        except (RadosError, ValueError):
            return 0

    def _record_pos(self, name: str, seq: int) -> None:
        body = json.dumps({"synced": seq}).encode()
        self.dst.write_full(_mirror_pos_oid(name), body)
        # tell the primary so it may trim its journal (reference:
        # the mirror peer's committed position)
        self.src.write_full(_mirror_peer_oid(name),
                            json.dumps({"committed": seq}).encode())

    def _journal_entries(self, name: str):
        try:
            raw = self.src.read(_journal_oid(name))
        except RadosError:
            return []
        out = []
        for line in raw.splitlines():
            if not line.strip():
                continue
            try:
                out.append(json.loads(line.decode()))
            except ValueError:
                continue
        return out

    # -- sync ----------------------------------------------------------
    def sync_image(self, name: str) -> Dict:
        """One replication pass for one image; -> stats."""
        hdr = json.loads(self.src.read(_header_oid(name)).decode())
        m = hdr.get("mirror") or {}
        if not m.get("enabled") or not m.get("primary", False):
            return {"skipped": True}
        stats = {"bootstrapped": False, "replayed": 0}
        dst_rbd = RBD(self.dst)
        if name not in dst_rbd.list():
            # note the journal top BEFORE the copy: entries at or
            # below it are covered by the full copy; later ones
            # replay on the next pass (re-applying a covered write is
            # harmless — events are absolute (offset, data))
            entries = self._journal_entries(name)
            top = max((e["seq"] for e in entries), default=0)
            dst_rbd.create(name, size=hdr["size"],
                           order=hdr["order"],
                           features=tuple(hdr.get("features", [])))
            dst_img = Image(self.dst, name)
            dst_img.header["mirror"] = {"enabled": True,
                                        "primary": False}
            dst_img._save_header()
            src_img = Image(self.src, name)
            osize = src_img.object_size
            for objno in range(src_img._n_objs()):
                data = src_img._read_object(objno)
                if data:
                    dst_img._apply_write(objno * osize, data)
            self._record_pos(name, top)
            stats["bootstrapped"] = True
            return stats
        dst_img = Image(self.dst, name)
        dm = dst_img.header.get("mirror") or {}
        if not dm.get("enabled"):
            # a same-name image that mirroring did NOT create: never
            # overwrite it (reference: the replayer requires a
            # mirror-registered peer image; anything else is an
            # operator conflict to resolve)
            return {"conflict": True}
        if dm.get("primary"):
            # both sides primary: split brain — refuse to overwrite
            # (reference flags the pair split-brained and waits for
            # an operator resync)
            return {"split_brain": True}
        stats["replayed"] = self._replay(name, dst_img, hdr)
        return stats

    def _replay(self, name: str, dst_img: Image, hdr: dict) -> int:
        """Incremental replay of the master journal into dst_img
        (shared by steady-state sync and promote's final catch-up so
        the two can never diverge); -> events applied."""
        synced = self._synced_pos(name)
        top = synced
        applied = 0
        for ev in sorted(self._journal_entries(name),
                         key=lambda e: e["seq"]):
            if ev["seq"] <= synced:
                continue
            if "resize" in ev:
                # object-level resize replay: shrink must shed the
                # secondary's truncated objects, not just the header
                # size, or a later grow re-exposes stale bytes
                dst_img._apply_resize(ev["resize"])
            else:
                dst_img._apply_write(ev["off"],
                                     base64.b64decode(ev["data"]))
            top = max(top, ev["seq"])
            applied += 1
        if dst_img.header["size"] != hdr["size"]:
            # drift safety net (resize that predates mirroring or a
            # trimmed journal): correct at the object level too
            dst_img._apply_resize(hdr["size"])
        if top != synced:
            self._record_pos(name, top)
        return applied

    def sync_once(self) -> Dict[str, Dict]:
        """One pass over every image at the primary site (the
        reference daemon's continuous replay loop, collapsed to a
        drivable step for tests/cron)."""
        out = {}
        for name in RBD(self.src).list():
            try:
                out[name] = self.sync_image(name)
            except RadosError as e:
                out[name] = {"error": str(e)}
        return out

    # -- failover ------------------------------------------------------
    def promote(self, name: str) -> None:
        """Promote the SECONDARY copy (reference rbd mirror image
        promote at the failover site): final journal catch-up, then
        flip primary.  The catch-up deliberately ignores the
        source's primary flag — the documented flow demotes the old
        primary FIRST, and its journal tail (writes the peer had not
        consumed at demotion) must drain here, not be lost."""
        try:
            hdr = json.loads(self.src.read(
                _header_oid(name)).decode())
        except RadosError:
            hdr = None               # old site gone: promote what we
                                     # have (disaster failover)
        if hdr is not None and (hdr.get("mirror") or {}).get(
                "enabled") and name in RBD(self.dst).list():
            dst_img = Image(self.dst, name)
            if (dst_img.header.get("mirror") or {}).get("enabled"):
                self._replay(name, dst_img, hdr)
        img = Image(self.dst, name)
        img.mirror_promote()

    def demote_primary(self, name: str) -> None:
        """Demote the source copy (failover step 1)."""
        Image(self.src, name).mirror_demote()
