"""RGW: S3-style object gateway (reference src/rgw/, SURVEY §2.6)."""
from .gateway import RGWService, RGWError  # noqa: F401
