"""S3 authentication: user store + AWS Signature V4 verification.

Python-native equivalent of the reference's RGW auth layer (reference
``src/rgw/rgw_auth_s3.{h,cc}`` AWSv4ComplMulti/rgw_create_s3_v4_*
+ the user store RGWUserCtl / radosgw-admin ``user create``):

* users live in a RADOS omap (`rgw.users`): uid -> access/secret keys
  and display name, keyed ALSO by access key for O(1) auth lookup;
* requests carry ``Authorization: AWS4-HMAC-SHA256 Credential=...``;
  the gateway rebuilds the canonical request per the public SigV4
  spec, derives the signing key from the stored secret, and compares
  digests constant-time.  ``UNSIGNED-PAYLOAD`` and signed payload
  hashes are both accepted (the reference likewise).
"""
from __future__ import annotations

import calendar
import hashlib
import hmac
import json
import secrets
import time
import urllib.parse
from typing import Dict, Optional, Tuple

from ..client.rados import RadosError
from .gateway import RGWError

USERS_OID = "rgw.users"
AKEY_PREFIX = "ak."                  # access-key -> uid mapping rows
SKEW = 15 * 60                       # clock skew window (reference 15m)


class UserStore:
    """radosgw-admin-style user admin (reference RGWUserCtl)."""

    def __init__(self, ioctx):
        self.ioctx = ioctx

    def create_user(self, uid: str, display_name: str = "") -> dict:
        if self.get_user(uid) is not None:
            raise RGWError(409, "UserAlreadyExists", uid)
        access = "AK" + secrets.token_hex(9).upper()
        secret = secrets.token_urlsafe(30)
        user = {"uid": uid, "display_name": display_name or uid,
                "access_key": access, "secret_key": secret,
                "created": time.time()}
        self.ioctx.omap_set(USERS_OID, {
            uid: json.dumps(user).encode(),
            AKEY_PREFIX + access: uid.encode()})
        return user

    def get_user(self, uid: str) -> Optional[dict]:
        try:
            raw = self.ioctx.omap_get_by_key(USERS_OID, uid)
        except RadosError:
            return None
        return json.loads(raw.decode()) if raw else None

    def user_by_access_key(self, access: str) -> Optional[dict]:
        try:
            uid = self.ioctx.omap_get_by_key(USERS_OID,
                                             AKEY_PREFIX + access)
        except RadosError:
            return None
        return self.get_user(uid.decode()) if uid else None

    def remove_user(self, uid: str) -> None:
        user = self.get_user(uid)
        if user is None:
            raise RGWError(404, "NoSuchUser", uid)
        self.ioctx.omap_rm_keys(USERS_OID, [
            uid, AKEY_PREFIX + user["access_key"]])

    def list_users(self):
        try:
            omap = self.ioctx.omap_get(USERS_OID)
        except RadosError:
            return []
        return sorted(k for k in omap
                      if not k.startswith(AKEY_PREFIX))


# ---------------------------------------------------------------------------
# SigV4 (public AWS spec; reference rgw_auth_s3.cc)
# ---------------------------------------------------------------------------

def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str,
                service: str = "s3") -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def _canonical_query(query: str) -> str:
    pairs = []
    for part in query.split("&") if query else []:
        if not part:
            continue
        k, _, v = part.partition("=")
        pairs.append((urllib.parse.quote(urllib.parse.unquote(k),
                                         safe="-_.~"),
                      urllib.parse.quote(urllib.parse.unquote(v),
                                         safe="-_.~")))
    return "&".join(f"{k}={v}" for k, v in sorted(pairs))


def sign_request(method: str, path: str, query: str,
                 headers: Dict[str, str], payload_hash: str,
                 access: str, secret: str, region: str = "us-east-1",
                 amz_date: Optional[str] = None) -> Dict[str, str]:
    """Client-side signer (tests + any SDK-less tooling): returns the
    headers to add (Authorization, x-amz-date, x-amz-content-sha256)."""
    amz_date = amz_date or time.strftime("%Y%m%dT%H%M%SZ",
                                         time.gmtime())
    date = amz_date[:8]
    hdrs = {k.lower(): v.strip() for k, v in headers.items()}
    hdrs["x-amz-date"] = amz_date
    hdrs["x-amz-content-sha256"] = payload_hash
    signed = ";".join(sorted(hdrs))
    # ``path`` must be the exact (already percent-encoded) path that
    # will go on the request line
    canonical = "\n".join([
        method,
        path,
        _canonical_query(query),
        "".join(f"{k}:{hdrs[k]}\n" for k in sorted(hdrs)),
        signed,
        payload_hash])
    scope = f"{date}/{region}/s3/aws4_request"
    sts = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])
    sig = hmac.new(signing_key(secret, date, region), sts.encode(),
                   hashlib.sha256).hexdigest()
    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}"),
    }


class SigV4Verifier:
    """Server-side verification (reference rgw::auth::s3)."""

    def __init__(self, users: UserStore):
        self.users = users

    def verify(self, method: str, path: str, query: str,
               headers: Dict[str, str], body: bytes) -> dict:
        """-> the authenticated user dict; raises RGWError."""
        headers = {k.lower(): str(v).strip()
                   for k, v in headers.items()}
        auth = headers.get("authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256 "):
            raise RGWError(403, "AccessDenied",
                           "missing SigV4 authorization")
        fields: Dict[str, str] = {}
        for part in auth[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = part.strip().partition("=")
            fields[k] = v
        try:
            access, date, region, service, term = \
                fields["Credential"].split("/")
            signed_headers = fields["SignedHeaders"].split(";")
            given_sig = fields["Signature"]
        except (KeyError, ValueError):
            raise RGWError(400, "AuthorizationHeaderMalformed", auth)
        user = self.users.user_by_access_key(access)
        if user is None:
            raise RGWError(403, "InvalidAccessKeyId", access)

        amz_date = headers.get("x-amz-date", "")
        if not amz_date:
            raise RGWError(403, "AccessDenied", "missing x-amz-date")
        try:
            req_time = calendar.timegm(time.strptime(
                amz_date, "%Y%m%dT%H%M%SZ"))
        except ValueError:
            raise RGWError(403, "AccessDenied", "bad x-amz-date")
        if abs(time.time() - req_time) > SKEW:
            raise RGWError(403, "RequestTimeTooSkewed", amz_date)

        payload_hash = headers.get("x-amz-content-sha256",
                                   "UNSIGNED-PAYLOAD")
        if payload_hash not in ("UNSIGNED-PAYLOAD",):
            actual = hashlib.sha256(body).hexdigest()
            if payload_hash != actual:
                raise RGWError(400, "XAmzContentSHA256Mismatch",
                               payload_hash)

        # canonical URI = the path exactly as sent on the request
        # line (clients sign the single-encoded form; re-quoting here
        # would double-encode %xx and reject keys with spaces)
        canonical = "\n".join([
            method,
            path,
            _canonical_query(query),
            "".join(f"{k}:{headers.get(k, '')}\n"
                    for k in sorted(signed_headers)),
            ";".join(sorted(signed_headers)),
            payload_hash])
        scope = f"{date}/{region}/{service}/{term}"
        sts = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])
        want = hmac.new(
            signing_key(user["secret_key"], date, region, service),
            sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, given_sig):
            raise RGWError(403, "SignatureDoesNotMatch", access)
        return user
