"""Object gateway core: buckets + objects over RADOS.

Python-native equivalent of the reference's RGW data layer (reference
``src/rgw/`` 182.6k LoC reduced to the S3 essentials): buckets are
metadata objects plus an omap **bucket index** listing keys in order
(reference cls_rgw bucket-index objects; omap gives the sorted
prefix/marker listing semantics S3 needs), object data+metadata live
in per-key RADOS objects, ETag is the content MD5 like S3.

Large objects stripe via the striper when they exceed one chunk
(reference RGW stripes tail objects the same way).  Auth, multisite,
lifecycle, versioning are out of scope; the HTTP frontend lives in
``server.py``.
"""
from __future__ import annotations

import hashlib
import json
import time
from typing import Dict, List, Optional, Tuple

from ..client.rados import IoCtx, RadosError
from ..client.striper import Layout, StripedIoCtx

BUCKETS_DIR = "rgw.buckets"          # gateway-wide bucket directory
CHUNK = 4 << 20


class RGWError(Exception):
    def __init__(self, status: int, code: str, msg: str = ""):
        super().__init__(f"{status} {code} {msg}")
        self.status = status
        self.code = code


def _index_oid(bucket: str) -> str:
    # length-prefixed bucket name: '.' is legal inside bucket names,
    # so 'rgw.index.<bucket>' alone would let (bucket, key) pairs
    # collide across buckets
    return f"rgw.index.{len(bucket)}.{bucket}"


def _data_soid(bucket: str, key: str) -> str:
    return f"rgw.data.{len(bucket)}.{bucket}.{key}"


def _mp_index_oid(bucket: str) -> str:
    return f"rgw.mp.{len(bucket)}.{bucket}"


def _part_soid(bucket: str, upload_id: str, num: int) -> str:
    return f"rgw.part.{len(bucket)}.{bucket}.{upload_id}.{num:05d}"


class MultipartMixin:
    """Multipart operations (reference rgw_multi.cc).  Every part is
    its OWN omap row (``<upload_id>.part.<n>``): per-key mutations are
    atomic at the OSD, so concurrent part uploads — the normal
    multipart pattern — cannot lose each other (a read-modify-write
    of one JSON record would)."""

    def initiate_multipart(self, bucket: str, key: str,
                           content_type: str = "binary/octet-stream",
                           meta: Optional[Dict[str, str]] = None
                           ) -> str:
        self._check_bucket(bucket)
        if not key:
            raise RGWError(400, "InvalidArgument", "empty key")
        import secrets as _secrets
        upload_id = _secrets.token_hex(16)
        rec = {"key": key, "content_type": content_type,
               "meta": meta or {}, "started": time.time()}
        self.ioctx.omap_set(_mp_index_oid(bucket),
                            {upload_id: json.dumps(rec).encode()})
        return upload_id

    def _mp_get(self, bucket: str, upload_id: str,
                key: Optional[str] = None) -> dict:
        try:
            raw = self.ioctx.omap_get_by_key(_mp_index_oid(bucket),
                                             upload_id)
        except RadosError:
            raw = None
        if raw is None:
            raise RGWError(404, "NoSuchUpload", upload_id)
        rec = json.loads(raw.decode())
        if key is not None and rec["key"] != key:
            # completing/uploading under a different key must not
            # silently write the object there (S3: NoSuchUpload)
            raise RGWError(404, "NoSuchUpload",
                           f"{upload_id} is for {rec['key']!r}")
        return rec

    def _mp_parts(self, bucket: str, upload_id: str
                  ) -> Dict[int, dict]:
        try:
            omap = self.ioctx.omap_get(_mp_index_oid(bucket))
        except RadosError:
            return {}
        prefix = f"{upload_id}.part."
        return {int(k[len(prefix):]): json.loads(v.decode())
                for k, v in omap.items() if k.startswith(prefix)}

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part_num: int, data: bytes) -> str:
        if not 1 <= part_num <= self._part_limit:
            raise RGWError(400, "InvalidPartNumber", str(part_num))
        self._mp_get(bucket, upload_id, key)
        etag = hashlib.md5(data).hexdigest()
        soid = _part_soid(bucket, upload_id, part_num)
        self.striper.write(soid, data)
        self.striper.truncate(soid, len(data))
        self.ioctx.omap_set(_mp_index_oid(bucket), {
            f"{upload_id}.part.{part_num}": json.dumps(
                {"etag": etag, "size": len(data),
                 "mtime": time.time()}).encode()})
        return etag

    def list_parts(self, bucket: str, upload_id: str) -> List[dict]:
        self._mp_get(bucket, upload_id)
        return [{"part": n, **p} for n, p in
                sorted(self._mp_parts(bucket, upload_id).items())]

    def list_multipart_uploads(self, bucket: str) -> List[dict]:
        self._check_bucket(bucket)
        try:
            omap = self.ioctx.omap_get(_mp_index_oid(bucket))
        except RadosError:
            return []
        out = []
        for uid, raw in sorted(omap.items()):
            if ".part." in uid:
                continue
            rec = json.loads(raw.decode())
            out.append({"upload_id": uid, "key": rec["key"],
                        "started": rec["started"]})
        return out

    def complete_multipart(self, bucket: str, key: str,
                           upload_id: str,
                           parts: List[Tuple[int, str]]) -> str:
        """Assemble the final object from the client's ordered part
        list (reference RGWCompleteMultipart: validates every part's
        ETag, concatenates, S3 multipart ETag = md5(part-md5s)-N)."""
        rec = self._mp_get(bucket, upload_id, key)
        have_parts = self._mp_parts(bucket, upload_id)
        if not parts:
            raise RGWError(400, "MalformedXML", "no parts")
        last = 0
        md5s = b""
        total = 0
        for num, etag in parts:
            if num <= last:
                raise RGWError(400, "InvalidPartOrder", str(num))
            last = num
            have = have_parts.get(num)
            if have is None or have["etag"] != etag.strip('"'):
                raise RGWError(400, "InvalidPart", str(num))
            md5s += bytes.fromhex(have["etag"])
            total += have["size"]
        final_etag = (hashlib.md5(md5s).hexdigest()
                      + f"-{len(parts)}")
        soid = _data_soid(bucket, key)
        off = 0
        for num, _ in parts:
            data = self.striper.read(_part_soid(bucket, upload_id,
                                                num))
            self.striper.write(soid, data, off)
            off += len(data)
        self.striper.truncate(soid, total)
        entry = {"size": total, "etag": final_etag,
                 "mtime": time.time(),
                 "content_type": rec["content_type"],
                 "meta": rec["meta"]}
        self.ioctx.omap_set(_index_oid(bucket),
                            {key: json.dumps(entry).encode()})
        self._mp_cleanup(bucket, upload_id, rec)
        return final_etag

    def abort_multipart(self, bucket: str, upload_id: str) -> None:
        rec = self._mp_get(bucket, upload_id)
        self._mp_cleanup(bucket, upload_id, rec)

    def _mp_cleanup(self, bucket: str, upload_id: str,
                    rec: dict) -> None:
        parts = self._mp_parts(bucket, upload_id)
        for n in parts:
            try:
                self.striper.remove(_part_soid(bucket, upload_id, n))
            except RadosError:
                pass
        self.ioctx.omap_rm_keys(
            _mp_index_oid(bucket),
            [upload_id] + [f"{upload_id}.part.{n}" for n in parts])


class RGWService(MultipartMixin):
    """Bucket/object operations (reference RGWRados)."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx
        from ..utils.config import default_config
        conf = getattr(ioctx.rados, "conf", None) or default_config()
        self._list_max = conf["rgw_list_max_keys"]
        self._part_limit = conf["rgw_multipart_part_limit"]
        self._max_put = conf["rgw_max_put_size"]
        self.striper = StripedIoCtx(
            ioctx, Layout(stripe_unit=CHUNK, stripe_count=1,
                          object_size=CHUNK))

    # -- buckets (reference RGWRados::create_bucket) -------------------
    # The directory is an omap on one object: per-key mutations are
    # atomic at the OSD, so concurrent bucket create/delete cannot
    # lose each other's updates (a read-modify-write JSON blob could).
    def list_buckets(self) -> List[dict]:
        try:
            omap = self.ioctx.omap_get(BUCKETS_DIR)
        except RadosError:
            return []
        return [json.loads(v.decode())
                for _, v in sorted(omap.items())]

    def create_bucket(self, bucket: str) -> None:
        if not bucket or "/" in bucket or "." == bucket[0]:
            raise RGWError(400, "InvalidBucketName", bucket)
        try:
            if bucket in self.ioctx.omap_get(BUCKETS_DIR):
                raise RGWError(409, "BucketAlreadyExists", bucket)
        except RadosError:
            pass
        meta = {"name": bucket, "created": time.time()}
        self.ioctx.omap_set(BUCKETS_DIR,
                            {bucket: json.dumps(meta).encode()})
        self.ioctx.create(_index_oid(bucket))

    def _check_bucket(self, bucket: str) -> None:
        try:
            if self.ioctx.omap_get_by_key(BUCKETS_DIR,
                                          bucket) is not None:
                return
        except RadosError:
            pass
        raise RGWError(404, "NoSuchBucket", bucket)

    def delete_bucket(self, bucket: str) -> None:
        self._check_bucket(bucket)
        if self.ioctx.omap_get(_index_oid(bucket)):
            raise RGWError(409, "BucketNotEmpty", bucket)
        if self.list_multipart_uploads(bucket):
            # S3: in-progress uploads must be aborted first; deleting
            # around them would orphan part objects and resurrect the
            # uploads if the name is recreated
            raise RGWError(409, "BucketNotEmpty",
                           f"{bucket}: multipart uploads in progress")
        for oid in (_index_oid(bucket), _mp_index_oid(bucket)):
            try:
                self.ioctx.remove(oid)
            except RadosError:
                pass
        self.ioctx.omap_rm_keys(BUCKETS_DIR, [bucket])

    # -- objects (reference RGWRados::Object::Write/Read) --------------
    def put_object(self, bucket: str, key: str, data: bytes,
                   content_type: str = "binary/octet-stream",
                   meta: Optional[Dict[str, str]] = None) -> str:
        self._check_bucket(bucket)
        if not key:
            raise RGWError(400, "InvalidArgument", "empty key")
        if len(data) > self._max_put:
            raise RGWError(400, "EntityTooLarge", key)
        etag = hashlib.md5(data).hexdigest()
        soid = _data_soid(bucket, key)
        self.striper.write(soid, data)
        # shrink past the new end: overwriting a larger object must
        # not serve the previous object's tail
        self.striper.truncate(soid, len(data))
        # index entry AFTER data (reference prepare/complete index
        # transaction: a failed put must not list)
        entry = {"size": len(data), "etag": etag,
                 "mtime": time.time(), "content_type": content_type,
                 "meta": meta or {}}
        self.ioctx.omap_set(_index_oid(bucket),
                            {key: json.dumps(entry).encode()})
        return etag

    def head_object(self, bucket: str, key: str) -> dict:
        self._check_bucket(bucket)
        try:
            entry = self.ioctx.omap_get_by_key(_index_oid(bucket),
                                               key)
        except RadosError:
            entry = None
        if entry is None:
            raise RGWError(404, "NoSuchKey", key)
        return json.loads(entry.decode())

    def get_object(self, bucket: str, key: str,
                   rng: Optional[Tuple[int, int]] = None
                   ) -> Tuple[dict, bytes]:
        head = self.head_object(bucket, key)
        soid = _data_soid(bucket, key)
        if head["size"] == 0:
            return head, b""
        if rng is None:
            return head, self.striper.read(soid)
        start, end = rng
        end = min(end, head["size"] - 1)
        if start > end:
            raise RGWError(416, "InvalidRange", key)
        return head, self.striper.read(soid, end - start + 1, start)

    def delete_object(self, bucket: str, key: str) -> None:
        self._check_bucket(bucket)
        idx = _index_oid(bucket)
        if self.ioctx.omap_get_by_key(idx, key) is None:
            raise RGWError(404, "NoSuchKey", key)
        try:
            self.striper.remove(_data_soid(bucket, key))
        except RadosError:
            pass
        self.ioctx.omap_rm_keys(idx, [key])

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", max_keys: Optional[int] = None,
                     delimiter: str = "") -> dict:
        """S3 ListObjects semantics: sorted keys, prefix filter,
        marker resume, delimiter common-prefix rollup (reference
        cls_rgw bucket listing + RGWListBucket)."""
        if max_keys is None:
            max_keys = self._list_max    # reference rgw_max_listing_results
        self._check_bucket(bucket)
        omap = self.ioctx.omap_get(_index_oid(bucket))
        keys = sorted(k for k in omap
                      if k.startswith(prefix) and k > marker)
        contents: List[dict] = []
        common: List[str] = []
        truncated = False
        for k in keys:
            if len(contents) + len(common) >= max_keys:
                truncated = True
                break
            if delimiter:
                rest = k[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter, 1)[0] \
                        + delimiter
                    if cp not in common:
                        common.append(cp)
                    continue
            entry = json.loads(omap[k].decode())
            contents.append({"key": k, "size": entry["size"],
                             "etag": entry["etag"],
                             "mtime": entry["mtime"]})
        return {"bucket": bucket, "prefix": prefix, "marker": marker,
                "contents": contents, "common_prefixes": common,
                "is_truncated": truncated}
