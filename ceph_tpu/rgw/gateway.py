"""Object gateway core: buckets + objects over RADOS.

Python-native equivalent of the reference's RGW data layer (reference
``src/rgw/`` 182.6k LoC reduced to the S3 essentials): buckets are
metadata objects plus an omap **bucket index** listing keys in order
(reference cls_rgw bucket-index objects; omap gives the sorted
prefix/marker listing semantics S3 needs), object data+metadata live
in per-key RADOS objects, ETag is the content MD5 like S3.

Large objects stripe via the striper when they exceed one chunk
(reference RGW stripes tail objects the same way).  Auth, multisite,
lifecycle, versioning are out of scope; the HTTP frontend lives in
``server.py``.
"""
from __future__ import annotations

import hashlib
import json
import time
from typing import Dict, List, Optional, Tuple

from ..client.rados import IoCtx, RadosError
from ..client.striper import Layout, StripedIoCtx

BUCKETS_DIR = "rgw.buckets"          # gateway-wide bucket directory
CHUNK = 4 << 20


class RGWError(Exception):
    def __init__(self, status: int, code: str, msg: str = ""):
        super().__init__(f"{status} {code} {msg}")
        self.status = status
        self.code = code


def _index_oid(bucket: str) -> str:
    # length-prefixed bucket name: '.' is legal inside bucket names,
    # so 'rgw.index.<bucket>' alone would let (bucket, key) pairs
    # collide across buckets
    return f"rgw.index.{len(bucket)}.{bucket}"


def _data_soid(bucket: str, key: str) -> str:
    return f"rgw.data.{len(bucket)}.{bucket}.{key}"


class RGWService:
    """Bucket/object operations (reference RGWRados)."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx
        self.striper = StripedIoCtx(
            ioctx, Layout(stripe_unit=CHUNK, stripe_count=1,
                          object_size=CHUNK))

    # -- buckets (reference RGWRados::create_bucket) -------------------
    # The directory is an omap on one object: per-key mutations are
    # atomic at the OSD, so concurrent bucket create/delete cannot
    # lose each other's updates (a read-modify-write JSON blob could).
    def list_buckets(self) -> List[dict]:
        try:
            omap = self.ioctx.omap_get(BUCKETS_DIR)
        except RadosError:
            return []
        return [json.loads(v.decode())
                for _, v in sorted(omap.items())]

    def create_bucket(self, bucket: str) -> None:
        if not bucket or "/" in bucket or "." == bucket[0]:
            raise RGWError(400, "InvalidBucketName", bucket)
        try:
            if bucket in self.ioctx.omap_get(BUCKETS_DIR):
                raise RGWError(409, "BucketAlreadyExists", bucket)
        except RadosError:
            pass
        meta = {"name": bucket, "created": time.time()}
        self.ioctx.omap_set(BUCKETS_DIR,
                            {bucket: json.dumps(meta).encode()})
        self.ioctx.create(_index_oid(bucket))

    def _check_bucket(self, bucket: str) -> None:
        try:
            if self.ioctx.omap_get_by_key(BUCKETS_DIR,
                                          bucket) is not None:
                return
        except RadosError:
            pass
        raise RGWError(404, "NoSuchBucket", bucket)

    def delete_bucket(self, bucket: str) -> None:
        self._check_bucket(bucket)
        if self.ioctx.omap_get(_index_oid(bucket)):
            raise RGWError(409, "BucketNotEmpty", bucket)
        try:
            self.ioctx.remove(_index_oid(bucket))
        except RadosError:
            pass
        self.ioctx.omap_rm_keys(BUCKETS_DIR, [bucket])

    # -- objects (reference RGWRados::Object::Write/Read) --------------
    def put_object(self, bucket: str, key: str, data: bytes,
                   content_type: str = "binary/octet-stream",
                   meta: Optional[Dict[str, str]] = None) -> str:
        self._check_bucket(bucket)
        if not key:
            raise RGWError(400, "InvalidArgument", "empty key")
        etag = hashlib.md5(data).hexdigest()
        soid = _data_soid(bucket, key)
        self.striper.write(soid, data)
        # shrink past the new end: overwriting a larger object must
        # not serve the previous object's tail
        self.striper.truncate(soid, len(data))
        # index entry AFTER data (reference prepare/complete index
        # transaction: a failed put must not list)
        entry = {"size": len(data), "etag": etag,
                 "mtime": time.time(), "content_type": content_type,
                 "meta": meta or {}}
        self.ioctx.omap_set(_index_oid(bucket),
                            {key: json.dumps(entry).encode()})
        return etag

    def head_object(self, bucket: str, key: str) -> dict:
        self._check_bucket(bucket)
        try:
            entry = self.ioctx.omap_get_by_key(_index_oid(bucket),
                                               key)
        except RadosError:
            entry = None
        if entry is None:
            raise RGWError(404, "NoSuchKey", key)
        return json.loads(entry.decode())

    def get_object(self, bucket: str, key: str,
                   rng: Optional[Tuple[int, int]] = None
                   ) -> Tuple[dict, bytes]:
        head = self.head_object(bucket, key)
        soid = _data_soid(bucket, key)
        if head["size"] == 0:
            return head, b""
        if rng is None:
            return head, self.striper.read(soid)
        start, end = rng
        end = min(end, head["size"] - 1)
        if start > end:
            raise RGWError(416, "InvalidRange", key)
        return head, self.striper.read(soid, end - start + 1, start)

    def delete_object(self, bucket: str, key: str) -> None:
        self._check_bucket(bucket)
        idx = _index_oid(bucket)
        if self.ioctx.omap_get_by_key(idx, key) is None:
            raise RGWError(404, "NoSuchKey", key)
        try:
            self.striper.remove(_data_soid(bucket, key))
        except RadosError:
            pass
        self.ioctx.omap_rm_keys(idx, [key])

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", max_keys: int = 1000,
                     delimiter: str = "") -> dict:
        """S3 ListObjects semantics: sorted keys, prefix filter,
        marker resume, delimiter common-prefix rollup (reference
        cls_rgw bucket listing + RGWListBucket)."""
        self._check_bucket(bucket)
        omap = self.ioctx.omap_get(_index_oid(bucket))
        keys = sorted(k for k in omap
                      if k.startswith(prefix) and k > marker)
        contents: List[dict] = []
        common: List[str] = []
        truncated = False
        for k in keys:
            if len(contents) + len(common) >= max_keys:
                truncated = True
                break
            if delimiter:
                rest = k[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter, 1)[0] \
                        + delimiter
                    if cp not in common:
                        common.append(cp)
                    continue
            entry = json.loads(omap[k].decode())
            contents.append({"key": k, "size": entry["size"],
                             "etag": entry["etag"],
                             "mtime": entry["mtime"]})
        return {"bucket": bucket, "prefix": prefix, "marker": marker,
                "contents": contents, "common_prefixes": common,
                "is_truncated": truncated}
