"""Object gateway core: buckets + objects over RADOS.

Python-native equivalent of the reference's RGW data layer (reference
``src/rgw/`` 182.6k LoC reduced to the S3 essentials): buckets are
metadata objects plus an omap **bucket index** listing keys in order
(reference cls_rgw bucket-index objects; omap gives the sorted
prefix/marker listing semantics S3 needs), object data+metadata live
in per-key RADOS objects, ETag is the content MD5 like S3.

Large objects stripe via the striper when they exceed one chunk
(reference RGW stripes tail objects the same way).  Versioning
(version rows in the same bucket index, delete markers, null-version
semantics — reference rgw_op.cc:2661 versioning_enabled +
rgw_bucket_index entry instances), canned ACLs (reference
rgw_acl_s3.cc) and lifecycle expiration (reference rgw_lc.cc) live
here too; the HTTP frontend is ``server.py``.
"""
from __future__ import annotations

import hashlib
import json
import secrets
import time
from typing import Dict, List, Optional, Tuple

from ..client.rados import IoCtx, RadosError
from ..client.striper import Layout, StripedIoCtx

BUCKETS_DIR = "rgw.buckets"          # gateway-wide bucket directory
CHUNK = 4 << 20

CANNED_ACLS = ("private", "public-read", "public-read-write",
               "authenticated-read")


class RGWError(Exception):
    def __init__(self, status: int, code: str, msg: str = ""):
        super().__init__(f"{status} {code} {msg}")
        self.status = status
        self.code = code


def _index_oid(bucket: str) -> str:
    # length-prefixed bucket name: '.' is legal inside bucket names,
    # so 'rgw.index.<bucket>' alone would let (bucket, key) pairs
    # collide across buckets
    return f"rgw.index.{len(bucket)}.{bucket}"


def _data_soid(bucket: str, key: str) -> str:
    return f"rgw.data.{len(bucket)}.{bucket}.{key}"


def _datalog_oid(bucket: str) -> str:
    """Per-bucket change log feeding multisite incremental sync
    (reference cls_rgw bucket index log + rgw_data_sync.cc's datalog):
    omap rows keyed by a monotonic-enough timestamp, value = the
    mutated key and op.  Trimmed by the sync agent once every peer
    zone has consumed them."""
    return f"rgw.datalog.{len(bucket)}.{bucket}"


def _vkey(key: str, vid: str) -> str:
    """Bucket-index row for one VERSION of a key.  NUL separates key
    from version id (keys containing NUL are rejected at PUT), and
    sorts before every printable byte, so a key's version rows
    cluster directly after its current row in omap order (the
    reference's bucket-index instance entries use the same
    key+instance composite)."""
    return f"{key}\x00{vid}"


def _data_vsoid(bucket: str, key: str, vid: str) -> str:
    """Version data object; the null version lives at the base soid
    (an object written before versioning was enabled IS the null
    version, reference rgw null-instance semantics)."""
    base = _data_soid(bucket, key)
    return base if vid == "null" else f"{base}\x00{vid}"


def _new_vid() -> str:
    """Opaque version id that sorts LEXICALLY NEWEST-FIRST (S3 lists
    versions newest first; an inverted-timestamp prefix gives that
    order straight out of the sorted omap)."""
    return f"{(1 << 63) - time.time_ns():016x}{secrets.token_hex(4)}"


def _mp_index_oid(bucket: str) -> str:
    return f"rgw.mp.{len(bucket)}.{bucket}"


def _part_soid(bucket: str, upload_id: str, num: int) -> str:
    return f"rgw.part.{len(bucket)}.{bucket}.{upload_id}.{num:05d}"


class MultipartMixin:
    """Multipart operations (reference rgw_multi.cc).  Every part is
    its OWN omap row (``<upload_id>.part.<n>``): per-key mutations are
    atomic at the OSD, so concurrent part uploads — the normal
    multipart pattern — cannot lose each other (a read-modify-write
    of one JSON record would)."""

    def initiate_multipart(self, bucket: str, key: str,
                           content_type: str = "binary/octet-stream",
                           meta: Optional[Dict[str, str]] = None
                           ) -> str:
        self._check_bucket(bucket)
        if not key:
            raise RGWError(400, "InvalidArgument", "empty key")
        if "\x00" in key:
            # same reservation as put_object: a NUL key would complete
            # into an index row the versioning machinery parses as a
            # version row
            raise RGWError(400, "InvalidArgument",
                           "NUL in key reserved for version rows")
        import secrets as _secrets
        upload_id = _secrets.token_hex(16)
        rec = {"key": key, "content_type": content_type,
               "meta": meta or {}, "started": time.time()}
        self.ioctx.omap_set(_mp_index_oid(bucket),
                            {upload_id: json.dumps(rec).encode()})
        return upload_id

    def _mp_get(self, bucket: str, upload_id: str,
                key: Optional[str] = None) -> dict:
        try:
            raw = self.ioctx.omap_get_by_key(_mp_index_oid(bucket),
                                             upload_id)
        except RadosError:
            raw = None
        if raw is None:
            raise RGWError(404, "NoSuchUpload", upload_id)
        rec = json.loads(raw.decode())
        if key is not None and rec["key"] != key:
            # completing/uploading under a different key must not
            # silently write the object there (S3: NoSuchUpload)
            raise RGWError(404, "NoSuchUpload",
                           f"{upload_id} is for {rec['key']!r}")
        return rec

    def _mp_parts(self, bucket: str, upload_id: str
                  ) -> Dict[int, dict]:
        try:
            omap = self.ioctx.omap_get(_mp_index_oid(bucket))
        except RadosError:
            return {}
        prefix = f"{upload_id}.part."
        return {int(k[len(prefix):]): json.loads(v.decode())
                for k, v in omap.items() if k.startswith(prefix)}

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part_num: int, data: bytes) -> str:
        if not 1 <= part_num <= self._part_limit:
            raise RGWError(400, "InvalidPartNumber", str(part_num))
        self._mp_get(bucket, upload_id, key)
        etag = hashlib.md5(data).hexdigest()
        soid = _part_soid(bucket, upload_id, part_num)
        self.striper.write(soid, data)
        self.striper.truncate(soid, len(data))
        self.ioctx.omap_set(_mp_index_oid(bucket), {
            f"{upload_id}.part.{part_num}": json.dumps(
                {"etag": etag, "size": len(data),
                 "mtime": time.time()}).encode()})
        return etag

    def list_parts(self, bucket: str, upload_id: str) -> List[dict]:
        self._mp_get(bucket, upload_id)
        return [{"part": n, **p} for n, p in
                sorted(self._mp_parts(bucket, upload_id).items())]

    def list_multipart_uploads(self, bucket: str) -> List[dict]:
        self._check_bucket(bucket)
        try:
            omap = self.ioctx.omap_get(_mp_index_oid(bucket))
        except RadosError:
            return []
        out = []
        for uid, raw in sorted(omap.items()):
            if ".part." in uid:
                continue
            rec = json.loads(raw.decode())
            out.append({"upload_id": uid, "key": rec["key"],
                        "started": rec["started"]})
        return out

    def complete_multipart(self, bucket: str, key: str,
                           upload_id: str,
                           parts: List[Tuple[int, str]]) -> str:
        """Assemble the final object from the client's ordered part
        list (reference RGWCompleteMultipart: validates every part's
        ETag, concatenates, S3 multipart ETag = md5(part-md5s)-N)."""
        rec = self._mp_get(bucket, upload_id, key)
        have_parts = self._mp_parts(bucket, upload_id)
        if not parts:
            raise RGWError(400, "MalformedXML", "no parts")
        last = 0
        md5s = b""
        total = 0
        for num, etag in parts:
            if num <= last:
                raise RGWError(400, "InvalidPartOrder", str(num))
            last = num
            have = have_parts.get(num)
            if have is None or have["etag"] != etag.strip('"'):
                raise RGWError(400, "InvalidPart", str(num))
            md5s += bytes.fromhex(have["etag"])
            total += have["size"]
        final_etag = (hashlib.md5(md5s).hexdigest()
                      + f"-{len(parts)}")
        bmeta = self._bucket_meta(bucket)
        versioning = bmeta.get("versioning", "off")
        idx = _index_oid(bucket)
        vid = _new_vid() if versioning == "enabled" else "null"
        rows: Dict[str, bytes] = {}
        if versioning == "enabled":
            self._materialize_null_version(idx, bucket, key, rows)
        soid = _data_vsoid(bucket, key, vid)
        off = 0
        for num, _ in parts:
            data = self.striper.read(_part_soid(bucket, upload_id,
                                                num))
            self.striper.write(soid, data, off)
            off += len(data)
        self.striper.truncate(soid, total)
        entry = {"size": total, "etag": final_etag,
                 "mtime": time.time(),
                 "content_type": rec["content_type"],
                 "meta": rec["meta"], "version_id": vid,
                 "acl": "private",
                 "owner": bmeta.get("owner", "")}
        enc = json.dumps(entry).encode()
        rows[key] = enc
        if versioning != "off":
            rows[_vkey(key, vid)] = enc
        self.ioctx.omap_set(idx, rows)
        self._datalog(bucket, key, "put")
        self._mp_cleanup(bucket, upload_id, rec)
        return final_etag

    def abort_multipart(self, bucket: str, upload_id: str) -> None:
        rec = self._mp_get(bucket, upload_id)
        self._mp_cleanup(bucket, upload_id, rec)

    def _mp_cleanup(self, bucket: str, upload_id: str,
                    rec: dict) -> None:
        parts = self._mp_parts(bucket, upload_id)
        for n in parts:
            try:
                self.striper.remove(_part_soid(bucket, upload_id, n))
            except RadosError:
                pass
        self.ioctx.omap_rm_keys(
            _mp_index_oid(bucket),
            [upload_id] + [f"{upload_id}.part.{n}" for n in parts])


class RGWService(MultipartMixin):
    """Bucket/object operations (reference RGWRados)."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx
        from ..utils.config import default_config
        conf = getattr(ioctx.rados, "conf", None) or default_config()
        self._list_max = conf["rgw_list_max_keys"]
        self._part_limit = conf["rgw_multipart_part_limit"]
        self._max_put = conf["rgw_max_put_size"]
        self.striper = StripedIoCtx(
            ioctx, Layout(stripe_unit=CHUNK, stripe_count=1,
                          object_size=CHUNK))

    # -- buckets (reference RGWRados::create_bucket) -------------------
    # The directory is an omap on one object: per-key mutations are
    # atomic at the OSD, so concurrent bucket create/delete cannot
    # lose each other's updates (a read-modify-write JSON blob could).
    def list_buckets(self) -> List[dict]:
        try:
            omap = self.ioctx.omap_get(BUCKETS_DIR)
        except RadosError:
            return []
        return [json.loads(v.decode())
                for _, v in sorted(omap.items())]

    def create_bucket(self, bucket: str, owner: str = "",
                      acl: str = "private") -> None:
        if not bucket or "/" in bucket or "." == bucket[0]:
            raise RGWError(400, "InvalidBucketName", bucket)
        if acl not in CANNED_ACLS:
            raise RGWError(400, "InvalidArgument", acl)
        try:
            if bucket in self.ioctx.omap_get(BUCKETS_DIR):
                raise RGWError(409, "BucketAlreadyExists", bucket)
        except RadosError:
            pass
        meta = {"name": bucket, "created": time.time(),
                "owner": owner, "acl": acl, "versioning": "off",
                "lifecycle": []}
        self.ioctx.omap_set(BUCKETS_DIR,
                            {bucket: json.dumps(meta).encode()})
        self.ioctx.create(_index_oid(bucket))

    def _bucket_meta(self, bucket: str) -> dict:
        try:
            raw = self.ioctx.omap_get_by_key(BUCKETS_DIR, bucket)
        except RadosError:
            raw = None
        if raw is None:
            raise RGWError(404, "NoSuchBucket", bucket)
        return json.loads(raw.decode())

    def _set_bucket_meta(self, bucket: str, meta: dict) -> None:
        self.ioctx.omap_set(BUCKETS_DIR,
                            {bucket: json.dumps(meta).encode()})

    def _check_bucket(self, bucket: str) -> None:
        self._bucket_meta(bucket)

    # -- versioning config (reference RGWSetBucketVersioning,
    # rgw_op.cc:2661) ---------------------------------------------------
    def put_bucket_versioning(self, bucket: str, state: str) -> None:
        if state not in ("Enabled", "Suspended"):
            raise RGWError(400, "IllegalVersioningConfiguration",
                           state)
        meta = self._bucket_meta(bucket)
        meta["versioning"] = ("enabled" if state == "Enabled"
                              else "suspended")
        self._set_bucket_meta(bucket, meta)

    def get_bucket_versioning(self, bucket: str) -> str:
        v = self._bucket_meta(bucket).get("versioning", "off")
        return {"enabled": "Enabled", "suspended": "Suspended",
                "off": ""}[v]

    # -- ACLs (canned; reference rgw_acl_s3.cc RGWAccessControlPolicy
    # _S3 + rgw_op.cc verify_bucket/object_permission) ------------------
    def get_bucket_acl(self, bucket: str) -> dict:
        meta = self._bucket_meta(bucket)
        return {"owner": meta.get("owner", ""),
                "acl": meta.get("acl", "private")}

    def put_bucket_acl(self, bucket: str, acl: str) -> None:
        if acl not in CANNED_ACLS:
            raise RGWError(400, "InvalidArgument", acl)
        meta = self._bucket_meta(bucket)
        meta["acl"] = acl
        self._set_bucket_meta(bucket, meta)

    def get_object_acl(self, bucket: str, key: str) -> dict:
        head = self.head_object(bucket, key)
        return {"owner": head.get("owner", ""),
                "acl": head.get("acl", "private")}

    def put_object_acl(self, bucket: str, key: str, acl: str) -> None:
        if acl not in CANNED_ACLS:
            raise RGWError(400, "InvalidArgument", acl)
        self._check_bucket(bucket)
        idx = _index_oid(bucket)
        raw = self.ioctx.omap_get_by_key(idx, key)
        if raw is None:
            raise RGWError(404, "NoSuchKey", key)
        entry = json.loads(raw.decode())
        entry["acl"] = acl
        rows = {key: json.dumps(entry).encode()}
        vid = entry.get("version_id")
        # keep the current version ROW in sync too — including a
        # materialized "null" version (suspended-era write): _entry
        # serves versionId=null from that row, so a stale copy would
        # enforce the old ACL for versioned reads of the same object
        if vid and self.ioctx.omap_get_by_key(
                idx, _vkey(key, vid)) is not None:
            rows[_vkey(key, vid)] = rows[key]
        self.ioctx.omap_set(idx, rows)

    def check_access(self, identity: Optional[str], op: str,
                     bucket: str, key: str = "",
                     head: Optional[dict] = None) -> None:
        """Enforce the canned ACL for ``identity`` (None = anonymous;
        an empty-owner bucket predates auth and stays open, matching
        the reference's anonymous dev mode).  op is 'read', 'write'
        or 'acl' (ACL reads/writes are owner-only, reference
        verify_bucket_owner_or_policy).  ``head``: a pre-fetched
        object head — the GET/HEAD hot path fetches the entry once
        and threads it through here and get_object instead of paying
        three bucket-meta + two index-row reads per request."""
        meta = self._bucket_meta(bucket)
        owner = meta.get("owner", "")
        acl = meta.get("acl", "private")
        if key and op == "read":
            # object ACLs govern READS only; writes/deletes are
            # bucket-WRITE-ACL territory (S3: DeleteObject/PutObject
            # permission comes from the bucket, GetObject from the
            # object)
            if head is None:
                try:
                    head = self.head_object(bucket, key)
                except RGWError:
                    pass             # no object yet: bucket ACL rules
            if head is not None:
                owner = head.get("owner", owner)
                acl = head.get("acl", acl)
        if not owner or identity == owner:
            return
        if op == "read" and acl in ("public-read",
                                    "public-read-write"):
            return
        if op == "read" and acl == "authenticated-read" \
                and identity is not None:
            return
        if op == "write" and acl == "public-read-write":
            return
        raise RGWError(403, "AccessDenied", f"{op} {bucket}/{key}")

    def delete_bucket(self, bucket: str) -> None:
        self._check_bucket(bucket)
        if self.ioctx.omap_get(_index_oid(bucket)):
            raise RGWError(409, "BucketNotEmpty", bucket)
        if self.list_multipart_uploads(bucket):
            # S3: in-progress uploads must be aborted first; deleting
            # around them would orphan part objects and resurrect the
            # uploads if the name is recreated
            raise RGWError(409, "BucketNotEmpty",
                           f"{bucket}: multipart uploads in progress")
        for oid in (_index_oid(bucket), _mp_index_oid(bucket)):
            try:
                self.ioctx.remove(oid)
            except RadosError:
                pass
        self.ioctx.omap_rm_keys(BUCKETS_DIR, [bucket])

    # -- objects (reference RGWRados::Object::Write/Read) --------------
    def put_object(self, bucket: str, key: str, data: bytes,
                   content_type: str = "binary/octet-stream",
                   meta: Optional[Dict[str, str]] = None,
                   acl: str = "private", owner: str = "") -> dict:
        bmeta = self._bucket_meta(bucket)
        if not key:
            raise RGWError(400, "InvalidArgument", "empty key")
        if "\x00" in key:
            raise RGWError(400, "InvalidArgument",
                           "NUL in key reserved for version rows")
        if len(data) > self._max_put:
            raise RGWError(400, "EntityTooLarge", key)
        if acl not in CANNED_ACLS:
            raise RGWError(400, "InvalidArgument", acl)
        versioning = bmeta.get("versioning", "off")
        idx = _index_oid(bucket)
        vid = _new_vid() if versioning == "enabled" else "null"
        etag = hashlib.md5(data).hexdigest()
        rows: Dict[str, bytes] = {}
        if versioning == "enabled":
            self._materialize_null_version(idx, bucket, key, rows)
        soid = _data_vsoid(bucket, key, vid)
        self.striper.write(soid, data)
        # shrink past the new end: overwriting a larger object must
        # not serve the previous object's tail
        self.striper.truncate(soid, len(data))
        # index entry AFTER data (reference prepare/complete index
        # transaction: a failed put must not list)
        entry = {"size": len(data), "etag": etag,
                 "mtime": time.time(), "content_type": content_type,
                 "meta": meta or {}, "version_id": vid,
                 "acl": acl, "owner": owner or bmeta.get("owner", "")}
        enc = json.dumps(entry).encode()
        rows[key] = enc
        if versioning != "off":
            # suspended PUTs REPLACE the null version row (S3: a
            # suspended bucket writes null versions); enabled PUTs add
            # a fresh version row
            rows[_vkey(key, vid)] = enc
        self.ioctx.omap_set(idx, rows)
        self._datalog(bucket, key, "put")
        return entry

    def _datalog(self, bucket: str, key: str, op: str) -> None:
        """Append one change record (reference bucket index log).
        The timestamp key keeps entries ordered; a random suffix
        keeps concurrent writers from colliding — sync copies the
        CURRENT state of each named key, so ordering within the same
        instant is immaterial."""
        import secrets as _secrets
        row = f"{time.time_ns():020d}.{_secrets.token_hex(4)}"
        try:
            self.ioctx.omap_set(_datalog_oid(bucket), {
                row: json.dumps({"key": key, "op": op}).encode()})
        except RadosError:
            pass                     # log loss degrades to full sync

    def _materialize_null_version(self, idx: str, bucket: str,
                                  key: str, rows: dict) -> None:
        """An object written before versioning was enabled is the
        'null' version: give it its version row the first time a
        versioned write lands on its key, so it survives as a
        noncurrent version instead of being silently overwritten
        (reference rgw null-instance handling)."""
        try:
            cur = self.ioctx.omap_get_by_key(idx, key)
        except RadosError:
            cur = None
        if cur is None:
            return
        entry = json.loads(cur.decode())
        if entry.get("version_id", "null") == "null":
            entry["version_id"] = "null"
            rows[_vkey(key, "null")] = json.dumps(entry).encode()

    def _entry(self, bucket: str, key: str,
               version_id: Optional[str] = None) -> dict:
        self._check_bucket(bucket)
        idx = _index_oid(bucket)
        row = key if version_id is None else _vkey(key, version_id)
        try:
            raw = self.ioctx.omap_get_by_key(idx, row)
        except RadosError:
            raw = None
        if raw is None and version_id == "null":
            # null version of a never-materialized key = current row
            # (if itself null)
            try:
                raw = self.ioctx.omap_get_by_key(idx, key)
            except RadosError:
                raw = None
            if raw is not None:
                e = json.loads(raw.decode())
                if e.get("version_id", "null") != "null":
                    raw = None
        if raw is None:
            raise RGWError(404, "NoSuchKey" if version_id is None
                           else "NoSuchVersion", key)
        return json.loads(raw.decode())

    def head_object(self, bucket: str, key: str,
                    version_id: Optional[str] = None) -> dict:
        entry = self._entry(bucket, key, version_id)
        if version_id is None and entry.get("delete_marker"):
            raise RGWError(404, "NoSuchKey", key)
        return entry

    def get_object(self, bucket: str, key: str,
                   rng: Optional[Tuple[int, int]] = None,
                   version_id: Optional[str] = None,
                   head: Optional[dict] = None
                   ) -> Tuple[dict, bytes]:
        if head is None:
            head = self.head_object(bucket, key, version_id)
        if head.get("delete_marker"):
            raise RGWError(405, "MethodNotAllowed",
                           f"{key} version {version_id} is a delete "
                           f"marker")
        soid = _data_vsoid(bucket, key,
                           head.get("version_id", "null"))
        if head["size"] == 0:
            return head, b""
        if rng is None:
            return head, self.striper.read(soid)
        start, end = rng
        end = min(end, head["size"] - 1)
        if start > end:
            raise RGWError(416, "InvalidRange", key)
        return head, self.striper.read(soid, end - start + 1, start)

    def delete_object(self, bucket: str, key: str,
                      version_id: Optional[str] = None
                      ) -> Optional[dict]:
        """S3 DELETE semantics.  Unversioned bucket: remove key.
        Versioning enabled, no version_id: write a DELETE MARKER
        (reference rgw_op.cc RGWDeleteObj versioned path).  With
        version_id: permanently remove that version; the newest
        remaining version becomes current."""
        bmeta = self._bucket_meta(bucket)
        idx = _index_oid(bucket)
        versioning = bmeta.get("versioning", "off")
        if version_id is not None:
            return self._delete_version(bucket, idx, key, version_id)
        if versioning == "off":
            if self.ioctx.omap_get_by_key(idx, key) is None:
                raise RGWError(404, "NoSuchKey", key)
            try:
                self.striper.remove(_data_soid(bucket, key))
            except RadosError:
                pass
            self.ioctx.omap_rm_keys(idx, [key])
            self._datalog(bucket, key, "del")
            return None
        # versioned (enabled or suspended): delete marker.  Suspended
        # buckets write it as the null version, removing any existing
        # null version's data (S3 suspended-delete semantics).
        rows: Dict[str, bytes] = {}
        vid = _new_vid() if versioning == "enabled" else "null"
        if versioning == "enabled":
            self._materialize_null_version(idx, bucket, key, rows)
        else:
            try:
                self.striper.remove(_data_soid(bucket, key))
            except RadosError:
                pass
        marker = {"delete_marker": True, "version_id": vid,
                  "mtime": time.time(), "size": 0, "etag": "",
                  "content_type": "", "meta": {},
                  "owner": bmeta.get("owner", ""), "acl": "private"}
        enc = json.dumps(marker).encode()
        rows[key] = enc
        rows[_vkey(key, vid)] = enc
        self.ioctx.omap_set(idx, rows)
        self._datalog(bucket, key, "del")
        return marker

    def _delete_version(self, bucket: str, idx: str, key: str,
                        vid: str) -> Optional[dict]:
        entry = self._entry(bucket, key, vid)
        if not entry.get("delete_marker"):
            try:
                self.striper.remove(_data_vsoid(bucket, key, vid))
            except RadosError:
                pass
        rm = [_vkey(key, vid)]
        # was this version current?  promote the newest survivor
        try:
            cur_raw = self.ioctx.omap_get_by_key(idx, key)
        except RadosError:
            cur_raw = None
        cur = json.loads(cur_raw.decode()) if cur_raw else None
        if cur is not None and cur.get("version_id",
                                       "null") == vid:
            survivors = self._version_rows(idx, key)
            survivors.pop(vid, None)
            if survivors:
                # promote by WRITE TIME, not lexical vid: the literal
                # "null" (suspended-era writes) sorts after every hex
                # vid, so a lexical pick would serve an old enabled-era
                # version over a newer null one
                newest = max(survivors.values(),
                             key=lambda e: e.get("mtime", 0.0))
                self.ioctx.omap_set(
                    idx, {key: json.dumps(newest).encode()})
            else:
                rm.append(key)
        self.ioctx.omap_rm_keys(idx, rm)
        # version deletes can change the key's CURRENT state
        # (survivor promotion / key removal): the peer zone must
        # re-converge it
        self._datalog(bucket, key, "del")
        return entry

    def _version_rows(self, idx: str, key: str,
                      omap: Optional[dict] = None) -> Dict[str, dict]:
        """vid -> entry for every version row of one key.  Pass a
        pre-fetched ``omap`` when iterating many keys — re-fetching
        the whole bucket index per key makes sweeps O(keys x
        bucket-size)."""
        if omap is None:
            try:
                omap = self.ioctx.omap_get(idx)
            except RadosError:
                return {}
        pre = key + "\x00"
        return {k[len(pre):]: json.loads(v.decode())
                for k, v in omap.items() if k.startswith(pre)}

    def list_object_versions(self, bucket: str, prefix: str = "",
                             key_marker: str = "",
                             max_keys: Optional[int] = None) -> dict:
        """S3 ListObjectVersions: every version row newest-first per
        key; keys never versioned surface their current row as the
        null version (reference RGWListBucketVersions)."""
        if max_keys is None:
            max_keys = self._list_max
        self._check_bucket(bucket)
        omap = self.ioctx.omap_get(_index_oid(bucket))
        versions: List[dict] = []
        truncated = False

        def emit(group: List[dict]) -> bool:
            """Append one key's versions newest-first; -> True when
            the page filled.  Ordering is by recorded mtime with the
            latest pinned on top: the omap's inverted-timestamp vids
            already sort newest-first, but a materialized "null"
            version (written in a suspended era) sorts
            lexicographically LAST however old or new it is — S3
            clients take the first entry as the newest.  Truncation
            is WHOLE-KEY: continuation is by key-marker, so a key cut
            mid-group could never finish listing — the partial key
            moves entirely to the next page (unless it alone exceeds
            the page, which then serves it oversized rather than
            loop forever)."""
            nonlocal truncated
            if len(versions) + len(group) > max_keys and versions:
                truncated = True
                return True
            group.sort(key=lambda e: (not e.get("is_latest"),
                                      -e.get("mtime", 0)))
            versions.extend(group)
            return len(versions) >= max_keys

        # rows of one key are contiguous in the sorted omap (keys
        # cannot contain NUL), so groups stream and the scan stops at
        # the page boundary instead of json-decoding the whole bucket
        # (same paging principle as list_objects)
        group: List[dict] = []
        group_key = None
        for row in sorted(omap):
            base = row.split("\x00", 1)[0]
            if not base.startswith(prefix) or base <= key_marker:
                continue
            if base != group_key:
                if group and emit(group):
                    # emit said stop AND a further key's row is in
                    # hand — whether the group was deferred or the
                    # page filled exactly, more data exists
                    truncated = True
                    break
                group, group_key = [], base
            if "\x00" not in row:
                ent = json.loads(omap[row].decode())
                if _vkey(base, ent.get("version_id",
                                       "null")) in omap:
                    continue         # materialized: row covers it
                ent.setdefault("version_id", "null")
                ent["is_latest"] = True
            else:
                ent = json.loads(omap[row].decode())
                cur = omap.get(base)
                cur_vid = (json.loads(cur.decode())
                           .get("version_id", "null")
                           if cur else None)
                ent["is_latest"] = ent.get("version_id") == cur_vid
            ent["key"] = base
            group.append(ent)
        if group and not truncated:
            emit(group)
        return {"bucket": bucket, "prefix": prefix,
                "versions": versions, "is_truncated": truncated}

    # -- lifecycle (reference rgw_lc.cc RGWLC::process + bucket_lc_
    # process; rules stored on the bucket like RGWLifecycleConfiguration
    # in bucket attrs) --------------------------------------------------
    def put_bucket_lifecycle(self, bucket: str,
                             rules: List[dict]) -> None:
        """rules: [{id, prefix, status, days, noncurrent_days,
        expired_delete_marker}] — the S3 subset the reference's LC
        worker applies most: current-object expiration, noncurrent
        version expiration, orphaned delete-marker cleanup."""
        clean = []
        for r in rules:
            if r.get("status", "Enabled") not in ("Enabled",
                                                  "Disabled"):
                raise RGWError(400, "MalformedXML",
                               str(r.get("status")))
            days = r.get("days")
            nc = r.get("noncurrent_days")
            if days is None and nc is None and \
                    not r.get("expired_delete_marker"):
                raise RGWError(400, "MalformedXML",
                               "rule without any action")
            for v in (days, nc):
                if v is not None and (not isinstance(v, int)
                                      or v < 1):
                    raise RGWError(400, "InvalidArgument", str(v))
            clean.append({"id": r.get("id", f"rule-{len(clean)}"),
                          "prefix": r.get("prefix", ""),
                          "status": r.get("status", "Enabled"),
                          "days": days, "noncurrent_days": nc,
                          "expired_delete_marker":
                              bool(r.get("expired_delete_marker"))})
        meta = self._bucket_meta(bucket)
        meta["lifecycle"] = clean
        self._set_bucket_meta(bucket, meta)

    def get_bucket_lifecycle(self, bucket: str) -> List[dict]:
        return self._bucket_meta(bucket).get("lifecycle", [])

    def delete_bucket_lifecycle(self, bucket: str) -> None:
        meta = self._bucket_meta(bucket)
        meta["lifecycle"] = []
        self._set_bucket_meta(bucket, meta)

    def lc_process(self, now: Optional[float] = None) -> dict:
        """One lifecycle pass over every bucket (reference
        RGWLC::process worker): expire current objects past
        ``days`` (versioned buckets get a delete marker, unversioned
        delete outright — S3 expiration semantics), permanently
        remove noncurrent versions past ``noncurrent_days``, and
        drop delete markers with no remaining versions when
        ``expired_delete_marker`` asks.  Returns action counts."""
        now = time.time() if now is None else now
        stats = {"expired": 0, "noncurrent_removed": 0,
                 "markers_removed": 0}
        for bmeta in self.list_buckets():
            bucket = bmeta["name"]
            rules = [r for r in bmeta.get("lifecycle", [])
                     if r.get("status") == "Enabled"]
            if not rules:
                continue
            versioned = bmeta.get("versioning", "off") != "off"
            idx = _index_oid(bucket)
            try:
                omap = self.ioctx.omap_get(idx)
            except RadosError:
                continue
            # rows already acted on this pass: the omap snapshot is
            # taken once per bucket, so without this an overlapping
            # later rule re-sees the stale pre-action entry and
            # double-expires (one junk delete marker per extra rule)
            acted: set = set()
            for rule in rules:
                pre = rule.get("prefix", "")
                days = rule.get("days")
                nc_days = rule.get("noncurrent_days")
                for row in sorted(omap):
                    base = row.split("\x00", 1)[0]
                    if not base.startswith(pre) or row in acted:
                        continue
                    ent = json.loads(omap[row].decode())
                    if "\x00" not in row:
                        cur_expired = (
                            days is not None
                            and not ent.get("delete_marker")
                            and ent["mtime"] + days * 86400 <= now)
                        if cur_expired:
                            try:
                                self.delete_object(bucket, base)
                                stats["expired"] += 1
                                acted.add(row)
                            except RGWError:
                                pass
                        continue
                    # version row: noncurrent expiration
                    vid = row.split("\x00", 1)[1]
                    cur_raw = omap.get(base)
                    cur_vid = (json.loads(cur_raw.decode())
                               .get("version_id", "null")
                               if cur_raw else None)
                    if vid == cur_vid:
                        continue     # current: only `days` applies
                    if nc_days is not None and \
                            ent["mtime"] + nc_days * 86400 <= now:
                        try:
                            self._delete_version(bucket, idx, base,
                                                 vid)
                            stats["noncurrent_removed"] += 1
                            acted.add(row)
                        except RGWError:
                            pass
                if rule.get("expired_delete_marker") and versioned:
                    # a delete marker whose key has no other versions
                    # serves nothing: S3's ExpiredObjectDeleteMarker
                    fresh = self.ioctx.omap_get(idx)
                    for row in sorted(fresh):
                        if "\x00" in row:
                            continue
                        if not row.startswith(pre):
                            continue
                        ent = json.loads(fresh[row].decode())
                        if not ent.get("delete_marker"):
                            continue
                        others = [v for v in
                                  self._version_rows(idx, row,
                                                     omap=fresh)
                                  if v != ent.get("version_id")]
                        if not others:
                            self.ioctx.omap_rm_keys(
                                idx, [row, _vkey(
                                    row, ent["version_id"])])
                            self._datalog(bucket, row, "del")
                            stats["markers_removed"] += 1
        return stats

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", max_keys: Optional[int] = None,
                     delimiter: str = "") -> dict:
        """S3 ListObjects semantics: sorted keys, prefix filter,
        marker resume, delimiter common-prefix rollup (reference
        cls_rgw bucket listing + RGWListBucket).  Version rows and
        delete-marker currents never list (S3 shows only latest
        non-deleted objects here)."""
        if max_keys is None:
            max_keys = self._list_max    # reference rgw_max_listing_results
        self._check_bucket(bucket)
        omap = self.ioctx.omap_get(_index_oid(bucket))
        # string-only prefilter; entries json-decode lazily inside the
        # paged loop so a huge bucket doesn't parse every row per page
        keys = sorted(k for k in omap
                      if k.startswith(prefix) and k > marker
                      and "\x00" not in k)
        contents: List[dict] = []
        common: List[str] = []
        truncated = False
        for k in keys:
            if len(contents) + len(common) >= max_keys:
                truncated = True
                break
            if delimiter:
                rest = k[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter, 1)[0] \
                        + delimiter
                    if cp not in common:
                        common.append(cp)
                    continue
            entry = json.loads(omap[k].decode())
            if entry.get("delete_marker"):
                continue             # S3 hides marker currents
            contents.append({"key": k, "size": entry["size"],
                             "etag": entry["etag"],
                             "mtime": entry["mtime"]})
        return {"bucket": bucket, "prefix": prefix, "marker": marker,
                "contents": contents, "common_prefixes": common,
                "is_truncated": truncated}
