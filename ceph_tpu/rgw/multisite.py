"""RGW multisite: zone-to-zone object sync.

Python-native equivalent of the reference's multisite machinery
(reference ``src/rgw/rgw_data_sync.cc`` + rgw_sync.cc metadata sync),
reduced to its operational core: a secondary-zone agent PULLS from
the master zone —

* **metadata sync**: buckets (with their ACL/versioning/lifecycle
  configuration) appear at the secondary as they appear at the
  master (reference metadata sync replicating bucket entrypoints);
* **full sync** on first contact per bucket: every current object
  copies over (reference RGWDataSyncCR full-sync state);
* **incremental sync** afterwards: the per-bucket datalog written by
  the gateway at each mutation (gateway._datalog — the reference's
  bucket index log) names the keys that changed; the agent re-reads
  each key's CURRENT state from the master and converges the
  secondary to it (copy or delete).  Syncing current state keyed by
  name makes replay idempotent and order-tolerant, exactly the
  property the reference's sync relies on;
* consumed datalog rows are trimmed (reference datalog trim once
  every zone has them).

Like the reference, replication is asynchronous and eventually
consistent; versioned buckets converge on the CURRENT version (the
noncurrent history is site-local — the reference syncs olh state
with more machinery than this framework carries).
"""
from __future__ import annotations

import json
from typing import Dict

from ..client.rados import RadosError
from .gateway import RGWError, RGWService, _datalog_oid


def _sync_marker_oid(bucket: str) -> str:
    return f"rgw.sync.{len(bucket)}.{bucket}"


class ZoneSyncAgent:
    """Pull-replicates the master zone's buckets into this zone
    (reference RGWDataSyncProcessorThread, drivable step-wise)."""

    def __init__(self, master: RGWService, local: RGWService):
        self.master = master
        self.local = local

    # -- markers -------------------------------------------------------
    def _marker(self, bucket: str) -> str:
        try:
            return json.loads(self.local.ioctx.read(
                _sync_marker_oid(bucket)).decode()).get("marker", "")
        except (RadosError, ValueError):
            return ""

    def _set_marker(self, bucket: str, marker: str) -> None:
        self.local.ioctx.write_full(
            _sync_marker_oid(bucket),
            json.dumps({"marker": marker}).encode())

    # -- one key -------------------------------------------------------
    def _converge_key(self, bucket: str, key: str) -> str:
        """Make the local CURRENT state of ``key`` match the
        master's; -> "copied" | "deleted" | "noop"."""
        try:
            head, data = self.master.get_object(bucket, key)
        except RGWError:
            head = None
        try:
            local_head = self.local.head_object(bucket, key)
        except RGWError:
            local_head = None
        if head is None:
            if local_head is None:
                return "noop"
            try:
                self.local.delete_object(bucket, key)
            except RGWError:
                pass
            return "deleted"
        if local_head is not None and \
                local_head.get("etag") == head.get("etag"):
            return "noop"
        self.local.put_object(
            bucket, key, data,
            content_type=head.get("content_type",
                                  "binary/octet-stream"),
            meta=head.get("meta") or {},
            acl=head.get("acl", "private"),
            owner=head.get("owner", ""))
        return "copied"

    # -- one bucket ----------------------------------------------------
    def sync_bucket(self, bucket: str, bmeta: Dict) -> Dict:
        stats = {"copied": 0, "deleted": 0, "full": False}
        # metadata sync: bucket + its configuration converge first
        try:
            self.local.create_bucket(bucket,
                                     owner=bmeta.get("owner", ""),
                                     acl=bmeta.get("acl", "private"))
        except RGWError:
            pass                         # exists: converge config
        lmeta = self.local._bucket_meta(bucket)
        changed = False
        for fld in ("acl", "owner", "versioning", "lifecycle"):
            if fld in bmeta and lmeta.get(fld) != bmeta[fld]:
                lmeta[fld] = bmeta[fld]
                changed = True
        if changed:
            self.local._set_bucket_meta(bucket, lmeta)
        marker = self._marker(bucket)
        if not marker:
            # full sync: walk the master's current listing; the
            # datalog position is noted FIRST so mutations racing the
            # walk replay incrementally next pass
            stats["full"] = True
            log = self._datalog_rows(bucket)
            top = max(log, default="")
            listing = self.master.list_objects(bucket,
                                               max_keys=1 << 30)
            for obj in listing["contents"]:
                if self._converge_key(bucket, obj["key"]) == "copied":
                    stats["copied"] += 1
            self._set_marker(bucket, top or "0")
            return stats
        log = self._datalog_rows(bucket)
        done = marker
        keys = []
        seen = set()
        for row in sorted(log):
            if row <= marker:
                continue
            k = log[row]["key"]
            if k not in seen:
                seen.add(k)
                keys.append(k)
            done = max(done, row)
        for k in keys:
            verdict = self._converge_key(bucket, k)
            if verdict in ("copied", "deleted"):
                stats[verdict] += 1
        if done != marker:
            self._set_marker(bucket, done)
            # trim consumed rows at the master (reference datalog
            # trim; single-peer zonegroup, so consumed = trimmable)
            try:
                self.master.ioctx.omap_rm_keys(
                    _datalog_oid(bucket),
                    [r for r in log if r <= done])
            except RadosError:
                pass
        return stats

    def _datalog_rows(self, bucket: str) -> Dict[str, dict]:
        try:
            omap = self.master.ioctx.omap_get(_datalog_oid(bucket))
        except RadosError:
            return {}
        out = {}
        for row, raw in omap.items():
            try:
                out[row] = json.loads(raw.decode())
            except ValueError:
                continue
        return out

    # -- the zone ------------------------------------------------------
    def sync_once(self) -> Dict[str, Dict]:
        out = {}
        for bmeta in self.master.list_buckets():
            try:
                out[bmeta["name"]] = self.sync_bucket(bmeta["name"],
                                                      bmeta)
            except (RGWError, RadosError) as e:
                out[bmeta["name"]] = {"error": str(e)}
        return out
