"""RGW HTTP frontend: S3 path-style REST over the gateway core.

Python-native equivalent of the reference's beast/civetweb frontend +
REST dispatch (reference ``src/rgw/rgw_rest_s3.cc``): path-style
routes (``/bucket``, ``/bucket/key``), ListAllMyBuckets /
ListObjects XML, ETag/Content-Type headers, Range reads, multipart
upload (initiate/part/complete/abort/list — reference rgw_multi.cc),
S3-style XML error bodies, and optional AWS SigV4 authentication
(``auth_enabled``; anonymous mode remains for dev parity with the
reference's anonymous access).  Single-site.
"""
from __future__ import annotations

import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from xml.sax.saxutils import escape

from .gateway import RGWError, RGWService


def _iso(ts: float) -> str:
    import datetime
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.000Z")


def _parse_lc_xml(body: bytes) -> list:
    """Minimal LifecycleConfiguration parser (the S3 subset
    put_bucket_lifecycle accepts; reference RGWLifecycleConfiguration
    ::decode_xml)."""
    import re as _re
    text = body.decode("utf-8", "replace")
    rules = []
    for rm in _re.finditer(r"<Rule>(.*?)</Rule>", text, _re.S):
        blk = rm.group(1)

        def tag(name, default=None):
            m = _re.search(rf"<{name}>\s*([^<]*?)\s*</{name}>", blk)
            return m.group(1) if m else default

        rule = {"id": tag("ID", f"rule-{len(rules)}"),
                "prefix": tag("Prefix", ""),
                "status": tag("Status", "Enabled")}
        days = tag("Days")
        if days is not None:
            try:
                rule["days"] = int(days)
            except ValueError:
                raise RGWError(400, "InvalidArgument", days)
        nc = tag("NoncurrentDays")
        if nc is not None:
            try:
                rule["noncurrent_days"] = int(nc)
            except ValueError:
                raise RGWError(400, "InvalidArgument", nc)
        if tag("ExpiredObjectDeleteMarker", "").lower() == "true":
            rule["expired_delete_marker"] = True
        rules.append(rule)
    if not rules:
        raise RGWError(400, "MalformedXML", "no rules")
    return rules


class RGWServer:
    """HTTP server hosting one RGWService (reference RGWFrontend)."""

    def __init__(self, ioctx, addr: Tuple[str, int] = ("127.0.0.1", 0),
                 auth_enabled: bool = False):
        from .auth import SigV4Verifier, UserStore
        from .swift import SwiftAdapter
        self.service = RGWService(ioctx)
        self.users = UserStore(ioctx)
        self.verifier = SigV4Verifier(self.users)
        self.auth_enabled = auth_enabled
        # the Swift dialect shares the gateway core (reference: one
        # radosgw, two REST APIs over the same buckets)
        self.swift = SwiftAdapter(self.service, self.users)
        svc = self.service
        gw = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            # ---------------------------------------------------- util
            def _split(self) -> Tuple[str, str, dict]:
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.lstrip("/").split("/", 1)
                bucket = urllib.parse.unquote(parts[0])
                key = urllib.parse.unquote(parts[1]) \
                    if len(parts) > 1 else ""
                q = {k: v[0] for k, v in urllib.parse.parse_qs(
                    parsed.query, keep_blank_values=True).items()}
                return bucket, key, q

            def _send(self, status: int, body: bytes = b"",
                      ctype: str = "application/xml",
                      headers: Optional[dict] = None) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _error(self, e: RGWError) -> None:
                body = (f"<?xml version='1.0'?><Error><Code>{e.code}"
                        f"</Code><Message>{escape(str(e))}</Message>"
                        f"</Error>").encode()
                self._send(e.status, body)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n) if n else b""

            def _auth(self, body: bytes) -> Optional[str]:
                """SigV4 check when enabled (reference rgw::auth);
                -> the authenticated uid, or None for anonymous
                (requests without Authorization are ANONYMOUS, not
                rejected — the canned ACLs decide what anonymous may
                touch, reference rgw handles anonymous the same
                way)."""
                if not gw.auth_enabled:
                    return None
                if "Authorization" not in self.headers:
                    return None
                parsed = urllib.parse.urlparse(self.path)
                user = gw.verifier.verify(
                    self.command, parsed.path, parsed.query,
                    dict(self.headers.items()), body)
                return user["uid"]

            # --------------------------------------------------- verbs
            def do_GET(self):          # noqa: N802
                if gw.swift.maybe_handle(self, "GET"):
                    return
                bucket, key, q = self._split()
                try:
                    ident = self._auth(b"")
                    if not bucket:
                        # S3 ListBuckets requires authentication and
                        # shows only the caller's buckets (anonymous
                        # enumeration of every bucket name would leak)
                        if gw.auth_enabled and ident is None:
                            raise RGWError(403, "AccessDenied",
                                           "anonymous ListBuckets")
                        self._list_buckets(ident)
                        return
                    if "acl" in q:
                        svc.check_access(ident, "acl", bucket, key)
                        self._get_acl(bucket, key)
                        return
                    if not key and ("versioning" in q
                                    or "lifecycle" in q):
                        # bucket CONFIG reads are owner-only (S3
                        # gates GetBucketVersioning/GetLifecycle on
                        # bucket-owner permissions, not READ ACL)
                        svc.check_access(ident, "acl", bucket)
                    else:
                        head = None
                        if key and "uploadId" not in q:
                            # fetch the entry ONCE for the object-GET
                            # hot path; check_access and get_object
                            # both reuse it
                            try:
                                head = svc.head_object(
                                    bucket, key, q.get("versionId"))
                            except RGWError:
                                head = None
                        svc.check_access(ident, "read", bucket, key,
                                         head=head)
                    if not key and "versioning" in q:
                        state = svc.get_bucket_versioning(bucket)
                        inner = (f"<Status>{state}</Status>"
                                 if state else "")
                        self._send(200, (
                            f"<?xml version='1.0'?>"
                            f"<VersioningConfiguration>{inner}"
                            f"</VersioningConfiguration>").encode())
                    elif not key and "lifecycle" in q:
                        self._get_lifecycle(bucket)
                    elif not key and "versions" in q:
                        self._list_versions(bucket, q)
                    elif not key and "uploads" in q:
                        self._list_uploads(bucket)
                    elif not key:
                        self._list_objects(bucket, q)
                    elif "uploadId" in q:
                        self._list_parts(bucket, q["uploadId"])
                    else:
                        self._get_object(bucket, key,
                                         q.get("versionId"),
                                         head=head)
                except RGWError as e:
                    self._error(e)

            def _get_acl(self, bucket, key):
                acl = (svc.get_object_acl(bucket, key) if key
                       else svc.get_bucket_acl(bucket))
                xml = (f"<?xml version='1.0'?>"
                       f"<AccessControlPolicy><Owner><ID>"
                       f"{escape(acl['owner'])}</ID></Owner>"
                       f"<Canned>{acl['acl']}</Canned>"
                       f"</AccessControlPolicy>")
                self._send(200, xml.encode())

            def _get_lifecycle(self, bucket):
                rules = svc.get_bucket_lifecycle(bucket)
                if not rules:
                    raise RGWError(404,
                                   "NoSuchLifecycleConfiguration",
                                   bucket)
                rows = ""
                for r in rules:
                    exp = ""
                    if r.get("days"):
                        exp += (f"<Expiration><Days>{r['days']}"
                                f"</Days></Expiration>")
                    if r.get("expired_delete_marker"):
                        exp += ("<Expiration>"
                                "<ExpiredObjectDeleteMarker>true"
                                "</ExpiredObjectDeleteMarker>"
                                "</Expiration>")
                    if r.get("noncurrent_days"):
                        exp += (f"<NoncurrentVersionExpiration>"
                                f"<NoncurrentDays>"
                                f"{r['noncurrent_days']}"
                                f"</NoncurrentDays>"
                                f"</NoncurrentVersionExpiration>")
                    rows += (f"<Rule><ID>{escape(r['id'])}</ID>"
                             f"<Prefix>{escape(r['prefix'])}"
                             f"</Prefix><Status>{r['status']}"
                             f"</Status>{exp}</Rule>")
                self._send(200, (
                    f"<?xml version='1.0'?>"
                    f"<LifecycleConfiguration>{rows}"
                    f"</LifecycleConfiguration>").encode())

            def _list_versions(self, bucket, q):
                try:
                    max_keys = int(q.get("max-keys", "0")) or None
                except ValueError:
                    raise RGWError(400, "InvalidArgument",
                                   q.get("max-keys", ""))
                res = svc.list_object_versions(
                    bucket, prefix=q.get("prefix", ""),
                    key_marker=q.get("key-marker", ""),
                    max_keys=max_keys)
                rows = ""
                for v in res["versions"]:
                    tag = ("DeleteMarker" if v.get("delete_marker")
                           else "Version")
                    extra = ("" if v.get("delete_marker") else
                             f"<ETag>\"{v['etag']}\"</ETag>"
                             f"<Size>{v['size']}</Size>")
                    rows += (
                        f"<{tag}><Key>{escape(v['key'])}</Key>"
                        f"<VersionId>{v['version_id']}</VersionId>"
                        f"<IsLatest>"
                        f"{str(v['is_latest']).lower()}</IsLatest>"
                        f"<LastModified>{_iso(v['mtime'])}"
                        f"</LastModified>{extra}</{tag}>")
                # paging contract (S3 ListObjectVersions): truncation
                # is explicit, and NextKeyMarker is the last key the
                # page covered so the client can continue
                trunc = res.get("is_truncated", False)
                marker = ""
                if trunc and res["versions"]:
                    marker = (f"<NextKeyMarker>"
                              f"{escape(res['versions'][-1]['key'])}"
                              f"</NextKeyMarker>")
                self._send(200, (
                    f"<?xml version='1.0'?><ListVersionsResult>"
                    f"<Name>{escape(bucket)}</Name>"
                    f"<IsTruncated>{str(trunc).lower()}"
                    f"</IsTruncated>{marker}{rows}"
                    f"</ListVersionsResult>").encode())

            def do_POST(self):         # noqa: N802
                if gw.swift.maybe_handle(self, "POST"):
                    return
                bucket, key, q = self._split()
                body = self._body()
                try:
                    ident = self._auth(body)
                    svc.check_access(ident, "write", bucket, key)
                    if key and "uploads" in q:
                        uid = svc.initiate_multipart(
                            bucket, key,
                            content_type=self.headers.get(
                                "Content-Type",
                                "binary/octet-stream"))
                        xml = (f"<?xml version='1.0'?>"
                               f"<InitiateMultipartUploadResult>"
                               f"<Bucket>{escape(bucket)}</Bucket>"
                               f"<Key>{escape(key)}</Key>"
                               f"<UploadId>{uid}</UploadId>"
                               f"</InitiateMultipartUploadResult>")
                        self._send(200, xml.encode())
                    elif key and "uploadId" in q:
                        self._complete_upload(bucket, key,
                                              q["uploadId"], body)
                    else:
                        raise RGWError(400, "InvalidRequest",
                                       self.path)
                except RGWError as e:
                    self._error(e)

            def _complete_upload(self, bucket, key, upload_id,
                                 body: bytes):
                # CompleteMultipartUpload XML: ordered Part/
                # PartNumber/ETag rows (reference RGWCompleteMultipart)
                import re as _re
                parts = []
                try:
                    text = body.decode()
                except UnicodeDecodeError:
                    raise RGWError(400, "MalformedXML", "not utf-8")
                for m in _re.finditer(
                        r"<Part>.*?<PartNumber>(\d+)</PartNumber>"
                        r".*?<ETag>\"?([a-f0-9-]+)\"?</ETag>.*?"
                        r"</Part>", text, _re.S):
                    parts.append((int(m.group(1)), m.group(2)))
                etag = svc.complete_multipart(bucket, key, upload_id,
                                              parts)
                xml = (f"<?xml version='1.0'?>"
                       f"<CompleteMultipartUploadResult>"
                       f"<Bucket>{escape(bucket)}</Bucket>"
                       f"<Key>{escape(key)}</Key>"
                       f"<ETag>\"{etag}\"</ETag>"
                       f"</CompleteMultipartUploadResult>")
                self._send(200, xml.encode())

            def _list_uploads(self, bucket):
                rows = "".join(
                    f"<Upload><Key>{escape(u['key'])}</Key>"
                    f"<UploadId>{u['upload_id']}</UploadId>"
                    f"<Initiated>{_iso(u['started'])}</Initiated>"
                    f"</Upload>"
                    for u in svc.list_multipart_uploads(bucket))
                xml = (f"<?xml version='1.0'?>"
                       f"<ListMultipartUploadsResult>"
                       f"<Bucket>{escape(bucket)}</Bucket>{rows}"
                       f"</ListMultipartUploadsResult>")
                self._send(200, xml.encode())

            def _list_parts(self, bucket, upload_id):
                rows = "".join(
                    f"<Part><PartNumber>{p['part']}</PartNumber>"
                    f"<ETag>\"{p['etag']}\"</ETag>"
                    f"<Size>{p['size']}</Size></Part>"
                    for p in svc.list_parts(bucket, upload_id))
                xml = (f"<?xml version='1.0'?><ListPartsResult>"
                       f"{rows}</ListPartsResult>")
                self._send(200, xml.encode())

            def do_HEAD(self):         # noqa: N802
                if gw.swift.maybe_handle(self, "HEAD"):
                    return
                bucket, key, q = self._split()
                try:
                    ident = self._auth(b"")
                    try:
                        head = svc.head_object(bucket, key,
                                               q.get("versionId"))
                    except RGWError:
                        # access verdict outranks existence: an
                        # unauthorized HEAD of a missing key must
                        # stay 403, not leak 404
                        svc.check_access(ident, "read", bucket, key)
                        raise
                    svc.check_access(ident, "read", bucket, key,
                                     head=head)
                    self.send_response(200)
                    self.send_header("Content-Length",
                                     str(head["size"]))
                    self.send_header("ETag", f'"{head["etag"]}"')
                    self.send_header("Content-Type",
                                     head["content_type"])
                    vid = head.get("version_id", "null")
                    if vid != "null":
                        self.send_header("x-amz-version-id", vid)
                    self.end_headers()
                except RGWError as e:
                    self.send_response(e.status)
                    self.send_header("Content-Length", "0")
                    self.end_headers()

            def do_PUT(self):          # noqa: N802
                if gw.swift.maybe_handle(self, "PUT"):
                    return
                bucket, key, q = self._split()
                # always drain the body first: leaving it unread
                # desyncs the keep-alive connection (the next request
                # line would parse from leftover body bytes)
                body = self._body()
                try:
                    ident = self._auth(body)
                    if "acl" in q:
                        svc.check_access(ident, "acl", bucket, key)
                        canned = self.headers.get("x-amz-acl",
                                                  "private")
                        if key:
                            svc.put_object_acl(bucket, key, canned)
                        else:
                            svc.put_bucket_acl(bucket, canned)
                        self._send(200)
                        return
                    if not key and "versioning" in q:
                        svc.check_access(ident, "acl", bucket)
                        import re as _re
                        m = _re.search(r"<Status>\s*(\w+)\s*"
                                       r"</Status>", body.decode(
                                           "utf-8", "replace"))
                        if not m:
                            raise RGWError(
                                400, "IllegalVersioning"
                                     "Configuration", "no Status")
                        svc.put_bucket_versioning(bucket,
                                                  m.group(1))
                        self._send(200)
                        return
                    if not key and "lifecycle" in q:
                        svc.check_access(ident, "acl", bucket)
                        svc.put_bucket_lifecycle(
                            bucket, _parse_lc_xml(body))
                        self._send(200)
                        return
                    if key and "uploadId" in q and "partNumber" in q:
                        svc.check_access(ident, "write", bucket, key)
                        try:
                            pnum = int(q["partNumber"])
                        except ValueError:
                            raise RGWError(400, "InvalidArgument",
                                           q["partNumber"])
                        etag = svc.upload_part(
                            bucket, key, q["uploadId"], pnum, body)
                        self._send(200,
                                   headers={"ETag": f'"{etag}"'})
                    elif not key:
                        if gw.auth_enabled and ident is None:
                            # anonymous callers never create buckets
                            # (S3; anonymous access is ACL-gated reads
                            # /writes on EXISTING buckets only)
                            raise RGWError(403, "AccessDenied",
                                           "anonymous create")
                        svc.create_bucket(
                            bucket, owner=ident or "",
                            acl=self.headers.get("x-amz-acl",
                                                 "private"))
                        self._send(200)
                    else:
                        svc.check_access(ident, "write", bucket,
                                         key)
                        entry = svc.put_object(
                            bucket, key, body,
                            content_type=self.headers.get(
                                "Content-Type",
                                "binary/octet-stream"),
                            acl=self.headers.get("x-amz-acl",
                                                 "private"),
                            owner=ident or "")
                        headers = {"ETag": f'"{entry["etag"]}"'}
                        if entry["version_id"] != "null":
                            headers["x-amz-version-id"] = \
                                entry["version_id"]
                        self._send(200, headers=headers)
                except RGWError as e:
                    self._error(e)

            def do_DELETE(self):       # noqa: N802
                if gw.swift.maybe_handle(self, "DELETE"):
                    return
                bucket, key, q = self._split()
                try:
                    ident = self._auth(b"")
                    if not key and "lifecycle" in q:
                        svc.check_access(ident, "acl", bucket)
                        svc.delete_bucket_lifecycle(bucket)
                        self._send(204)
                        return
                    if not key:
                        # DeleteBucket is owner-only: bucket WRITE
                        # ACL grants object creation, never bucket
                        # destruction (S3 semantics)
                        svc.check_access(ident, "acl", bucket)
                        svc.delete_bucket(bucket)
                        self._send(204)
                        return
                    svc.check_access(ident, "write", bucket, key)
                    if "uploadId" in q:
                        svc.abort_multipart(bucket, q["uploadId"])
                        self._send(204)
                    else:
                        res = svc.delete_object(
                            bucket, key, q.get("versionId"))
                        headers = {}
                        if res is not None:
                            vid = res.get("version_id", "null")
                            if vid != "null":
                                headers["x-amz-version-id"] = vid
                            if res.get("delete_marker"):
                                headers["x-amz-delete-marker"] = \
                                    "true"
                        self._send(204, headers=headers)
                except RGWError as e:
                    self._error(e)

            # ------------------------------------------------ handlers
            def _list_buckets(self, ident=None):
                rows = "".join(
                    f"<Bucket><Name>{escape(b['name'])}</Name>"
                    f"<CreationDate>{_iso(b['created'])}"
                    f"</CreationDate></Bucket>"
                    for b in svc.list_buckets()
                    if b.get("owner", "") in ("", ident))
                body = (f"<?xml version='1.0'?>"
                        f"<ListAllMyBucketsResult><Buckets>{rows}"
                        f"</Buckets></ListAllMyBucketsResult>").encode()
                self._send(200, body)

            def _list_objects(self, bucket: str, q: dict):
                try:
                    max_keys = int(q.get("max-keys", 1000))
                except ValueError:
                    raise RGWError(400, "InvalidArgument", "max-keys")
                res = svc.list_objects(
                    bucket, prefix=q.get("prefix", ""),
                    marker=q.get("marker", ""),
                    max_keys=max_keys,
                    delimiter=q.get("delimiter", ""))
                rows = "".join(
                    f"<Contents><Key>{escape(c['key'])}</Key>"
                    f"<Size>{c['size']}</Size>"
                    f"<ETag>\"{c['etag']}\"</ETag>"
                    f"<LastModified>{_iso(c['mtime'])}</LastModified>"
                    f"</Contents>" for c in res["contents"])
                cps = "".join(
                    f"<CommonPrefixes><Prefix>{escape(p)}</Prefix>"
                    f"</CommonPrefixes>"
                    for p in res["common_prefixes"])
                body = (f"<?xml version='1.0'?><ListBucketResult>"
                        f"<Name>{escape(bucket)}</Name>"
                        f"<Prefix>{escape(res['prefix'])}</Prefix>"
                        f"<IsTruncated>"
                        f"{str(res['is_truncated']).lower()}"
                        f"</IsTruncated>{rows}{cps}"
                        f"</ListBucketResult>").encode()
                self._send(200, body)

            def _get_object(self, bucket: str, key: str,
                            version_id: Optional[str] = None,
                            head: Optional[dict] = None):
                rng = None
                hdr = self.headers.get("Range", "")
                if hdr.startswith("bytes="):
                    lo, _, hi = hdr[6:].partition("-")
                    try:
                        if lo == "" and hi:
                            # suffix range: last N bytes
                            size = (head or svc.head_object(
                                bucket, key, version_id))["size"]
                            n = int(hi)
                            rng = (max(0, size - n), size - 1)
                        else:
                            rng = (int(lo),
                                   int(hi) if hi else (1 << 62))
                    except ValueError:
                        raise RGWError(416, "InvalidRange", hdr)
                head, data = svc.get_object(bucket, key, rng,
                                            version_id, head=head)
                headers = {"ETag": f'"{head["etag"]}"'}
                if head.get("version_id", "null") != "null":
                    headers["x-amz-version-id"] = \
                        head["version_id"]
                if rng:
                    # RFC 7233: 206 must carry Content-Range
                    start = rng[0]
                    headers["Content-Range"] = (
                        f"bytes {start}-{start + len(data) - 1}"
                        f"/{head['size']}")
                self._send(206 if rng else 200, data,
                           ctype=head["content_type"],
                           headers=headers)

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer(addr, Handler)
        self.addr = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None
        self._lc_stop = threading.Event()
        self._lc_thread: Optional[threading.Thread] = None

    def _lc_worker(self, interval: float) -> None:
        """Lifecycle agent (reference RGWLC::LCWorker::entry): one
        expiration pass per interval until shutdown."""
        while not self._lc_stop.wait(interval):
            try:
                self.service.lc_process()
            except Exception:
                pass                 # next pass retries

    def start(self) -> "RGWServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rgw-http",
            daemon=True)
        self._thread.start()
        from ..utils.config import default_config
        conf = getattr(self.service.ioctx.rados, "conf", None) \
            or default_config()
        interval = conf["rgw_lc_interval"]
        if interval > 0:
            self._lc_thread = threading.Thread(
                target=self._lc_worker, args=(interval,),
                name="rgw-lc", daemon=True)
            self._lc_thread.start()
        return self

    def shutdown(self) -> None:
        self._lc_stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        if self._lc_thread:
            self._lc_thread.join(timeout=5)
