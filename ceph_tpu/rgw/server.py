"""RGW HTTP frontend: S3 path-style REST over the gateway core.

Python-native equivalent of the reference's beast/civetweb frontend +
REST dispatch (reference ``src/rgw/rgw_rest_s3.cc``): path-style
routes (``/bucket``, ``/bucket/key``), ListAllMyBuckets /
ListObjects XML, ETag/Content-Type headers, Range reads, multipart
upload (initiate/part/complete/abort/list — reference rgw_multi.cc),
S3-style XML error bodies, and optional AWS SigV4 authentication
(``auth_enabled``; anonymous mode remains for dev parity with the
reference's anonymous access).  Single-site.
"""
from __future__ import annotations

import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from xml.sax.saxutils import escape

from .gateway import RGWError, RGWService


def _iso(ts: float) -> str:
    import datetime
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.000Z")


class RGWServer:
    """HTTP server hosting one RGWService (reference RGWFrontend)."""

    def __init__(self, ioctx, addr: Tuple[str, int] = ("127.0.0.1", 0),
                 auth_enabled: bool = False):
        from .auth import SigV4Verifier, UserStore
        self.service = RGWService(ioctx)
        self.users = UserStore(ioctx)
        self.verifier = SigV4Verifier(self.users)
        self.auth_enabled = auth_enabled
        svc = self.service
        gw = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            # ---------------------------------------------------- util
            def _split(self) -> Tuple[str, str, dict]:
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.lstrip("/").split("/", 1)
                bucket = urllib.parse.unquote(parts[0])
                key = urllib.parse.unquote(parts[1]) \
                    if len(parts) > 1 else ""
                q = {k: v[0] for k, v in urllib.parse.parse_qs(
                    parsed.query, keep_blank_values=True).items()}
                return bucket, key, q

            def _send(self, status: int, body: bytes = b"",
                      ctype: str = "application/xml",
                      headers: Optional[dict] = None) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _error(self, e: RGWError) -> None:
                body = (f"<?xml version='1.0'?><Error><Code>{e.code}"
                        f"</Code><Message>{escape(str(e))}</Message>"
                        f"</Error>").encode()
                self._send(e.status, body)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n) if n else b""

            def _auth(self, body: bytes) -> None:
                """SigV4 check when enabled (reference rgw::auth)."""
                if not gw.auth_enabled:
                    return
                parsed = urllib.parse.urlparse(self.path)
                gw.verifier.verify(
                    self.command, parsed.path, parsed.query,
                    dict(self.headers.items()), body)

            # --------------------------------------------------- verbs
            def do_GET(self):          # noqa: N802
                bucket, key, q = self._split()
                try:
                    self._auth(b"")
                    if not bucket:
                        self._list_buckets()
                    elif not key and "uploads" in q:
                        self._list_uploads(bucket)
                    elif not key:
                        self._list_objects(bucket, q)
                    elif "uploadId" in q:
                        self._list_parts(bucket, q["uploadId"])
                    else:
                        self._get_object(bucket, key)
                except RGWError as e:
                    self._error(e)

            def do_POST(self):         # noqa: N802
                bucket, key, q = self._split()
                body = self._body()
                try:
                    self._auth(body)
                    if key and "uploads" in q:
                        uid = svc.initiate_multipart(
                            bucket, key,
                            content_type=self.headers.get(
                                "Content-Type",
                                "binary/octet-stream"))
                        xml = (f"<?xml version='1.0'?>"
                               f"<InitiateMultipartUploadResult>"
                               f"<Bucket>{escape(bucket)}</Bucket>"
                               f"<Key>{escape(key)}</Key>"
                               f"<UploadId>{uid}</UploadId>"
                               f"</InitiateMultipartUploadResult>")
                        self._send(200, xml.encode())
                    elif key and "uploadId" in q:
                        self._complete_upload(bucket, key,
                                              q["uploadId"], body)
                    else:
                        raise RGWError(400, "InvalidRequest",
                                       self.path)
                except RGWError as e:
                    self._error(e)

            def _complete_upload(self, bucket, key, upload_id,
                                 body: bytes):
                # CompleteMultipartUpload XML: ordered Part/
                # PartNumber/ETag rows (reference RGWCompleteMultipart)
                import re as _re
                parts = []
                try:
                    text = body.decode()
                except UnicodeDecodeError:
                    raise RGWError(400, "MalformedXML", "not utf-8")
                for m in _re.finditer(
                        r"<Part>.*?<PartNumber>(\d+)</PartNumber>"
                        r".*?<ETag>\"?([a-f0-9-]+)\"?</ETag>.*?"
                        r"</Part>", text, _re.S):
                    parts.append((int(m.group(1)), m.group(2)))
                etag = svc.complete_multipart(bucket, key, upload_id,
                                              parts)
                xml = (f"<?xml version='1.0'?>"
                       f"<CompleteMultipartUploadResult>"
                       f"<Bucket>{escape(bucket)}</Bucket>"
                       f"<Key>{escape(key)}</Key>"
                       f"<ETag>\"{etag}\"</ETag>"
                       f"</CompleteMultipartUploadResult>")
                self._send(200, xml.encode())

            def _list_uploads(self, bucket):
                rows = "".join(
                    f"<Upload><Key>{escape(u['key'])}</Key>"
                    f"<UploadId>{u['upload_id']}</UploadId>"
                    f"<Initiated>{_iso(u['started'])}</Initiated>"
                    f"</Upload>"
                    for u in svc.list_multipart_uploads(bucket))
                xml = (f"<?xml version='1.0'?>"
                       f"<ListMultipartUploadsResult>"
                       f"<Bucket>{escape(bucket)}</Bucket>{rows}"
                       f"</ListMultipartUploadsResult>")
                self._send(200, xml.encode())

            def _list_parts(self, bucket, upload_id):
                rows = "".join(
                    f"<Part><PartNumber>{p['part']}</PartNumber>"
                    f"<ETag>\"{p['etag']}\"</ETag>"
                    f"<Size>{p['size']}</Size></Part>"
                    for p in svc.list_parts(bucket, upload_id))
                xml = (f"<?xml version='1.0'?><ListPartsResult>"
                       f"{rows}</ListPartsResult>")
                self._send(200, xml.encode())

            def do_HEAD(self):         # noqa: N802
                bucket, key, _ = self._split()
                try:
                    self._auth(b"")
                    head = svc.head_object(bucket, key)
                    self.send_response(200)
                    self.send_header("Content-Length",
                                     str(head["size"]))
                    self.send_header("ETag", f'"{head["etag"]}"')
                    self.send_header("Content-Type",
                                     head["content_type"])
                    self.end_headers()
                except RGWError as e:
                    self.send_response(e.status)
                    self.send_header("Content-Length", "0")
                    self.end_headers()

            def do_PUT(self):          # noqa: N802
                bucket, key, q = self._split()
                # always drain the body first: leaving it unread
                # desyncs the keep-alive connection (the next request
                # line would parse from leftover body bytes)
                body = self._body()
                try:
                    self._auth(body)
                    if key and "uploadId" in q and "partNumber" in q:
                        try:
                            pnum = int(q["partNumber"])
                        except ValueError:
                            raise RGWError(400, "InvalidArgument",
                                           q["partNumber"])
                        etag = svc.upload_part(
                            bucket, key, q["uploadId"], pnum, body)
                        self._send(200,
                                   headers={"ETag": f'"{etag}"'})
                    elif not key:
                        svc.create_bucket(bucket)
                        self._send(200)
                    else:
                        etag = svc.put_object(
                            bucket, key, body,
                            content_type=self.headers.get(
                                "Content-Type",
                                "binary/octet-stream"))
                        self._send(200, headers={"ETag": f'"{etag}"'})
                except RGWError as e:
                    self._error(e)

            def do_DELETE(self):       # noqa: N802
                bucket, key, q = self._split()
                try:
                    self._auth(b"")
                    if key and "uploadId" in q:
                        svc.abort_multipart(bucket, q["uploadId"])
                        self._send(204)
                        return
                    if not key:
                        svc.delete_bucket(bucket)
                    else:
                        svc.delete_object(bucket, key)
                    self._send(204)
                except RGWError as e:
                    self._error(e)

            # ------------------------------------------------ handlers
            def _list_buckets(self):
                rows = "".join(
                    f"<Bucket><Name>{escape(b['name'])}</Name>"
                    f"<CreationDate>{_iso(b['created'])}"
                    f"</CreationDate></Bucket>"
                    for b in svc.list_buckets())
                body = (f"<?xml version='1.0'?>"
                        f"<ListAllMyBucketsResult><Buckets>{rows}"
                        f"</Buckets></ListAllMyBucketsResult>").encode()
                self._send(200, body)

            def _list_objects(self, bucket: str, q: dict):
                try:
                    max_keys = int(q.get("max-keys", 1000))
                except ValueError:
                    raise RGWError(400, "InvalidArgument", "max-keys")
                res = svc.list_objects(
                    bucket, prefix=q.get("prefix", ""),
                    marker=q.get("marker", ""),
                    max_keys=max_keys,
                    delimiter=q.get("delimiter", ""))
                rows = "".join(
                    f"<Contents><Key>{escape(c['key'])}</Key>"
                    f"<Size>{c['size']}</Size>"
                    f"<ETag>\"{c['etag']}\"</ETag>"
                    f"<LastModified>{_iso(c['mtime'])}</LastModified>"
                    f"</Contents>" for c in res["contents"])
                cps = "".join(
                    f"<CommonPrefixes><Prefix>{escape(p)}</Prefix>"
                    f"</CommonPrefixes>"
                    for p in res["common_prefixes"])
                body = (f"<?xml version='1.0'?><ListBucketResult>"
                        f"<Name>{escape(bucket)}</Name>"
                        f"<Prefix>{escape(res['prefix'])}</Prefix>"
                        f"<IsTruncated>"
                        f"{str(res['is_truncated']).lower()}"
                        f"</IsTruncated>{rows}{cps}"
                        f"</ListBucketResult>").encode()
                self._send(200, body)

            def _get_object(self, bucket: str, key: str):
                rng = None
                hdr = self.headers.get("Range", "")
                if hdr.startswith("bytes="):
                    lo, _, hi = hdr[6:].partition("-")
                    try:
                        if lo == "" and hi:
                            # suffix range: last N bytes
                            size = svc.head_object(bucket,
                                                   key)["size"]
                            n = int(hi)
                            rng = (max(0, size - n), size - 1)
                        else:
                            rng = (int(lo),
                                   int(hi) if hi else (1 << 62))
                    except ValueError:
                        raise RGWError(416, "InvalidRange", hdr)
                head, data = svc.get_object(bucket, key, rng)
                headers = {"ETag": f'"{head["etag"]}"'}
                if rng:
                    # RFC 7233: 206 must carry Content-Range
                    start = rng[0]
                    headers["Content-Range"] = (
                        f"bytes {start}-{start + len(data) - 1}"
                        f"/{head['size']}")
                self._send(206 if rng else 200, data,
                           ctype=head["content_type"],
                           headers=headers)

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer(addr, Handler)
        self.addr = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "RGWServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rgw-http",
            daemon=True)
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
