"""Swift-compatible REST API over the same gateway core.

Python-native equivalent of the reference's Swift frontend (reference
``src/rgw/rgw_rest_swift.cc`` + ``rgw_swift_auth.cc`` TempAuth):
the SAME buckets/objects the S3 API serves, spoken Swift —

  GET  /auth/v1.0                   TempAuth: X-Auth-User/X-Auth-Key
                                    -> X-Storage-Url + X-Auth-Token
  GET  /v1/AUTH_<acct>              list containers (plain or json)
  PUT  /v1/AUTH_<acct>/<cont>       create container
  GET  /v1/AUTH_<acct>/<cont>      list objects (prefix/marker/limit)
  HEAD /v1/AUTH_<acct>/<cont>      object count + bytes headers
  DELETE /v1/AUTH_<acct>/<cont>    remove empty container
  PUT/GET/HEAD/DELETE .../<obj>    object IO, X-Object-Meta-* carried

Tokens are process-local with a TTL (the reference's TempAuth keeps
them in cache too); accounts are the same UserStore uids the S3
SigV4 path authenticates, so one user can speak both dialects at the
same data — the defining property of the reference radosgw.
"""
from __future__ import annotations

import json
import secrets
import time
from typing import Dict, Optional, Tuple

from .gateway import RGWError

TOKEN_TTL = 3600.0


class SwiftAdapter:
    """Routes Swift-dialect requests; everything else falls through
    to the S3 handler (reference RGWREST::preprocess choosing the
    API by path prefix)."""

    def __init__(self, service, users):
        self.svc = service
        self.users = users
        self._tokens: Dict[str, Tuple[str, float]] = {}

    # -- TempAuth ------------------------------------------------------
    def _issue_token(self, uid: str) -> str:
        tok = "AUTH_tk" + secrets.token_hex(16)
        self._tokens[tok] = (uid, time.monotonic() + TOKEN_TTL)
        return tok

    def _account_of(self, token: Optional[str]) -> Optional[str]:
        if not token:
            return None
        ent = self._tokens.get(token)
        if ent is None or ent[1] < time.monotonic():
            self._tokens.pop(token, None)
            return None
        return ent[0]

    # -- entry ---------------------------------------------------------
    def maybe_handle(self, h, method: str) -> bool:
        """-> True when the request was a Swift route (handled,
        including errors); False = not Swift, S3 handler proceeds."""
        import urllib.parse
        parsed = urllib.parse.urlparse(h.path)
        path = urllib.parse.unquote(parsed.path)
        if path == "/auth/v1.0":
            self._tempauth(h, method)
            return True
        if not path.startswith("/v1/AUTH_"):
            return False
        q = {k: v[0] for k, v in urllib.parse.parse_qs(
            parsed.query, keep_blank_values=True).items()}
        # drain the request body FIRST (keep-alive invariant, same as
        # the S3 handlers): an error reply with unread body bytes
        # would desync the connection for the next request
        length = int(h.headers.get("Content-Length", 0) or 0)
        body = h.rfile.read(length) if length else b""
        try:
            self._dispatch(h, method, path, q, body)
        except RGWError as e:
            out = json.dumps({"error": e.code,
                              "message": str(e)}).encode()
            h._send(e.status, out, ctype="application/json")
        return True

    def _tempauth(self, h, method: str) -> None:
        if method != "GET":
            h._send(405, b"")
            return
        uid = h.headers.get("X-Auth-User", "")
        key = h.headers.get("X-Auth-Key", "")
        user = self.users.get_user(uid)
        # TempAuth validates against the user's SECRET key (reference
        # RGW_SWIFT_Auth_Get::execute comparing swift keys; the
        # framework folds swift keys onto the S3 secret)
        if user is None or key != user.get("secret_key"):
            h._send(401, b"")
            return
        tok = self._issue_token(uid)
        host, port = h.server.server_address
        h._send(204, b"", headers={
            "X-Storage-Url": f"http://{host}:{port}/v1/AUTH_{uid}",
            "X-Auth-Token": tok})

    # -- dispatch ------------------------------------------------------
    def _dispatch(self, h, method: str, path: str, q: dict,
                  body: bytes) -> None:
        acct = self._account_of(h.headers.get("X-Auth-Token"))
        parts = path[len("/v1/AUTH_"):].split("/", 2)
        owner = parts[0]
        if acct is None or acct != owner:
            raise RGWError(401, "AccessDenied", "bad or stale token")
        cont = parts[1] if len(parts) > 1 and parts[1] else ""
        obj = parts[2] if len(parts) > 2 else ""
        if not cont:
            if method in ("GET", "HEAD"):
                return self._account(h, acct, method, q)
            raise RGWError(405, "MethodNotAllowed", method)
        if not obj:
            return self._container(h, acct, cont, method, q)
        return self._object(h, acct, cont, obj, method, body)

    # -- account -------------------------------------------------------
    def _account(self, h, acct: str, method: str, q: dict) -> None:
        conts = [b for b in self.svc.list_buckets()
                 if b.get("owner", "") == acct]
        if method == "HEAD":
            h._send(204, b"", headers={
                "X-Account-Container-Count": str(len(conts))})
            return
        if q.get("format") == "json":
            body = json.dumps([{"name": b["name"]} for b in conts]
                              ).encode()
            h._send(200, body, ctype="application/json")
        else:
            text = "".join(f"{b['name']}\n" for b in conts)
            h._send(200 if text else 204, text.encode(),
                    ctype="text/plain")

    # -- containers ----------------------------------------------------
    def _container(self, h, acct: str, cont: str, method: str,
                   q: dict) -> None:
        if method == "PUT":
            try:
                self.svc.create_bucket(cont, owner=acct)
                h._send(201, b"")
            except RGWError as e:
                if e.code == "BucketAlreadyExists":
                    # idempotent 202 is for re-PUTting YOUR OWN
                    # container; a name collision with another
                    # account's bucket must surface, not masquerade
                    # as success
                    owner = self.svc.get_bucket_acl(cont)["owner"]
                    if owner and owner != acct:
                        raise RGWError(403, "AccessDenied", cont)
                    h._send(202, b"")    # Swift PUT is idempotent
                else:
                    raise
            return
        if method == "DELETE":
            # owner-only, matching S3 DeleteBucket: bucket WRITE ACL
            # grants object creation, never bucket destruction
            self.svc.check_access(acct, "acl", cont)
            self.svc.delete_bucket(cont)
            h._send(204, b"")
            return
        self.svc.check_access(acct, "read", cont)
        limit = int(q["limit"]) if q.get("limit") else None
        # follow continuation markers: a container larger than one
        # S3 listing page must not silently under-count or truncate
        # (Swift has no IsTruncated to warn the client)
        objs = []
        marker = q.get("marker", "")
        while True:
            listing = self.svc.list_objects(
                cont, prefix=q.get("prefix", ""), marker=marker,
                max_keys=limit - len(objs) if limit else None)
            objs.extend(listing["contents"])
            if not listing.get("is_truncated") or \
                    (limit and len(objs) >= limit):
                break
            marker = listing["contents"][-1]["key"] \
                if listing["contents"] else ""
            if not marker:
                break
        if method == "HEAD":
            h._send(204, b"", headers={
                "X-Container-Object-Count": str(len(objs)),
                "X-Container-Bytes-Used":
                    str(sum(o["size"] for o in objs))})
            return
        if q.get("format") == "json":
            body = json.dumps([
                {"name": o["key"], "bytes": o["size"],
                 "hash": o["etag"],
                 "last_modified": o["mtime"]}
                for o in objs]).encode()
            h._send(200, body, ctype="application/json")
        else:
            text = "".join(f"{o['key']}\n" for o in objs)
            h._send(200 if text else 204, text.encode(),
                    ctype="text/plain")

    # -- objects -------------------------------------------------------
    def _object(self, h, acct: str, cont: str, obj: str,
                method: str, body: bytes) -> None:
        if method == "PUT":
            self.svc.check_access(acct, "write", cont, obj)
            data = body
            meta = {k[len("X-Object-Meta-"):]: v
                    for k, v in h.headers.items()
                    if k.startswith("X-Object-Meta-")}
            out = self.svc.put_object(
                cont, obj, data,
                content_type=h.headers.get(
                    "Content-Type", "application/octet-stream"),
                meta=meta, owner=acct)
            h._send(201, b"", headers={"ETag": out["etag"]})
            return
        if method == "DELETE":
            self.svc.check_access(acct, "write", cont, obj)
            self.svc.delete_object(cont, obj)
            h._send(204, b"")
            return
        self.svc.check_access(acct, "read", cont, obj)
        head, data = self.svc.get_object(cont, obj)
        headers = {"ETag": head["etag"]}
        for k, v in (head.get("meta") or {}).items():
            headers[f"X-Object-Meta-{k}"] = v
        if method == "HEAD":
            headers["Content-Length"] = str(head["size"])
            headers["Content-Type"] = head["content_type"]
            h.send_response(200)
            for k, v in headers.items():
                h.send_header(k, v)
            h.end_headers()
            return
        h._send(200, data, ctype=head["content_type"],
                headers=headers)
