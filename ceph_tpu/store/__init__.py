"""Local object stores (reference src/os/, src/kv/).

- objectstore: the transactional ObjectStore seam + Transaction
  (reference os/ObjectStore.h)
- memstore: in-RAM test double (reference os/memstore/MemStore.cc)
- filestore: persistent files + LogDB metadata + WAL journal
- blockstore: raw block space + bitmap allocator + KV metadata with
  copy-on-write overwrites (reference os/bluestore/, synchronous)
- bluestore: async BlockStore subclass — WAL group commit, deferred
  apply off the PG-lock path, device-batched checksums (reference
  os/bluestore/ transaction pipeline)
- kv: KeyValueDB abstraction, MemDB/LogDB backends (reference
  src/kv/KeyValueDB.h)
"""
from .objectstore import COLL_META, GHObject, ObjectStat, ObjectStore, \
    Transaction
from .memstore import MemStore
from .filestore import FileStore
from .blockstore import BlockStore
from .bluestore import BlueStore
from .kv import KeyValueDB, LogDB, MemDB, WriteBatch

__all__ = ["COLL_META", "GHObject", "ObjectStat", "ObjectStore",
           "Transaction", "MemStore", "FileStore", "BlockStore",
           "BlueStore", "KeyValueDB",
           "LogDB", "MemDB", "WriteBatch"]
