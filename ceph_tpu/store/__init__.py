"""Local object stores (reference src/os/, src/kv/).

- objectstore: the transactional ObjectStore seam + Transaction
  (reference os/ObjectStore.h)
- memstore: in-RAM test double (reference os/memstore/MemStore.cc)
- filestore: persistent files + LogDB metadata + WAL journal
- blockstore: raw block space + bitmap allocator + KV metadata with
  copy-on-write overwrites (reference os/bluestore/)
- kv: KeyValueDB abstraction, MemDB/LogDB backends (reference
  src/kv/KeyValueDB.h)
"""
from .objectstore import COLL_META, GHObject, ObjectStat, ObjectStore, \
    Transaction
from .memstore import MemStore
from .filestore import FileStore
from .blockstore import BlockStore
from .kv import KeyValueDB, LogDB, MemDB, WriteBatch

__all__ = ["COLL_META", "GHObject", "ObjectStat", "ObjectStore",
           "Transaction", "MemStore", "FileStore", "BlockStore",
           "KeyValueDB",
           "LogDB", "MemDB", "WriteBatch"]
