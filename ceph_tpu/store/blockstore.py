"""BlockStore: objects on raw block space + KV metadata (BlueStore).

Python-native equivalent of the reference's flagship store (reference
``src/os/bluestore/`` — BlueStore.cc 16.7k LoC): object DATA lives on
a raw block device carved into fixed blocks by an allocator (reference
BitmapAllocator), all METADATA (existence, extent maps, xattrs, omap,
allocator state) lives in a key-value DB (reference RocksDB via
BlueFS; here the framework's LogDB), and overwrites are COPY-ON-WRITE
into freshly allocated blocks (reference blob/extent COW) so crash
consistency reduces to "data blocks written+synced BEFORE the one
atomic KV commit that references them".

Every data block carries a CRC32C in the extent map, verified on
every read (reference BlueStore::_verify_csum on each blob read,
BlueStore.cc:10425,10446 — scrub is the backstop, the csum is the
front line): a mismatch surfaces as EIO so the OSD read path retries
over other replicas/shards and repair-via-recovery can re-home a good
copy over the rot.  Large aligned writes optionally compress inline
through the framework's compressor registry (reference
bluestore_compression_algorithm/_mode, BlueStore.cc:4549 blob
compression): a run of full blocks that shrinks by at least one block
is stored as a compressed SEGMENT; per-logical-block CRCs are kept of
the UNCOMPRESSED content, so the same verify covers both paths.

Layout:
  block file     fixed ``BLOCK`` -sized slots, grown on demand
  kv ``meta``    C/<coll>, E/<coll>/<obj>          (as FileStore)
                 A/… xattrs, M/… omap, H/… omap header
                 X/<coll>/<obj> -> {"size": n, "blocks": [...],
                                    "crcs": [...], "segs": {...}}
                 alloc          -> allocator bitmap (bytes)
                 J/<seq>        -> journaled Transaction (WAL)

``blocks[lb]``: >= 0 raw physical block, -1 hole, <= -2 member of
compressed segment ``-(lb_value) - 2`` (see ``_Extents``).

Write path per transaction: journal the txn (WAL) → for every touched
logical block, read old block (if partial), merge, write a NEW block →
fsync the block file once → commit ONE KV batch that flips extent
maps, frees the replaced blocks in the bitmap, and retires the
journal entry.  A crash before the commit replays the journal; blocks
allocated but never referenced were also never persisted as allocated,
so nothing leaks.
"""
from __future__ import annotations

import errno
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Set

from ..utils.crc import crc32c
from ..utils.finisher import Finisher
from .filestore import _BatchView, _objkey, _unobjkey
from .kv import LogDB, WriteBatch
from .objectstore import (GHObject, ObjectStat, ObjectStore,
                          Transaction, check_ops, xor_into)

BLOCK = 4096
# compress only runs of at least this many full blocks (reference
# bluestore min_blob sizing: tiny blobs aren't worth the cycles)
COMPRESS_MIN_BLOCKS = 4


class BitmapAllocator:
    """Fixed-block allocator (reference BitmapAllocator): a bytearray
    of 0/1 flags, persisted opaquely in the KV at each commit."""

    def __init__(self, state: bytes = b""):
        self.bits = bytearray(state)

    def allocate(self) -> int:
        idx = self.bits.find(0)
        if idx < 0:
            idx = len(self.bits)
            self.bits.extend(b"\x00" * 1024)
        self.bits[idx] = 1
        return idx

    def free(self, idx: int) -> None:
        if 0 <= idx < len(self.bits):
            self.bits[idx] = 0

    def state(self) -> bytes:
        return bytes(self.bits)  # copycheck: ok - allocator bitmap snapshot for the KV record, not payload

    def used(self) -> int:
        return sum(self.bits)


class _Extents:
    """Per-object extent map (reference ExtentMap + blob csums):
    logical block i -> physical block (>= 0), hole (-1), or compressed
    segment member (value <= -2 names segment ``-value - 2``); a
    parallel per-logical-block CRC32C of the UNCOMPRESSED content
    (0 = hole/unknown — pre-csum maps verify lazily as they rewrite);
    and the segment table sid -> {phys blocks, compressed length,
    algorithm, first logical block}."""

    def __init__(self, size: int = 0,
                 blocks: Optional[List[int]] = None,
                 crcs: Optional[List[int]] = None,
                 segs: Optional[Dict[str, dict]] = None):
        self.size = size
        self.blocks = blocks if blocks is not None else []
        self.crcs = crcs if crcs is not None else []
        self.segs = segs if segs is not None else {}
        while len(self.crcs) < len(self.blocks):
            self.crcs.append(0)

    @classmethod
    def load(cls, raw: Optional[bytes]) -> "_Extents":
        if raw is None:
            return cls()
        d = json.loads(raw.decode())
        return cls(d["size"], d["blocks"], d.get("crcs"),
                   d.get("segs"))

    def dump(self) -> bytes:
        out = {"size": self.size, "blocks": self.blocks,
               "crcs": self.crcs}
        if self.segs:
            out["segs"] = self.segs
        return json.dumps(out).encode()

    def seg_of(self, lb: int) -> Optional[str]:
        v = self.blocks[lb]
        return str(-v - 2) if v <= -2 else None

    def next_sid(self) -> str:
        return str(1 + max((int(s) for s in self.segs), default=-1))


class BlockStore(ObjectStore):
    medium = "hdd"
    """reference BlueStore, collapsed to its storage model."""

    def __init__(self, path: str, compression: str = "none"):
        self.path = path
        self._lock = threading.RLock()
        self._db: Optional[LogDB] = None
        self._dev = None                 # block file handle
        self._alloc: Optional[BitmapAllocator] = None
        self._journal_seq = 0
        self._finisher: Optional[Finisher] = None
        # inline compression (reference bluestore_compression_algorithm)
        # — decompression ignores this and honors whatever algorithm a
        # segment was written with, so flipping the option is safe on
        # existing data
        self._comp_alg = "" if compression in ("", "none") \
            else compression
        self._comp = None
        # observability (reference bluestore compressed/original statfs
        # + checksum error counters)
        self.compress_logical_bytes = 0
        self.compress_stored_bytes = 0
        self.csum_failures = 0

    def _compressor(self, alg: str):
        from ..compressor import registry as creg
        if self._comp is None or self._comp.name != alg:
            self._comp = creg().create(alg)
        return self._comp

    # -- lifecycle -----------------------------------------------------
    def mkfs(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        db = LogDB(os.path.join(self.path, "meta.kv"))
        db.open()
        db.close()
        open(os.path.join(self.path, "block.dev"), "ab").close()

    def mount(self) -> None:
        with self._lock:
            if self._db is not None:
                return
            db = LogDB(os.path.join(self.path, "meta.kv"))
            db.open()
            self._db = db
            self._dev = open(os.path.join(self.path, "block.dev"),
                             "r+b" if os.path.exists(
                                 os.path.join(self.path, "block.dev"))
                             else "w+b")
            self._alloc = BitmapAllocator(db.get("alloc") or b"")
            self._finisher = Finisher("blockstore")
            self._replay_journal()

    def umount(self) -> None:
        # drain queued commit callbacks BEFORE closing anything: they
        # may touch the store (FileStore does the same)
        if self._finisher:
            self._finisher.wait_for_empty()
            self._finisher.stop()
            self._finisher = None
        with self._lock:
            if self._db is None:
                return
            self._db.close()
            self._db = None
            self._dev.close()
            self._dev = None

    def _replay_journal(self) -> None:
        """Re-apply journaled transactions (reference deferred-write
        replay): data may have partially landed; COW makes re-apply
        idempotent at the extent-map level."""
        entries = sorted(self._db.iterate("J/"))
        for key, raw in entries:
            txn = Transaction.decode(raw)
            batch = WriteBatch()
            dirty = self._apply_ops(txn.ops, batch, replay=True)
            self._flush_dev(dirty)
            batch.rm(key)
            batch.set("alloc", self._alloc.state())
            self._db.submit(batch, sync=True)
            self._journal_seq = max(self._journal_seq,
                                    int(key.split("/")[1]))

    # -- checksum seam -------------------------------------------------
    def _crc_block(self, ext: _Extents, lb: int, blk: bytes) -> None:
        """Stamp the per-logical-block CRC of freshly written content.
        Synchronous base: compute inline, one host call per block.
        BlueStore overrides to queue the block and fold all CRCs of an
        apply batch through one GF-bitmatrix pass (_crc_fold)."""
        ext.crcs[lb] = crc32c(blk)

    def _crc_fold(self) -> None:
        """Hook before extent maps fold into the KV batch: deferred
        checksum backends materialize queued CRCs here (base: CRCs
        were computed inline, nothing to do)."""

    # -- block IO ------------------------------------------------------
    def _read_block(self, phys: int) -> bytes:
        self._dev.seek(phys * BLOCK)
        buf = self._dev.read(BLOCK)
        return buf.ljust(BLOCK, b"\x00")

    def _write_block(self, phys: int, data: bytes) -> None:
        assert len(data) == BLOCK
        self._dev.seek(phys * BLOCK)
        self._dev.write(data)

    def _flush_dev(self, dirty: bool) -> None:
        if dirty:
            self._dev.flush()
            os.fsync(self._dev.fileno())

    # -- keys ----------------------------------------------------------
    @staticmethod
    def _xkey(coll: str, obj: GHObject) -> str:
        return f"X/{coll}/{_objkey(obj)}"

    def _exists_key(self, coll: str, obj: GHObject) -> str:
        return f"E/{coll}/{_objkey(obj)}"

    def _load_extents(self, coll: str, obj: GHObject) -> _Extents:
        return _Extents.load(self._db.get(self._xkey(coll, obj)))

    # -- transaction apply ---------------------------------------------
    def _do_queue_transactions(self, txns: List[Transaction],
                               on_commit: Optional[Callable[[], None]]
                               = None) -> None:
        with self._lock:
            if self._db is None:
                raise RuntimeError("store not mounted")
            merged = Transaction()
            for txn in txns:
                merged.ops.extend(txn.ops)
            check_ops(merged.ops,
                      lambda c: self._db.get(f"C/{c}") is not None,
                      lambda c, o: self._db.get(
                          self._exists_key(c, o)) is not None)
            self._journal_seq += 1
            jkey = f"J/{self._journal_seq:016d}"
            record = merged.encode()
            self._txn_meta("journal_bytes", len(record))
            # WAL append and WAL durability are separate ledger
            # phases: a wedged disk shows up as journal_fsync, a
            # bloated txn encode as journal_append
            self._db.submit(WriteBatch().set(jkey, record))
            self._stamp_txn("journal_append")
            self._db.sync()
            self._stamp_txn("journal_fsync")
            batch = WriteBatch()
            try:
                dirty = self._apply_ops(merged.ops, batch)
            except Exception:
                # apply failed (e.g. csum EIO on an RMW base read):
                # COW means nothing it did is referenced — the KV
                # batch was never submitted, so extent maps are
                # untouched and blocks it allocated were never
                # persisted as allocated.  Retire the WAL entry and
                # surface the error; leaving it would re-raise the
                # same failure from _replay_journal on EVERY mount
                # (one rotten block must not brick the store)
                self._db.submit(WriteBatch().rm(jkey), sync=True)
                raise
            self._flush_dev(dirty)       # data durable first
            self._stamp_txn("data_write")
            batch.rm(jkey)
            batch.set("alloc", self._alloc.state())
            self._db.submit(batch, sync=True)   # ONE atomic flip
            self._stamp_txn("kv_commit")
            fin = self._finisher
        for txn in txns:
            for fn in txn.on_applied:
                fn()
        self._stamp_txn("flush")
        callbacks = [fn for txn in txns for fn in txn.on_commit]
        if on_commit is not None:
            callbacks.append(on_commit)
        for fn in callbacks:
            fin.queue(fn)

    def apply_transaction(self, txn: Transaction) -> None:
        self.queue_transactions([txn])

    def _apply_ops(self, ops, batch: WriteBatch,
                   replay: bool = False) -> bool:
        """-> True if the block device was written."""
        # overlay of extent maps mutated within this txn; the batch
        # view gives read-your-writes for metadata (same-txn mkcoll,
        # clone of a just-written source, ...)
        ext_cache: Dict[str, _Extents] = {}
        view = _BatchView(self._db, batch)
        freed: Set[int] = set()
        allocated: List[int] = []
        dirty = False
        # alloc/compress interleave per block inside this loop, so
        # their time cannot carry monotone ledger stamps: it
        # accumulates here and rides the ledger as carved meta
        # seconds (store_ledger.charge carves them out of data_write)
        alloc_s = 0.0
        compress_s = 0.0

        def alloc() -> int:
            # every in-txn allocation is tracked so a failed apply
            # (csum EIO mid-transaction) rolls the in-memory bitmap
            # back — otherwise the next successful commit would
            # persist the leak with no reclaim path
            nonlocal alloc_s
            t0 = time.time()
            phys = self._alloc.allocate()
            allocated.append(phys)
            alloc_s += time.time() - t0
            return phys

        def get_ext(coll, obj) -> _Extents:
            key = self._xkey(coll, obj)
            if key not in ext_cache:
                ext_cache[key] = _Extents.load(view.get(key))
            return ext_cache[key]

        def read_in_txn(coll, obj) -> bytes:
            return self._materialize(get_ext(coll, obj))

        def put_ext(coll, obj, ext) -> None:
            ext_cache[self._xkey(coll, obj)] = ext

        def ensure_obj(coll, obj):
            if view.get(f"C/{coll}") is None:
                raise FileNotFoundError(f"no collection {coll!r}")
            batch.set(self._exists_key(coll, obj), b"")

        def free_ext(ext: _Extents) -> None:
            for phys in ext.blocks:
                if phys >= 0:
                    freed.add(phys)
            for seg in ext.segs.values():
                freed.update(seg["phys"])

        def grow(ext: _Extents, nblocks: int) -> None:
            while len(ext.blocks) < nblocks:
                ext.blocks.append(-1)
                ext.crcs.append(0)

        def read_base_block(ext: _Extents, lb: int) -> bytes:
            """RMW base read, CRC-verified: merging over rotten bytes
            and stamping a FRESH crc would launder the corruption as
            valid data — the partial write must fail with EIO instead
            (and the txn unrolls via the queue_transactions guard)."""
            blk = self._read_block(ext.blocks[lb])
            want = ext.crcs[lb] if lb < len(ext.crcs) else 0
            if want and crc32c(blk) != want:
                self.csum_failures += 1
                raise OSError(errno.EIO,
                              f"csum mismatch at logical block {lb} "
                              f"(RMW base)")
            return blk

        def flatten_seg(ext: _Extents, sid: str,
                        drop_lbs: frozenset = frozenset()) -> None:
            """Dissolve a compressed segment back into raw COW blocks
            (members in ``drop_lbs`` become holes instead): any
            mutation that touches part of a segment re-materializes
            the rest — overwrite of compressed data is the store's
            rare path, so simplicity wins over re-compression.  When
            every member is dropped (full overwrite / truncate-away)
            nothing is decompressed: the old bytes are not needed, so
            a ROTTEN segment must not brick the overwrite that would
            replace it."""
            nonlocal dirty
            seg = ext.segs.pop(sid)
            keep = [i for i in range(seg["nlb"])
                    if (lb := seg["lb0"] + i) < len(ext.blocks)
                    and ext.seg_of(lb) == sid and lb not in drop_lbs]
            raw = self._decompress_seg(seg) if keep else b""
            for i in range(seg["nlb"]):
                lb = seg["lb0"] + i
                if lb >= len(ext.blocks) or ext.seg_of(lb) != sid:
                    continue             # member dropped earlier
                if i not in keep:
                    ext.blocks[lb] = -1
                    ext.crcs[lb] = 0
                    continue
                blk = raw[i * BLOCK:(i + 1) * BLOCK]
                phys = alloc()
                self._write_block(phys, blk)
                ext.blocks[lb] = phys
                self._crc_block(ext, lb, blk)
                dirty = True
            freed.update(seg["phys"])

        def flatten_range(ext: _Extents, lb0: int, lb1: int,
                          drop_lbs: frozenset = frozenset()) -> None:
            for lb in range(lb0, min(lb1, len(ext.blocks))):
                sid = ext.seg_of(lb)
                if sid is not None:
                    flatten_seg(ext, sid, drop_lbs)

        def try_compress(ext, data, offset, first_full, last_full
                         ) -> bool:
            """Store the full-block span [first_full, last_full) as a
            compressed segment when it saves at least one block;
            -> True when it did (reference BlueStore blob compression:
            compress, keep only if the result helps)."""
            nonlocal dirty, compress_s
            nfull = last_full - first_full
            if not self._comp_alg or nfull < COMPRESS_MIN_BLOCKS:
                return False
            lo = first_full * BLOCK - offset
            span = data[lo:lo + nfull * BLOCK]
            t0 = time.time()
            try:
                comp = self._compressor(self._comp_alg).compress(span)
            except Exception:
                return False
            finally:
                compress_s += time.time() - t0
            nphys = (len(comp) + BLOCK - 1) // BLOCK
            if nphys >= nfull:           # no win: store raw
                return False
            # old content of the span: raw blocks freed, segment
            # members flattened-with-drop (their survivors re-home)
            flatten_range(ext, first_full, last_full,
                          frozenset(range(first_full, last_full)))
            phys_list = []
            for i in range(nphys):
                phys = alloc()
                self._write_block(phys, comp[i * BLOCK:(i + 1) * BLOCK]
                                  .ljust(BLOCK, b"\x00"))
                phys_list.append(phys)
            sid = ext.next_sid()
            ext.segs[sid] = {"phys": phys_list, "clen": len(comp),
                             "alg": self._comp_alg, "lb0": first_full,
                             "nlb": nfull}
            ref = -(int(sid) + 2)
            for i in range(nfull):
                lb = first_full + i
                if ext.blocks[lb] >= 0:
                    freed.add(ext.blocks[lb])
                ext.blocks[lb] = ref
                self._crc_block(ext, lb,
                                span[i * BLOCK:(i + 1) * BLOCK])
            self.compress_logical_bytes += len(span)
            self.compress_stored_bytes += nphys * BLOCK
            self._txn_meta("compress_logical", len(span))
            self._txn_meta("compress_stored", nphys * BLOCK)
            dirty = True
            return True

        def write_extent(coll, obj, offset, data) -> None:
            nonlocal dirty
            ensure_obj(coll, obj)
            ext = get_ext(coll, obj)
            end = offset + len(data)
            nblocks = (max(ext.size, end) + BLOCK - 1) // BLOCK
            grow(ext, nblocks)
            first_full = (offset + BLOCK - 1) // BLOCK
            last_full = end // BLOCK
            ranges = [(offset, end)]
            if try_compress(ext, data, offset, first_full, last_full):
                ranges = [(offset, first_full * BLOCK),
                          (last_full * BLOCK, end)]
            for lo, hi in ranges:
                if lo >= hi:
                    continue
                # a partial overwrite of a compressed segment member
                # re-materializes the segment's survivors first —
                # but blocks this write FULLY covers need none of
                # their old bytes, so they drop instead of decompress
                # (a rotten segment must not brick the overwrite that
                # replaces it, and a full overwrite of compressed
                # data must not pay a pointless decompress)
                full = frozenset(range((lo + BLOCK - 1) // BLOCK,
                                       hi // BLOCK))
                flatten_range(ext, lo // BLOCK,
                              (hi + BLOCK - 1) // BLOCK, full)
                pos = lo
                while pos < hi:
                    lb = pos // BLOCK
                    boff = pos % BLOCK
                    run = min(BLOCK - boff, hi - pos)
                    old_phys = ext.blocks[lb]
                    if boff == 0 and run == BLOCK:
                        base = b"\x00" * BLOCK
                    elif old_phys >= 0:
                        base = read_base_block(ext, lb)
                    else:
                        base = b"\x00" * BLOCK
                    merged_blk = (base[:boff]
                                  + data[pos - offset:pos - offset
                                         + run]
                                  + base[boff + run:])
                    new_phys = alloc()   # COW
                    self._write_block(new_phys, merged_blk)
                    if old_phys >= 0:
                        freed.add(old_phys)
                    ext.blocks[lb] = new_phys
                    self._crc_block(ext, lb, merged_blk)
                    dirty = True
                    pos += run
            ext.size = max(ext.size, end)
            put_ext(coll, obj, ext)

        def xor_extent(coll, obj, offset, data) -> None:
            """Parity-delta fold: read ONLY the covered blocks
            (zero-fill holes/EOF, compressed members re-home first),
            XOR the delta in, then store through the normal COW write
            path so CRC discipline and crash atomicity are inherited
            rather than re-implemented."""
            ensure_obj(coll, obj)
            ext = get_ext(coll, obj)
            end = offset + len(data)
            lb0, lb1 = offset // BLOCK, (end + BLOCK - 1) // BLOCK
            flatten_range(ext, lb0, lb1)
            base = bytearray(len(data))
            pos = offset
            while pos < end:
                lb = pos // BLOCK
                boff = pos % BLOCK
                run = min(BLOCK - boff, end - pos)
                if lb < len(ext.blocks) and ext.blocks[lb] >= 0:
                    blk = read_base_block(ext, lb)
                    base[pos - offset:pos - offset + run] = \
                        blk[boff:boff + run]
                pos += run
            put_ext(coll, obj, ext)
            xor_into(base, 0, data)
            write_extent(coll, obj, offset, base)

        for op in ops:
            name = op[0]
            try:
                if name == "touch":
                    _, coll, obj = op
                    ensure_obj(coll, obj)
                    put_ext(coll, obj, get_ext(coll, obj))
                elif name == "write":
                    _, coll, obj, offset, data = op
                    write_extent(coll, obj, offset, data)
                elif name == "xor_write":
                    _, coll, obj, offset, data = op
                    xor_extent(coll, obj, offset, data)
                elif name == "zero":
                    _, coll, obj, offset, length = op
                    ensure_obj(coll, obj)
                    ext = get_ext(coll, obj)
                    end = offset + length
                    nblocks = (max(ext.size, end) + BLOCK - 1) // BLOCK
                    grow(ext, nblocks)
                    # aligned full blocks become holes (deallocation,
                    # as BlueStore treats zero); ragged edges RMW;
                    # compressed segments re-home their survivors
                    first_full = (offset + BLOCK - 1) // BLOCK
                    last_full = end // BLOCK
                    flatten_range(ext, first_full, last_full,
                                  frozenset(range(first_full,
                                                  last_full)))
                    for lb in range(first_full, last_full):
                        if ext.blocks[lb] >= 0:
                            freed.add(ext.blocks[lb])
                        ext.blocks[lb] = -1
                        ext.crcs[lb] = 0
                    ext.size = max(ext.size, end)
                    put_ext(coll, obj, ext)
                    if first_full * BLOCK > offset:
                        write_extent(coll, obj, offset,
                                     b"\x00" * min(length,
                                                   first_full * BLOCK
                                                   - offset))
                    if end > max(last_full * BLOCK, offset):
                        lo = max(last_full * BLOCK, offset)
                        write_extent(coll, obj, lo,
                                     b"\x00" * (end - lo))
                elif name == "truncate":
                    _, coll, obj, size = op
                    ensure_obj(coll, obj)
                    ext = get_ext(coll, obj)
                    nblocks = (size + BLOCK - 1) // BLOCK
                    # any segment reaching past the cut (or holding
                    # the new ragged tail block) re-homes its kept
                    # members; the cut ones drop straight to holes.
                    # A block-aligned cut keeps block nblocks-1 whole,
                    # so its segment (if any) survives untouched.
                    flat_from = nblocks if size % BLOCK == 0 \
                        else max(0, nblocks - 1)
                    flatten_range(ext, flat_from, len(ext.blocks),
                                  frozenset(range(nblocks,
                                                  len(ext.blocks))))
                    for phys in ext.blocks[nblocks:]:
                        if phys >= 0:
                            freed.add(phys)
                    ext.blocks = ext.blocks[:nblocks]
                    ext.crcs = ext.crcs[:nblocks]
                    grow(ext, nblocks)           # grow = holes
                    if size % BLOCK and size < ext.size:
                        lb = size // BLOCK
                        if lb < len(ext.blocks) and \
                                ext.blocks[lb] >= 0:
                            base = read_base_block(ext, lb)
                            keep = size % BLOCK
                            blk = base[:keep].ljust(BLOCK, b"\x00")
                            new_phys = alloc()
                            self._write_block(new_phys, blk)
                            freed.add(ext.blocks[lb])
                            ext.blocks[lb] = new_phys
                            self._crc_block(ext, lb, blk)
                            dirty = True
                    ext.size = size
                    put_ext(coll, obj, ext)
                elif name == "remove":
                    _, coll, obj = op
                    if view.get(f"C/{coll}") is None:
                        raise FileNotFoundError(f"no coll {coll!r}")
                    free_ext(get_ext(coll, obj))
                    k = _objkey(obj)
                    batch.rm(self._exists_key(coll, obj))
                    batch.rm(self._xkey(coll, obj))
                    batch.rm(f"H/{coll}/{k}")
                    batch.rm_prefix(f"A/{coll}/{k}/")
                    batch.rm_prefix(f"M/{coll}/{k}/")
                    ext_cache.pop(self._xkey(coll, obj), None)
                elif name == "clone":
                    _, coll, src, dst = op
                    if view.get(self._exists_key(coll, src)) is None:
                        raise FileNotFoundError(
                            f"no object {src} in {coll!r}")
                    data = read_in_txn(coll, src)
                    # dst replaced wholesale
                    free_ext(get_ext(coll, dst))
                    put_ext(coll, dst, _Extents())
                    ensure_obj(coll, dst)
                    if data:
                        write_extent(coll, dst, 0, data)
                    sk, dk = _objkey(src), _objkey(dst)
                    for pfx in ("A", "M"):
                        src_pfx = f"{pfx}/{coll}/{sk}/"
                        src_rows = view.iterate(src_pfx)
                        batch.rm_prefix(f"{pfx}/{coll}/{dk}/")
                        for kk, vv in src_rows:
                            batch.set(
                                f"{pfx}/{coll}/{dk}/"
                                f"{kk[len(src_pfx):]}", vv)
                    hdr = view.get(f"H/{coll}/{sk}")
                    batch.rm(f"H/{coll}/{dk}")
                    if hdr is not None:
                        batch.set(f"H/{coll}/{dk}", hdr)
                elif name == "setattr":
                    _, coll, obj, attr, value = op
                    ensure_obj(coll, obj)
                    batch.set(f"A/{coll}/{_objkey(obj)}/{attr}", value)
                elif name == "setattrs":
                    _, coll, obj, attrs = op
                    ensure_obj(coll, obj)
                    for a, v in attrs.items():
                        batch.set(f"A/{coll}/{_objkey(obj)}/{a}", v)
                elif name == "rmattr":
                    _, coll, obj, attr = op
                    batch.rm(f"A/{coll}/{_objkey(obj)}/{attr}")
                elif name == "omap_setkeys":
                    _, coll, obj, kvs = op
                    ensure_obj(coll, obj)
                    for kk, vv in kvs.items():
                        batch.set(f"M/{coll}/{_objkey(obj)}/{kk}", vv)
                elif name == "omap_rmkeys":
                    _, coll, obj, keys = op
                    for kk in keys:
                        batch.rm(f"M/{coll}/{_objkey(obj)}/{kk}")
                elif name == "omap_clear":
                    _, coll, obj = op
                    batch.rm_prefix(f"M/{coll}/{_objkey(obj)}/")
                elif name == "omap_setheader":
                    _, coll, obj, hdr = op
                    ensure_obj(coll, obj)
                    batch.set(f"H/{coll}/{_objkey(obj)}", hdr)
                elif name == "mkcoll":
                    _, coll = op
                    batch.set(f"C/{coll}", b"")
                elif name == "rmcoll":
                    _, coll = op
                    # free every object's blocks and purge all of the
                    # collection's metadata rows — a later mkcoll with
                    # the same name must start empty (FileStore parity)
                    pfx = f"E/{coll}/"
                    for kk, _vv in view.iterate(pfx):
                        o = _unobjkey(kk[len(pfx):])
                        free_ext(get_ext(coll, o))
                        ext_cache.pop(self._xkey(coll, o), None)
                    batch.rm_prefix(f"E/{coll}/")
                    batch.rm_prefix(f"X/{coll}/")
                    batch.rm_prefix(f"A/{coll}/")
                    batch.rm_prefix(f"M/{coll}/")
                    batch.rm_prefix(f"H/{coll}/")
                    batch.rm(f"C/{coll}")
                elif name == "coll_move_rename":
                    (_, src_coll, src, dst_coll, dst) = op
                    if view.get(self._exists_key(src_coll,
                                                 src)) is None:
                        raise FileNotFoundError(
                            f"no object {src} in {src_coll!r}")
                    data = read_in_txn(src_coll, src)
                    ensure_obj(dst_coll, dst)
                    free_ext(get_ext(dst_coll, dst))
                    put_ext(dst_coll, dst, _Extents())
                    if data:
                        write_extent(dst_coll, dst, 0, data)
                    sk = _objkey(src)
                    dk = _objkey(dst)
                    for pfx in ("A", "M"):
                        src_pfx = f"{pfx}/{src_coll}/{sk}/"
                        rows = view.iterate(src_pfx)
                        batch.rm_prefix(f"{pfx}/{dst_coll}/{dk}/")
                        for kk, vv in rows:
                            batch.set(
                                f"{pfx}/{dst_coll}/{dk}/"
                                f"{kk[len(src_pfx):]}", vv)
                    hdr = view.get(f"H/{src_coll}/{sk}")
                    batch.rm(f"H/{dst_coll}/{dk}")
                    if hdr is not None:
                        batch.set(f"H/{dst_coll}/{dk}", hdr)
                    batch.rm(f"H/{src_coll}/{sk}")
                    # drop the source
                    free_ext(get_ext(src_coll, src))
                    batch.rm(self._exists_key(src_coll, src))
                    batch.rm(self._xkey(src_coll, src))
                    batch.rm_prefix(f"A/{src_coll}/{sk}/")
                    batch.rm_prefix(f"M/{src_coll}/{sk}/")
                    ext_cache.pop(self._xkey(src_coll, src), None)
                else:
                    raise ValueError(f"unknown store op {name!r}")
            except Exception as e:
                # missing object (idempotent re-apply) or csum EIO:
                # on replay, skip the op and keep mounting — a WAL
                # entry poisoned by rot must not brick the store.
                # Any other failure: roll the in-memory bitmap back
                # (nothing this apply did is referenced — the batch
                # never commits) and surface the error.  The rollback
                # covers EVERY exception kind, not just OSError — a
                # malformed op mid-transaction must not leak its
                # earlier allocations into the next commit
                if replay and isinstance(e, OSError):
                    continue
                for phys in allocated:
                    self._alloc.free(phys)
                raise
        # the COW flip: all extent maps updated in the same batch
        # (deferred-checksum backends land their batched CRCs first so
        # the dumped maps carry real values, not placeholders)
        self._crc_fold()
        for key, ext in ext_cache.items():
            batch.set(key, ext.dump())
        for phys in freed:
            self._alloc.free(phys)
        # IO accounting + carved phase seconds onto the ledger
        # (no-ops during mount-time replay — no active ledger)
        if allocated:
            self._txn_meta("blocks_allocated", len(allocated))
        if freed:
            self._txn_meta("blocks_freed", len(freed))
        if alloc_s > 0:
            self._txn_meta("alloc_s", alloc_s)
        if compress_s > 0:
            self._txn_meta("compress_s", compress_s)
        return dirty

    # -- reads ---------------------------------------------------------
    def _check_obj(self, coll: str, obj: GHObject) -> None:
        if self._db is None:
            raise RuntimeError("store not mounted")
        if self._db.get(f"C/{coll}") is None:
            raise FileNotFoundError(f"no collection {coll!r}")
        if self._db.get(self._exists_key(coll, obj)) is None:
            raise FileNotFoundError(f"no object {obj} in {coll!r}")

    def _decompress_seg(self, seg: dict) -> bytes:
        """Compressed segment -> its nlb * BLOCK uncompressed bytes."""
        comp = bytearray()
        for phys in seg["phys"]:
            comp.extend(self._read_block(phys))
        try:
            raw = self._compressor(seg["alg"]).decompress(
                bytes(comp[:seg["clen"]]))  # copycheck: ok - zlib/lz4 need a contiguous buffer; read path, not apply
        except Exception as e:
            self.csum_failures += 1
            raise OSError(errno.EIO,
                          f"segment decompress failed: {e!r}")
        if len(raw) != seg["nlb"] * BLOCK:
            self.csum_failures += 1
            raise OSError(errno.EIO, "segment length mismatch")
        return raw

    def _materialize(self, ext: _Extents) -> bytes:
        """Full object bytes with every block CRC-verified (reference
        _verify_csum on each read, BlueStore.cc:10425): rot surfaces
        as EIO here instead of propagating silently — the OSD read
        path turns it into a reconstructing/replica retry and scrub
        repair re-homes a good copy."""
        out = bytearray()
        seg_cache: Dict[str, bytes] = {}
        for lb, phys in enumerate(ext.blocks):
            if phys == -1:
                out.extend(b"\x00" * BLOCK)
                continue
            sid = ext.seg_of(lb)
            if sid is None:
                blk = self._read_block(phys)
            else:
                if sid not in seg_cache:
                    seg_cache[sid] = self._decompress_seg(
                        ext.segs[sid])
                i = lb - ext.segs[sid]["lb0"]
                blk = seg_cache[sid][i * BLOCK:(i + 1) * BLOCK]
            want = ext.crcs[lb] if lb < len(ext.crcs) else 0
            if want and crc32c(blk) != want:
                self.csum_failures += 1
                raise OSError(errno.EIO,
                              f"csum mismatch at logical block {lb}")
            out.extend(blk)
        return bytes(out[:ext.size])  # copycheck: ok - returns an immutable object image; read path, not apply

    def _read_object(self, coll: str, obj: GHObject) -> bytes:
        return self._materialize(self._load_extents(coll, obj))

    def read(self, coll: str, obj: GHObject, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        with self._lock:
            self._check_obj(coll, obj)
            data = self._read_object(coll, obj)
        if length is None:
            return data[offset:]
        return data[offset:offset + length]

    def stat(self, coll: str, obj: GHObject) -> ObjectStat:
        with self._lock:
            self._check_obj(coll, obj)
            ext = self._load_extents(coll, obj)
            return ObjectStat(size=ext.size)

    def exists(self, coll: str, obj: GHObject) -> bool:
        with self._lock:
            if self._db is None:
                return False
            return self._db.get(self._exists_key(coll, obj)) is not None

    def getattr(self, coll: str, obj: GHObject, name: str) -> bytes:
        with self._lock:
            self._check_obj(coll, obj)
            v = self._db.get(f"A/{coll}/{_objkey(obj)}/{name}")
            if v is None:
                raise KeyError(name)
            return v

    def getattrs(self, coll: str, obj: GHObject) -> Dict[str, bytes]:
        with self._lock:
            self._check_obj(coll, obj)
            pfx = f"A/{coll}/{_objkey(obj)}/"
            return {k[len(pfx):]: v
                    for k, v in self._db.iterate(pfx)}

    def omap_get(self, coll: str, obj: GHObject) -> Dict[str, bytes]:
        with self._lock:
            self._check_obj(coll, obj)
            pfx = f"M/{coll}/{_objkey(obj)}/"
            return {k[len(pfx):]: v
                    for k, v in self._db.iterate(pfx)}

    def omap_get_header(self, coll: str, obj: GHObject) -> bytes:
        with self._lock:
            self._check_obj(coll, obj)
            return self._db.get(f"H/{coll}/{_objkey(obj)}") or b""

    def omap_get_keys(self, coll: str, obj: GHObject,
                      start_after: str = "",
                      max_return: Optional[int] = None) -> List[str]:
        keys = sorted(self.omap_get(coll, obj))
        keys = [k for k in keys if k > start_after]
        return keys[:max_return] if max_return else keys

    def list_collections(self) -> List[str]:
        with self._lock:
            return sorted(k[2:] for k, _ in self._db.iterate("C/"))

    def collection_exists(self, coll: str) -> bool:
        with self._lock:
            return self._db.get(f"C/{coll}") is not None

    def collection_list(self, coll: str, start_after: str = "",
                        max_return: Optional[int] = None
                        ) -> List[GHObject]:
        with self._lock:
            pfx = f"E/{coll}/"
            objs = []
            for k, _ in self._db.iterate(pfx):
                objs.append(_unobjkey(k[len(pfx):]))
            objs.sort(key=lambda o: (o.oid, o.shard))
            objs = [o for o in objs if o.oid > start_after]
            return objs[:max_return] if max_return else objs

    # -- introspection -------------------------------------------------
    def usage(self) -> Dict:
        """Allocator accounting (reference bluestore statfs)."""
        with self._lock:
            return {"block_size": BLOCK,
                    "blocks_used": self._alloc.used(),
                    "bytes_used": self._alloc.used() * BLOCK,
                    "dev_bytes": os.path.getsize(
                        os.path.join(self.path, "block.dev")),
                    "compress_logical_bytes":
                        self.compress_logical_bytes,
                    "compress_stored_bytes":
                        self.compress_stored_bytes,
                    "csum_failures": self.csum_failures}

