"""BlockStore: objects on raw block space + KV metadata (BlueStore).

Python-native equivalent of the reference's flagship store (reference
``src/os/bluestore/`` — BlueStore.cc 16.7k LoC): object DATA lives on
a raw block device carved into fixed blocks by an allocator (reference
BitmapAllocator), all METADATA (existence, extent maps, xattrs, omap,
allocator state) lives in a key-value DB (reference RocksDB via
BlueFS; here the framework's LogDB), and overwrites are COPY-ON-WRITE
into freshly allocated blocks (reference blob/extent COW) so crash
consistency reduces to "data blocks written+synced BEFORE the one
atomic KV commit that references them".

Layout:
  block file     fixed ``BLOCK`` -sized slots, grown on demand
  kv ``meta``    C/<coll>, E/<coll>/<obj>          (as FileStore)
                 A/… xattrs, M/… omap, H/… omap header
                 X/<coll>/<obj> -> {"size": n, "blocks": [phys...]}
                 alloc          -> allocator bitmap (bytes)
                 J/<seq>        -> journaled Transaction (WAL)

Write path per transaction: journal the txn (WAL) → for every touched
logical block, read old block (if partial), merge, write a NEW block →
fsync the block file once → commit ONE KV batch that flips extent
maps, frees the replaced blocks in the bitmap, and retires the
journal entry.  A crash before the commit replays the journal; blocks
allocated but never referenced were also never persisted as allocated,
so nothing leaks.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, List, Optional, Set

from ..utils.finisher import Finisher
from .filestore import _BatchView, _objkey, _unobjkey
from .kv import LogDB, WriteBatch
from .objectstore import (GHObject, ObjectStat, ObjectStore,
                          Transaction, check_ops)

BLOCK = 4096


class BitmapAllocator:
    """Fixed-block allocator (reference BitmapAllocator): a bytearray
    of 0/1 flags, persisted opaquely in the KV at each commit."""

    def __init__(self, state: bytes = b""):
        self.bits = bytearray(state)

    def allocate(self) -> int:
        idx = self.bits.find(0)
        if idx < 0:
            idx = len(self.bits)
            self.bits.extend(b"\x00" * 1024)
        self.bits[idx] = 1
        return idx

    def free(self, idx: int) -> None:
        if 0 <= idx < len(self.bits):
            self.bits[idx] = 0

    def state(self) -> bytes:
        return bytes(self.bits)

    def used(self) -> int:
        return sum(self.bits)


class _Extents:
    """Per-object extent map: logical block i -> physical block (or -1
    for a hole), plus the byte size (reference ExtentMap)."""

    def __init__(self, size: int = 0,
                 blocks: Optional[List[int]] = None):
        self.size = size
        self.blocks = blocks if blocks is not None else []

    @classmethod
    def load(cls, raw: Optional[bytes]) -> "_Extents":
        if raw is None:
            return cls()
        d = json.loads(raw.decode())
        return cls(d["size"], d["blocks"])

    def dump(self) -> bytes:
        return json.dumps({"size": self.size,
                           "blocks": self.blocks}).encode()


class BlockStore(ObjectStore):
    medium = "hdd"
    """reference BlueStore, collapsed to its storage model."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.RLock()
        self._db: Optional[LogDB] = None
        self._dev = None                 # block file handle
        self._alloc: Optional[BitmapAllocator] = None
        self._journal_seq = 0
        self._finisher: Optional[Finisher] = None

    # -- lifecycle -----------------------------------------------------
    def mkfs(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        db = LogDB(os.path.join(self.path, "meta.kv"))
        db.open()
        db.close()
        open(os.path.join(self.path, "block.dev"), "ab").close()

    def mount(self) -> None:
        with self._lock:
            if self._db is not None:
                return
            db = LogDB(os.path.join(self.path, "meta.kv"))
            db.open()
            self._db = db
            self._dev = open(os.path.join(self.path, "block.dev"),
                             "r+b" if os.path.exists(
                                 os.path.join(self.path, "block.dev"))
                             else "w+b")
            self._alloc = BitmapAllocator(db.get("alloc") or b"")
            self._finisher = Finisher("blockstore")
            self._replay_journal()

    def umount(self) -> None:
        # drain queued commit callbacks BEFORE closing anything: they
        # may touch the store (FileStore does the same)
        if self._finisher:
            self._finisher.wait_for_empty()
            self._finisher.stop()
            self._finisher = None
        with self._lock:
            if self._db is None:
                return
            self._db.close()
            self._db = None
            self._dev.close()
            self._dev = None

    def _replay_journal(self) -> None:
        """Re-apply journaled transactions (reference deferred-write
        replay): data may have partially landed; COW makes re-apply
        idempotent at the extent-map level."""
        entries = sorted(self._db.iterate("J/"))
        for key, raw in entries:
            txn = Transaction.decode(raw)
            batch = WriteBatch()
            dirty = self._apply_ops(txn.ops, batch, replay=True)
            self._flush_dev(dirty)
            batch.rm(key)
            batch.set("alloc", self._alloc.state())
            self._db.submit(batch, sync=True)
            self._journal_seq = max(self._journal_seq,
                                    int(key.split("/")[1]))

    # -- block IO ------------------------------------------------------
    def _read_block(self, phys: int) -> bytes:
        self._dev.seek(phys * BLOCK)
        buf = self._dev.read(BLOCK)
        return buf.ljust(BLOCK, b"\x00")

    def _write_block(self, phys: int, data: bytes) -> None:
        assert len(data) == BLOCK
        self._dev.seek(phys * BLOCK)
        self._dev.write(data)

    def _flush_dev(self, dirty: bool) -> None:
        if dirty:
            self._dev.flush()
            os.fsync(self._dev.fileno())

    # -- keys ----------------------------------------------------------
    @staticmethod
    def _xkey(coll: str, obj: GHObject) -> str:
        return f"X/{coll}/{_objkey(obj)}"

    def _exists_key(self, coll: str, obj: GHObject) -> str:
        return f"E/{coll}/{_objkey(obj)}"

    def _load_extents(self, coll: str, obj: GHObject) -> _Extents:
        return _Extents.load(self._db.get(self._xkey(coll, obj)))

    # -- transaction apply ---------------------------------------------
    def queue_transactions(self, txns: List[Transaction],
                           on_commit: Optional[Callable[[], None]]
                           = None) -> None:
        with self._lock:
            if self._db is None:
                raise RuntimeError("store not mounted")
            merged = Transaction()
            for txn in txns:
                merged.ops.extend(txn.ops)
            check_ops(merged.ops,
                      lambda c: self._db.get(f"C/{c}") is not None,
                      lambda c, o: self._db.get(
                          self._exists_key(c, o)) is not None)
            self._journal_seq += 1
            jkey = f"J/{self._journal_seq:016d}"
            self._db.submit(WriteBatch().set(jkey, merged.encode()),
                            sync=True)
            batch = WriteBatch()
            dirty = self._apply_ops(merged.ops, batch)
            self._flush_dev(dirty)       # data durable first
            batch.rm(jkey)
            batch.set("alloc", self._alloc.state())
            self._db.submit(batch, sync=True)   # ONE atomic flip
            fin = self._finisher
        for txn in txns:
            for fn in txn.on_applied:
                fn()
        callbacks = [fn for txn in txns for fn in txn.on_commit]
        if on_commit is not None:
            callbacks.append(on_commit)
        for fn in callbacks:
            fin.queue(fn)

    def apply_transaction(self, txn: Transaction) -> None:
        self.queue_transactions([txn])

    def _apply_ops(self, ops, batch: WriteBatch,
                   replay: bool = False) -> bool:
        """-> True if the block device was written."""
        # overlay of extent maps mutated within this txn; the batch
        # view gives read-your-writes for metadata (same-txn mkcoll,
        # clone of a just-written source, ...)
        ext_cache: Dict[str, _Extents] = {}
        view = _BatchView(self._db, batch)
        freed: Set[int] = set()
        dirty = False

        def get_ext(coll, obj) -> _Extents:
            key = self._xkey(coll, obj)
            if key not in ext_cache:
                ext_cache[key] = _Extents.load(view.get(key))
            return ext_cache[key]

        def read_in_txn(coll, obj) -> bytes:
            ext = get_ext(coll, obj)
            out = bytearray()
            for phys in ext.blocks:
                out.extend(b"\x00" * BLOCK if phys < 0
                           else self._read_block(phys))
            return bytes(out[:ext.size])

        def put_ext(coll, obj, ext) -> None:
            ext_cache[self._xkey(coll, obj)] = ext

        def ensure_obj(coll, obj):
            if view.get(f"C/{coll}") is None:
                raise FileNotFoundError(f"no collection {coll!r}")
            batch.set(self._exists_key(coll, obj), b"")

        def write_extent(coll, obj, offset, data) -> None:
            nonlocal dirty
            ensure_obj(coll, obj)
            ext = get_ext(coll, obj)
            end = offset + len(data)
            nblocks = (max(ext.size, end) + BLOCK - 1) // BLOCK
            while len(ext.blocks) < nblocks:
                ext.blocks.append(-1)
            pos = offset
            while pos < end:
                lb = pos // BLOCK
                boff = pos % BLOCK
                run = min(BLOCK - boff, end - pos)
                old_phys = ext.blocks[lb]
                if boff == 0 and run == BLOCK:
                    base = b"\x00" * BLOCK
                elif old_phys >= 0:
                    base = self._read_block(old_phys)
                else:
                    base = b"\x00" * BLOCK
                merged_blk = (base[:boff]
                              + data[pos - offset:pos - offset + run]
                              + base[boff + run:])
                new_phys = self._alloc.allocate()   # COW
                self._write_block(new_phys, merged_blk)
                if old_phys >= 0:
                    freed.add(old_phys)
                ext.blocks[lb] = new_phys
                dirty = True
                pos += run
            ext.size = max(ext.size, end)
            put_ext(coll, obj, ext)

        for op in ops:
            name = op[0]
            try:
                if name == "touch":
                    _, coll, obj = op
                    ensure_obj(coll, obj)
                    put_ext(coll, obj, get_ext(coll, obj))
                elif name == "write":
                    _, coll, obj, offset, data = op
                    write_extent(coll, obj, offset, data)
                elif name == "zero":
                    _, coll, obj, offset, length = op
                    ensure_obj(coll, obj)
                    ext = get_ext(coll, obj)
                    end = offset + length
                    nblocks = (max(ext.size, end) + BLOCK - 1) // BLOCK
                    while len(ext.blocks) < nblocks:
                        ext.blocks.append(-1)
                    # aligned full blocks become holes (deallocation,
                    # as BlueStore treats zero); ragged edges RMW
                    first_full = (offset + BLOCK - 1) // BLOCK
                    last_full = end // BLOCK
                    for lb in range(first_full, last_full):
                        if ext.blocks[lb] >= 0:
                            freed.add(ext.blocks[lb])
                        ext.blocks[lb] = -1
                    ext.size = max(ext.size, end)
                    put_ext(coll, obj, ext)
                    if first_full * BLOCK > offset:
                        write_extent(coll, obj, offset,
                                     b"\x00" * min(length,
                                                   first_full * BLOCK
                                                   - offset))
                    if end > max(last_full * BLOCK, offset):
                        lo = max(last_full * BLOCK, offset)
                        write_extent(coll, obj, lo,
                                     b"\x00" * (end - lo))
                elif name == "truncate":
                    _, coll, obj, size = op
                    ensure_obj(coll, obj)
                    ext = get_ext(coll, obj)
                    nblocks = (size + BLOCK - 1) // BLOCK
                    for phys in ext.blocks[nblocks:]:
                        if phys >= 0:
                            freed.add(phys)
                    ext.blocks = ext.blocks[:nblocks]
                    while len(ext.blocks) < nblocks:
                        ext.blocks.append(-1)    # grow = holes
                    if size % BLOCK and size < ext.size:
                        lb = size // BLOCK
                        if lb < len(ext.blocks) and \
                                ext.blocks[lb] >= 0:
                            base = self._read_block(ext.blocks[lb])
                            keep = size % BLOCK
                            new_phys = self._alloc.allocate()
                            self._write_block(
                                new_phys, base[:keep].ljust(BLOCK,
                                                            b"\x00"))
                            freed.add(ext.blocks[lb])
                            ext.blocks[lb] = new_phys
                            dirty = True
                    ext.size = size
                    put_ext(coll, obj, ext)
                elif name == "remove":
                    _, coll, obj = op
                    if view.get(f"C/{coll}") is None:
                        raise FileNotFoundError(f"no coll {coll!r}")
                    ext = get_ext(coll, obj)
                    for phys in ext.blocks:
                        if phys >= 0:
                            freed.add(phys)
                    k = _objkey(obj)
                    batch.rm(self._exists_key(coll, obj))
                    batch.rm(self._xkey(coll, obj))
                    batch.rm(f"H/{coll}/{k}")
                    batch.rm_prefix(f"A/{coll}/{k}/")
                    batch.rm_prefix(f"M/{coll}/{k}/")
                    ext_cache.pop(self._xkey(coll, obj), None)
                elif name == "clone":
                    _, coll, src, dst = op
                    if view.get(self._exists_key(coll, src)) is None:
                        raise FileNotFoundError(
                            f"no object {src} in {coll!r}")
                    data = read_in_txn(coll, src)
                    # dst replaced wholesale
                    old = get_ext(coll, dst)
                    for phys in old.blocks:
                        if phys >= 0:
                            freed.add(phys)
                    put_ext(coll, dst, _Extents())
                    ensure_obj(coll, dst)
                    if data:
                        write_extent(coll, dst, 0, data)
                    sk, dk = _objkey(src), _objkey(dst)
                    for pfx in ("A", "M"):
                        src_pfx = f"{pfx}/{coll}/{sk}/"
                        src_rows = view.iterate(src_pfx)
                        batch.rm_prefix(f"{pfx}/{coll}/{dk}/")
                        for kk, vv in src_rows:
                            batch.set(
                                f"{pfx}/{coll}/{dk}/"
                                f"{kk[len(src_pfx):]}", vv)
                    hdr = view.get(f"H/{coll}/{sk}")
                    batch.rm(f"H/{coll}/{dk}")
                    if hdr is not None:
                        batch.set(f"H/{coll}/{dk}", hdr)
                elif name == "setattr":
                    _, coll, obj, attr, value = op
                    ensure_obj(coll, obj)
                    batch.set(f"A/{coll}/{_objkey(obj)}/{attr}", value)
                elif name == "setattrs":
                    _, coll, obj, attrs = op
                    ensure_obj(coll, obj)
                    for a, v in attrs.items():
                        batch.set(f"A/{coll}/{_objkey(obj)}/{a}", v)
                elif name == "rmattr":
                    _, coll, obj, attr = op
                    batch.rm(f"A/{coll}/{_objkey(obj)}/{attr}")
                elif name == "omap_setkeys":
                    _, coll, obj, kvs = op
                    ensure_obj(coll, obj)
                    for kk, vv in kvs.items():
                        batch.set(f"M/{coll}/{_objkey(obj)}/{kk}", vv)
                elif name == "omap_rmkeys":
                    _, coll, obj, keys = op
                    for kk in keys:
                        batch.rm(f"M/{coll}/{_objkey(obj)}/{kk}")
                elif name == "omap_clear":
                    _, coll, obj = op
                    batch.rm_prefix(f"M/{coll}/{_objkey(obj)}/")
                elif name == "omap_setheader":
                    _, coll, obj, hdr = op
                    ensure_obj(coll, obj)
                    batch.set(f"H/{coll}/{_objkey(obj)}", hdr)
                elif name == "mkcoll":
                    _, coll = op
                    batch.set(f"C/{coll}", b"")
                elif name == "rmcoll":
                    _, coll = op
                    # free every object's blocks and purge all of the
                    # collection's metadata rows — a later mkcoll with
                    # the same name must start empty (FileStore parity)
                    pfx = f"E/{coll}/"
                    for kk, _vv in view.iterate(pfx):
                        o = _unobjkey(kk[len(pfx):])
                        ext = get_ext(coll, o)
                        for phys in ext.blocks:
                            if phys >= 0:
                                freed.add(phys)
                        ext_cache.pop(self._xkey(coll, o), None)
                    batch.rm_prefix(f"E/{coll}/")
                    batch.rm_prefix(f"X/{coll}/")
                    batch.rm_prefix(f"A/{coll}/")
                    batch.rm_prefix(f"M/{coll}/")
                    batch.rm_prefix(f"H/{coll}/")
                    batch.rm(f"C/{coll}")
                elif name == "coll_move_rename":
                    (_, src_coll, src, dst_coll, dst) = op
                    if view.get(self._exists_key(src_coll,
                                                 src)) is None:
                        raise FileNotFoundError(
                            f"no object {src} in {src_coll!r}")
                    data = read_in_txn(src_coll, src)
                    ensure_obj(dst_coll, dst)
                    old = get_ext(dst_coll, dst)
                    for phys in old.blocks:
                        if phys >= 0:
                            freed.add(phys)
                    put_ext(dst_coll, dst, _Extents())
                    if data:
                        write_extent(dst_coll, dst, 0, data)
                    sk = _objkey(src)
                    dk = _objkey(dst)
                    for pfx in ("A", "M"):
                        src_pfx = f"{pfx}/{src_coll}/{sk}/"
                        rows = view.iterate(src_pfx)
                        batch.rm_prefix(f"{pfx}/{dst_coll}/{dk}/")
                        for kk, vv in rows:
                            batch.set(
                                f"{pfx}/{dst_coll}/{dk}/"
                                f"{kk[len(src_pfx):]}", vv)
                    hdr = view.get(f"H/{src_coll}/{sk}")
                    batch.rm(f"H/{dst_coll}/{dk}")
                    if hdr is not None:
                        batch.set(f"H/{dst_coll}/{dk}", hdr)
                    batch.rm(f"H/{src_coll}/{sk}")
                    # drop the source
                    src_ext = get_ext(src_coll, src)
                    for phys in src_ext.blocks:
                        if phys >= 0:
                            freed.add(phys)
                    batch.rm(self._exists_key(src_coll, src))
                    batch.rm(self._xkey(src_coll, src))
                    batch.rm_prefix(f"A/{src_coll}/{sk}/")
                    batch.rm_prefix(f"M/{src_coll}/{sk}/")
                    ext_cache.pop(self._xkey(src_coll, src), None)
                else:
                    raise ValueError(f"unknown store op {name!r}")
            except FileNotFoundError:
                if not replay:
                    raise
        # the COW flip: all extent maps updated in the same batch
        for key, ext in ext_cache.items():
            batch.set(key, ext.dump())
        for phys in freed:
            self._alloc.free(phys)
        return dirty

    # -- reads ---------------------------------------------------------
    def _check_obj(self, coll: str, obj: GHObject) -> None:
        if self._db is None:
            raise RuntimeError("store not mounted")
        if self._db.get(f"C/{coll}") is None:
            raise FileNotFoundError(f"no collection {coll!r}")
        if self._db.get(self._exists_key(coll, obj)) is None:
            raise FileNotFoundError(f"no object {obj} in {coll!r}")

    def _read_object(self, coll: str, obj: GHObject) -> bytes:
        ext = self._load_extents(coll, obj)
        out = bytearray()
        for phys in ext.blocks:
            if phys < 0:
                out.extend(b"\x00" * BLOCK)
            else:
                out.extend(self._read_block(phys))
        return bytes(out[:ext.size])

    def read(self, coll: str, obj: GHObject, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        with self._lock:
            self._check_obj(coll, obj)
            data = self._read_object(coll, obj)
        if length is None:
            return data[offset:]
        return data[offset:offset + length]

    def stat(self, coll: str, obj: GHObject) -> ObjectStat:
        with self._lock:
            self._check_obj(coll, obj)
            ext = self._load_extents(coll, obj)
            return ObjectStat(size=ext.size)

    def exists(self, coll: str, obj: GHObject) -> bool:
        with self._lock:
            if self._db is None:
                return False
            return self._db.get(self._exists_key(coll, obj)) is not None

    def getattr(self, coll: str, obj: GHObject, name: str) -> bytes:
        with self._lock:
            self._check_obj(coll, obj)
            v = self._db.get(f"A/{coll}/{_objkey(obj)}/{name}")
            if v is None:
                raise KeyError(name)
            return v

    def getattrs(self, coll: str, obj: GHObject) -> Dict[str, bytes]:
        with self._lock:
            self._check_obj(coll, obj)
            pfx = f"A/{coll}/{_objkey(obj)}/"
            return {k[len(pfx):]: v
                    for k, v in self._db.iterate(pfx)}

    def omap_get(self, coll: str, obj: GHObject) -> Dict[str, bytes]:
        with self._lock:
            self._check_obj(coll, obj)
            pfx = f"M/{coll}/{_objkey(obj)}/"
            return {k[len(pfx):]: v
                    for k, v in self._db.iterate(pfx)}

    def omap_get_header(self, coll: str, obj: GHObject) -> bytes:
        with self._lock:
            self._check_obj(coll, obj)
            return self._db.get(f"H/{coll}/{_objkey(obj)}") or b""

    def omap_get_keys(self, coll: str, obj: GHObject,
                      start_after: str = "",
                      max_return: Optional[int] = None) -> List[str]:
        keys = sorted(self.omap_get(coll, obj))
        keys = [k for k in keys if k > start_after]
        return keys[:max_return] if max_return else keys

    def list_collections(self) -> List[str]:
        with self._lock:
            return sorted(k[2:] for k, _ in self._db.iterate("C/"))

    def collection_exists(self, coll: str) -> bool:
        with self._lock:
            return self._db.get(f"C/{coll}") is not None

    def collection_list(self, coll: str, start_after: str = "",
                        max_return: Optional[int] = None
                        ) -> List[GHObject]:
        with self._lock:
            pfx = f"E/{coll}/"
            objs = []
            for k, _ in self._db.iterate(pfx):
                objs.append(_unobjkey(k[len(pfx):]))
            objs.sort(key=lambda o: (o.oid, o.shard))
            objs = [o for o in objs if o.oid > start_after]
            return objs[:max_return] if max_return else objs

    # -- introspection -------------------------------------------------
    def usage(self) -> Dict:
        """Allocator accounting (reference bluestore statfs)."""
        with self._lock:
            return {"block_size": BLOCK,
                    "blocks_used": self._alloc.used(),
                    "bytes_used": self._alloc.used() * BLOCK,
                    "dev_bytes": os.path.getsize(
                        os.path.join(self.path, "block.dev"))}

