"""BlueStore-class async local store: WAL group commit + deferred apply.

BlockStore (ceph_tpu/store/blockstore.py) keeps the reference's
storage MODEL — raw block space + KV metadata + per-block CRCs — but
not its execution model: every ``queue_transactions`` runs journal
append, journal fsync, the whole extent apply, a device flush and the
KV commit INLINE under one global store lock, on the PG-lock path.
This subclass keeps the storage model and replaces the transaction
discipline with the reference BlueStore's async pipeline (reference
src/os/bluestore/BlueStore.cc _txc_state_proc: PREPARE → AIO_WAIT →
IO_DONE → KV_QUEUED → KV_COMMITTING → deferred apply):

* **WAL with group commit** — callers append length+CRC framed
  records to a shared WAL segment under a short queue lock and then
  JOIN a shared fsync: the first waiter becomes the sync leader
  (optionally dwelling ``group_commit_window_s`` so followers pile
  in), syncs once, and advances the durable watermark for everyone
  (reference KernelDevice::aio_submit batching + the kv_sync_thread's
  one-fsync-per-batch discipline).  ``on_commit`` fires on WAL
  durability, NOT on apply — the OSD's commit ack leaves the store
  path after one buffered write + an amortized fsync share.
* **Deferred apply** — durable transactions queue for a background
  applier (classic: a dedicated thread; crimson: a reactor task via
  ``bind_apply_reactor``) that folds them into extents + KV in
  batches: one vectored multi-object device pass, one device flush,
  one atomic KV commit per batch (reference deferred_try_submit /
  _deferred_submit_unlock).  Reads in the commit→apply window wait on
  a per-object barrier fed by an existence overlay; the waiter
  WORK-STEALS the apply when the driver is busy or gone, so progress
  never depends on the background driver (and a crimson reactor
  reading its own pending write cannot deadlock).
* **Checksums on the device batcher** — the per-block CRC32C stamps
  of an apply batch are queued and folded through ONE batched
  GF-bitmatrix pass (ops/crclinear, the same [32, 8·BLOCK] bitmatrix
  matmul the EC kernels run), device-routed through the codec backend
  when an accelerator is live (``attach_device_batcher``), host loop
  otherwise — mirroring the deep-scrub offload gate in
  osd/ecbackend.py.  Verification on read is inherited unchanged.

Ledger contract (utils/store_ledger.py): the queueing thread stamps
``journal_append`` / ``journal_fsync``; ownership of the ledger then
transfers to the applier (``_deferred`` handshake with the
ObjectStore base), which stamps ``deferred_queue`` / ``data_write`` /
``kv_commit`` / ``flush`` and finalizes — stamps stay monotone
because the applier only takes WAL-durable, sealed entries, so
charge-sum == txn wall survives the async split.

Crash consistency: COW data blocks + the one atomic KV flip, as the
base.  A crash before the KV commit replays the WAL on mount (records
with seq <= the persisted applied watermark are skipped, re-apply is
idempotent); a torn or corrupt WAL tail record is discarded whole.

RAM mode (``path=""``): MemDB metadata + BytesIO device + no WAL
file — same code paths minus durability, so memory-backed clusters
(bench, tests) exercise the full async pipeline.
"""
from __future__ import annotations

import io
import os
import struct
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.crc import crc32c
from ..utils.finisher import Finisher
from .blockstore import BLOCK, BitmapAllocator, BlockStore, _Extents
from .kv import MemDB, LogDB, WriteBatch
from .objectstore import (_TXN_TLS, GHObject, Transaction, check_ops)

#: KV key persisting the highest WAL seq whose apply has committed —
#: mount-time replay skips records at/below it
APPLIED_KEY = "bluestore_applied_seq"

#: WAL record framing: u32 payload len | u32 crc32c(payload) | u64 seq
_WAL_HDR = struct.Struct("<IIQ")

#: xattr-overlay tombstone for a pending rmattr
_ATTR_DEL = object()


class _Pending:
    """One WAL-durable transaction waiting for the deferred applier."""

    __slots__ = ("seq", "txns", "ops", "led", "sealed", "taken",
                 "aborted")

    def __init__(self, seq: int, txns: List[Transaction], ops: List):
        self.seq = seq
        self.txns = txns
        self.ops = ops
        self.led: Optional[Dict[str, float]] = None
        self.sealed = False        # queueing thread done stamping
        self.taken = False         # claimed by an in-flight apply batch
        self.aborted = False       # queueing thread raised post-append


class BlueStore(BlockStore):
    """Async BlueStore-class backend (osd_objectstore=bluestore)."""

    medium = "ssd"

    def __init__(self, path: str = "", compression: str = "none",
                 wal_segment_bytes: int = 16 << 20,
                 group_commit_window_s: float = 0.0,
                 apply_batch_txns: int = 16,
                 deferred_queue_depth: int = 128,
                 start_applier: bool = True):
        super().__init__(path, compression)
        self.wal_segment_bytes = int(wal_segment_bytes)
        self.group_commit_window_s = float(group_commit_window_s)
        self.apply_batch_txns = max(1, int(apply_batch_txns))
        self.deferred_queue_depth = max(1, int(deferred_queue_depth))
        self._start_applier = bool(start_applier)
        # admission/overlay state (lock order: _qcond's lock BEFORE
        # the base _lock; never the reverse)
        self._qcond = threading.Condition(threading.Lock())
        self._pending: deque = deque()
        self._ov_colls: Dict[str, Tuple[bool, int]] = {}
        self._ov_objs: Dict[Tuple[str, GHObject], Tuple[bool, int]] = {}
        self._ov_wiped: Dict[str, int] = {}
        # xattr overlay: pending setattr/rmattr values served to
        # readers WITHOUT an apply barrier — the EC write path reads
        # the hinfo + object-info xattrs before every sub-write, so a
        # barrier here would re-serialize the whole deferred pipeline
        self._ov_attrs: Dict[Tuple[str, GHObject, str],
                             Tuple[object, int]] = {}
        # object-identity changes (remove/clone-dst/rename) whose
        # attr outcome is unknowable from the ops alone: readers past
        # this seq must barrier
        self._ov_attr_dirty: Dict[Tuple[str, GHObject], int] = {}
        self._wal_seq = 0
        self._applied_seq = 0
        self._stop = False
        # group-commit state
        self._gc_cond = threading.Condition(threading.Lock())
        self._gc_syncing = False
        self._wal_durable_seq = 0
        # WAL segments: [segno, path, fh, last_seq, bytes]
        self._wal_segs: List[list] = []
        self._wal_segno = 0
        self._wal_unsynced: List = []   # fhs with appended-not-synced data
        # single-applier mutex (work-stealing: any thread may pump)
        self._apply_mutex = threading.Lock()
        self._apply_thread: Optional[threading.Thread] = None
        self._reactor = None
        # vectored device-write buffer (apply-batch scope, under _lock)
        self._wbuf: Dict[int, bytes] = {}
        # deferred-checksum queue (apply-entry scope, under _lock)
        self._crcq: List[Tuple[_Extents, int, bytes]] = []
        self._csum_backend_fn: Optional[Callable] = None
        # counters (surfaced via usage() and the store_ladder bench)
        self.wal_records = 0
        self.wal_bytes = 0
        self.wal_group_syncs = 0
        self.wal_group_txns = 0
        self.apply_batches = 0
        self.apply_txns = 0
        self.apply_errors = 0
        self.vectored_flushes = 0
        self.vectored_blocks = 0
        self.vectored_runs = 0
        self.csum_batches = 0
        self.csum_blocks = 0
        self.csum_device_batches = 0

    # -- lifecycle -----------------------------------------------------
    def mkfs(self) -> None:
        if self.path:
            super().mkfs()
        # RAM mode: nothing to initialize — mount starts empty

    def mount(self) -> None:
        with self._lock:
            if self._db is not None:
                return
            if self.path:
                db = LogDB(os.path.join(self.path, "meta.kv"))
                db.open()
                self._db = db
                devp = os.path.join(self.path, "block.dev")
                self._dev = open(
                    devp, "r+b" if os.path.exists(devp) else "w+b")
            else:
                self._db = MemDB()
                self._db.open()
                self._dev = io.BytesIO()
            self._alloc = BitmapAllocator(self._db.get("alloc") or b"")
            self._finisher = Finisher("bluestore")
            self._applied_seq = int(
                (self._db.get(APPLIED_KEY) or b"0").decode())
            self._wal_seq = self._applied_seq
            self._wal_durable_seq = self._applied_seq
            self._stop = False
            if self.path:
                self._wal_replay()
                self._wal_roll()
        if self._start_applier:
            t = threading.Thread(target=self._apply_loop,
                                 name="bluestore-apply", daemon=True)
            self._apply_thread = t
            t.start()

    def umount(self) -> None:
        # stop the background driver, then drain inline: the applier
        # (thread OR reactor) may already be gone at shutdown, so the
        # drain must not depend on it
        with self._qcond:
            self._stop = True
            self._qcond.notify_all()
        t = self._apply_thread
        if t is not None:
            t.join(timeout=10.0)
            self._apply_thread = None
        while self._pump_once():
            pass
        if self._finisher:
            self._finisher.wait_for_empty()
            self._finisher.stop()
            self._finisher = None
        with self._lock:
            if self._db is None:
                return
            for seg in self._wal_segs:
                try:
                    seg[2].close()
                except Exception:
                    pass
            self._wal_segs = []
            self._wal_unsynced = []
            self._db.close()
            self._db = None
            self._dev.close()
            self._dev = None
        with self._qcond:
            self._pending.clear()
            self._ov_colls.clear()
            self._ov_objs.clear()
            self._ov_wiped.clear()
            self._ov_attrs.clear()
            self._ov_attr_dirty.clear()
            self._qcond.notify_all()

    # -- WAL -----------------------------------------------------------
    def _wal_path(self, segno: int) -> str:
        return os.path.join(self.path, f"wal.{segno:08d}")

    def _wal_roll(self) -> None:
        """Open a fresh active segment (caller: mount under _lock, or
        _wal_write under the queue lock)."""
        self._wal_segno += 1
        fh = open(self._wal_path(self._wal_segno), "ab")
        self._wal_segs.append([self._wal_segno,
                               self._wal_path(self._wal_segno),
                               fh, 0, 0])

    def _wal_write(self, seq: int, record, nbytes: int) -> None:
        """Append one framed record to the active segment (caller
        holds the queue lock).  flush() pushes it to the OS page cache
        so a process crash preserves it; durability against power loss
        is the group fsync's job.  RAM mode passes record=None (no
        segment to write) with the byte count precomputed."""
        self.wal_records += 1
        self.wal_bytes += nbytes
        if record is None or not self.path:
            return
        seg = self._wal_segs[-1]
        if seg[4] >= self.wal_segment_bytes:
            self._wal_roll()
            seg = self._wal_segs[-1]
        fh = seg[2]
        fh.write(_WAL_HDR.pack(len(record), crc32c(record), seq))
        fh.write(record)
        fh.flush()
        seg[3] = seq
        seg[4] += _WAL_HDR.size + len(record)
        if fh not in self._wal_unsynced:
            self._wal_unsynced.append(fh)

    def _wal_fsync(self, seq: int) -> None:
        """Group commit: return once WAL seq ``seq`` is durable.  The
        first waiter leads — dwells the group-commit window, syncs
        every segment touched since the last sync, and advances the
        durable watermark for all followers."""
        while True:
            with self._gc_cond:
                if self._wal_durable_seq >= seq:
                    return
                if self._gc_syncing:
                    self._gc_cond.wait(1.0)
                    continue
                self._gc_syncing = True
                prev = self._wal_durable_seq
            try:
                if self.group_commit_window_s > 0:
                    time.sleep(self.group_commit_window_s)
                with self._qcond:
                    top = self._wal_seq
                    fhs, self._wal_unsynced = self._wal_unsynced, []
                for fh in fhs:
                    fh.flush()
                    os.fsync(fh.fileno())
            except BaseException:
                with self._gc_cond:
                    self._gc_syncing = False
                    self._gc_cond.notify_all()
                raise
            with self._gc_cond:
                self._gc_syncing = False
                self._wal_durable_seq = max(self._wal_durable_seq, top)
                self._gc_cond.notify_all()
            self.wal_group_syncs += 1
            self.wal_group_txns += top - prev

    def _wal_retire(self) -> None:
        """Drop fully-applied non-active segments (caller holds the
        queue lock)."""
        keep = []
        for seg in self._wal_segs:
            active = seg is self._wal_segs[-1]
            if not active and seg[3] <= self._applied_seq:
                try:
                    seg[2].close()
                    os.remove(seg[1])
                except Exception:
                    pass
            else:
                keep.append(seg)
        self._wal_segs = keep

    def _wal_replay(self) -> None:
        """Mount-time recovery: apply WAL records above the persisted
        applied watermark, in seq order, then start a fresh WAL.
        Re-apply is idempotent at the extent-map level (COW), and a
        torn/corrupt tail record discards the rest of its segment."""
        names = sorted(n for n in os.listdir(self.path)
                       if n.startswith("wal."))
        entries: List[Tuple[int, bytes]] = []
        top_segno = 0
        for name in names:
            top_segno = max(top_segno, int(name.split(".")[1]))
            with open(os.path.join(self.path, name), "rb") as fh:
                while True:
                    hdr = fh.read(_WAL_HDR.size)
                    if len(hdr) < _WAL_HDR.size:
                        break
                    length, want, seq = _WAL_HDR.unpack(hdr)
                    payload = fh.read(length)
                    if len(payload) < length or \
                            crc32c(payload) != want:
                        break              # torn tail: discard rest
                    entries.append((seq, payload))
        entries.sort()
        for seq, payload in entries:
            self._wal_seq = max(self._wal_seq, seq)
            if seq <= self._applied_seq:
                continue
            txn = Transaction.decode(payload)
            batch = WriteBatch()
            dirty = self._apply_ops(txn.ops, batch, replay=True)
            self._wbuf_flush()
            self._flush_dev(dirty)
            batch.set("alloc", self._alloc.state())
            batch.set(APPLIED_KEY, str(seq).encode())
            self._db.submit(batch, sync=True)
            self._applied_seq = seq
        self._wal_durable_seq = self._wal_seq
        for name in names:
            try:
                os.remove(os.path.join(self.path, name))
            except OSError:
                pass
        self._wal_segno = top_segno

    # -- admission overlay ---------------------------------------------
    def _coll_exists_q(self, coll: str) -> bool:
        st = self._ov_colls.get(coll)
        if st is not None:
            return st[0]
        return self._db.get(f"C/{coll}") is not None

    def _obj_exists_q(self, coll: str, obj: GHObject) -> bool:
        e = self._ov_objs.get((coll, obj))
        w = self._ov_wiped.get(coll)
        if e is not None and (w is None or e[1] >= w):
            return e[0]
        if w is not None:
            return False
        return self._db.get(self._exists_key(coll, obj)) is not None

    _CREATES = frozenset(("touch", "write", "xor_write", "zero",
                          "truncate", "setattr", "omap_setkeys",
                          "omap_setheader", "omap_rmkeys", "omap_clear",
                          "rmattr"))

    def _admit_overlay(self, ops, seq: int) -> None:
        """Record the existence outcome of admitted (not yet applied)
        ops so later admissions validate against them and reads know
        which WAL seq they must wait for (caller holds the queue
        lock).  check_ops already validated, so the requires-family
        ops only refresh the barrier seq."""
        for op in ops:
            name = op[0]
            if name in self._CREATES:
                self._ov_objs[(op[1], op[2])] = (True, seq)
                if name == "setattr":
                    self._ov_attrs[(op[1], op[2], op[3])] = \
                        (op[4], seq)
                elif name == "rmattr":
                    self._ov_attrs[(op[1], op[2], op[3])] = \
                        (_ATTR_DEL, seq)
            elif name == "remove":
                self._ov_objs[(op[1], op[2])] = (False, seq)
                self._ov_attr_dirty[(op[1], op[2])] = seq
            elif name == "clone":
                _, coll, src, dst = op
                self._ov_objs[(coll, src)] = (True, seq)
                self._ov_objs[(coll, dst)] = (True, seq)
                # dst inherits src's attrs as of this seq — a value
                # the overlay cannot synthesize
                self._ov_attr_dirty[(coll, dst)] = seq
            elif name == "mkcoll":
                self._ov_colls[op[1]] = (True, seq)
            elif name == "rmcoll":
                self._ov_colls[op[1]] = (False, seq)
                self._ov_wiped[op[1]] = seq
            elif name == "coll_move_rename":
                _, src_coll, src, dst_coll, dst = op
                self._ov_objs[(src_coll, src)] = (False, seq)
                self._ov_objs[(dst_coll, dst)] = (True, seq)
                self._ov_attr_dirty[(src_coll, src)] = seq
                self._ov_attr_dirty[(dst_coll, dst)] = seq

    def _ov_gc(self) -> None:
        """Drop overlay entries the KV now reflects (caller holds the
        queue lock; applied_seq just advanced)."""
        a = self._applied_seq
        for d in (self._ov_colls, self._ov_objs):
            for k in [k for k, v in d.items() if v[1] <= a]:
                del d[k]
        for k in [k for k, v in self._ov_wiped.items() if v <= a]:
            del self._ov_wiped[k]
        for k in [k for k, v in self._ov_attrs.items() if v[1] <= a]:
            del self._ov_attrs[k]
        for k in [k for k, v in self._ov_attr_dirty.items() if v <= a]:
            del self._ov_attr_dirty[k]

    def _pending_seq_for(self, coll: str,
                         obj: Optional[GHObject] = None) -> int:
        seq = 0
        c = self._ov_colls.get(coll)
        if c is not None:
            seq = c[1]
        w = self._ov_wiped.get(coll)
        if w is not None and w > seq:
            seq = w
        if obj is not None:
            e = self._ov_objs.get((coll, obj))
            if e is not None and e[1] > seq:
                seq = e[1]
        return seq

    # -- queue path ----------------------------------------------------
    def _do_queue_transactions(self, txns: List[Transaction],
                               on_commit: Optional[Callable[[], None]]
                               = None) -> None:
        led = getattr(_TXN_TLS, "led", None)
        merged_ops = [op for txn in txns for op in txn.ops]
        while True:
            # backpressure BEFORE validation: admissions that raced in
            # while we waited must be visible to check_ops.  A full
            # queue turns the submitter into an applier (work-steal)
            # instead of parking it — a crimson reactor blocking here
            # would stall its whole data plane.
            with self._qcond:
                if self._db is None:
                    raise RuntimeError("store not mounted")
                if len(self._pending) < self.deferred_queue_depth \
                        or self._stop:
                    break
            if not self._pump_once():
                with self._qcond:
                    if self._db is not None and not self._stop and \
                            len(self._pending) >= \
                            self.deferred_queue_depth:
                        self._qcond.wait(0.05)
        with self._qcond:
            if self._db is None:
                raise RuntimeError("store not mounted")
            check_ops(merged_ops, self._coll_exists_q,
                      self._obj_exists_q)
            self._wal_seq += 1
            seq = self._wal_seq
            if self.path:
                merged = Transaction()
                merged.ops = merged_ops
                record = merged.encode()
            else:
                # volatile store: the WAL buys nothing a process
                # crash wouldn't lose anyway, so skip the payload
                # serialization and account the data bytes directly
                record = None
            nbytes = len(record) if record is not None else sum(
                len(op[4]) for op in merged_ops
                if op[0] in ("write", "xor_write"))
            self._txn_meta("journal_bytes", nbytes)
            self._wal_write(seq, record, nbytes)
            self._stamp_txn("journal_append")
            p = _Pending(seq, txns, merged_ops)
            p.led = led
            self._pending.append(p)
            self._admit_overlay(merged_ops, seq)
        try:
            self._wal_fsync(seq)            # group commit join
            self._stamp_txn("journal_fsync")
        except BaseException:
            # WAL durability failed: the entry must not wedge the
            # queue — seal it aborted so the applier skips past it
            with self._qcond:
                p.aborted = True
                p.led = None
                p.sealed = True
                self._qcond.notify_all()
            raise
        if led is not None:
            # hand the ledger to the applier: the base finalizes
            # nothing, the apply batch stamps the remaining phases
            led["_deferred"] = True
        with self._qcond:
            p.sealed = True
            self._qcond.notify_all()
        # commit callbacks ride WAL durability, not apply (the whole
        # point: the OSD's commit ack leaves the PG-lock path here)
        fin = self._finisher
        callbacks = [fn for txn in txns for fn in txn.on_commit]
        if on_commit is not None:
            callbacks.append(on_commit)
        if fin is not None:
            for fn in callbacks:
                fin.queue(fn)
        else:
            for fn in callbacks:
                fn()
        self._kick_apply()

    # -- deferred apply ------------------------------------------------
    def bind_apply_reactor(self, reactor) -> None:
        """Crimson wiring: schedule apply batches as reactor tasks
        instead of the background thread (which parks).  Pass None to
        unbind (shutdown)."""
        self._reactor = reactor
        if reactor is not None:
            self._kick_apply()

    def _kick_apply(self) -> None:
        r = self._reactor
        if r is not None:
            try:
                r.call_soon(self._reactor_pump)
                return
            except Exception:
                pass
        with self._qcond:
            self._qcond.notify_all()

    def _reactor_pump(self) -> None:
        self._pump_once()
        with self._qcond:
            more = self._ready_locked() and not self._stop
        r = self._reactor
        if more and r is not None:
            r.call_soon(self._reactor_pump)

    def _ready_locked(self) -> bool:
        for p in self._pending:
            if p.taken:
                continue
            return p.sealed and p.seq <= self._wal_durable_seq
        return False

    def _apply_loop(self) -> None:
        while True:
            with self._qcond:
                while not self._stop and (
                        self._reactor is not None
                        or not self._ready_locked()):
                    self._qcond.wait(0.25)
                if self._stop:
                    return
            self._pump_once()

    def _take_batch(self) -> List[_Pending]:
        """Claim the next apply batch: the longest sealed, durable,
        unclaimed prefix of the queue, up to apply_batch_txns (caller
        holds _apply_mutex)."""
        batch: List[_Pending] = []
        with self._qcond:
            for p in self._pending:
                if p.taken:
                    continue
                if not p.sealed or p.seq > self._wal_durable_seq:
                    break
                p.taken = True
                batch.append(p)
                if len(batch) >= self.apply_batch_txns:
                    break
        return batch

    def _pump_once(self) -> bool:
        """Apply one batch if one is ready and no other applier is at
        it; -> True if transactions were applied.  Work-stealing entry
        point: the background driver, a reactor task, a blocked
        reader, flush() and umount() all come through here."""
        if not self._apply_mutex.acquire(blocking=False):
            return False
        try:
            batch = self._take_batch()
            if not batch:
                return False
            self._apply_batch(batch)
            return True
        finally:
            self._apply_mutex.release()

    def _apply_batch(self, batch: List[_Pending]) -> None:
        t_dq = time.time()
        live = [p for p in batch if not p.aborted]
        for p in live:
            if p.led is not None:
                p.led["deferred_queue"] = t_dq
        kvbatch = WriteBatch()
        dirty = False
        with self._lock:
            for p in live:
                prev = getattr(_TXN_TLS, "led", None)
                _TXN_TLS.led = p.led
                mark = len(kvbatch.ops)
                try:
                    dirty = self._apply_ops(p.ops, kvbatch) or dirty
                except Exception:
                    # commit was already acked at WAL durability; a
                    # failed apply (csum EIO on an RMW base) cannot
                    # unwind it.  Roll this entry's KV ops back so
                    # the rest of the batch commits clean, and count
                    # the casualty (reference BlueStore asserts here;
                    # we degrade to a surfaced counter).
                    del kvbatch.ops[mark:]
                    self.apply_errors += 1
                finally:
                    _TXN_TLS.led = prev
            self._wbuf_flush()
            self._flush_dev(dirty)
            t_dw = time.time()
            kvbatch.set("alloc", self._alloc.state())
            kvbatch.set(APPLIED_KEY, str(batch[-1].seq).encode())
            self._db.submit(kvbatch, sync=bool(self.path))
            t_kv = time.time()
        for p in live:
            for txn in p.txns:
                for fn in txn.on_applied:
                    fn()
        t_fl = time.time()
        self.apply_batches += 1
        self.apply_txns += len(live)
        for p in live:
            led = p.led
            if led is None:
                continue
            led["data_write"] = t_dw
            led["kv_commit"] = t_kv
            led["flush"] = t_fl
            self._finalize_txn(led, p.txns)
        with self._qcond:
            self._applied_seq = batch[-1].seq
            for p in batch:
                self._pending.remove(p)
            self._ov_gc()
            self._wal_retire()
            self._qcond.notify_all()

    # -- vectored device writes ----------------------------------------
    def _write_block(self, phys: int, data: bytes) -> None:
        assert len(data) == BLOCK
        self._wbuf[phys] = data

    def _read_block(self, phys: int) -> bytes:
        buf = self._wbuf.get(phys)
        if buf is not None:
            return buf
        return super()._read_block(phys)

    def _wbuf_flush(self) -> None:
        """Land the apply batch's buffered blocks as sorted contiguous
        runs: one seek + one writelines per run instead of one
        seek+write per block (caller holds _lock)."""
        if not self._wbuf:
            return
        items = sorted(self._wbuf.items())
        dev = self._dev
        i, n = 0, len(items)
        while i < n:
            j = i + 1
            while j < n and items[j][0] == items[j - 1][0] + 1:
                j += 1
            dev.seek(items[i][0] * BLOCK)
            dev.writelines(blk for _, blk in items[i:j])
            self.vectored_runs += 1
            i = j
        self.vectored_flushes += 1
        self.vectored_blocks += n
        self._wbuf.clear()

    def _flush_dev(self, dirty: bool) -> None:
        if not self.path:
            return                       # BytesIO: nothing to fsync
        super()._flush_dev(dirty)

    # -- batched checksums ---------------------------------------------
    def attach_device_batcher(self, backend_fn: Callable) -> None:
        """OSD wiring: ``backend_fn()`` -> the live codec backend (or
        None).  Resolved per batch, because the EncodeBatcher only
        learns its backend after the first device dispatch."""
        self._csum_backend_fn = backend_fn

    def _crc_block(self, ext: _Extents, lb: int, blk: bytes) -> None:
        # defer: placeholder 0 means "unknown" to every reader, so
        # intra-batch RMW/materialize reads stay correct pre-fold
        self._crcq.append((ext, lb, blk))
        ext.crcs[lb] = 0

    def _crc_fold(self) -> None:
        q = self._crcq
        if not q:
            return
        self._crcq = []
        crcs = self._crc_batch([blk for _, _, blk in q])
        for (ext, lb, _), c in zip(q, crcs):
            ext.crcs[lb] = int(c)

    def _crc_batch(self, blocks: List[bytes]) -> List[int]:
        """One batched CRC pass over an apply batch's blocks.  Device
        route only when an accelerator is live AND a codec backend
        with the bitmatrix kernel is attached (the deep-scrub gate,
        osd/ecbackend.py); a plain-CPU host loop is strictly faster
        than the bitplane matmul, so that is the fallback."""
        self.csum_batches += 1
        self.csum_blocks += len(blocks)
        fn = self._csum_backend_fn
        if fn is not None and len(blocks) > 1:
            try:
                backend = fn()
                if backend is not None and \
                        hasattr(backend, "apply_bitmatrix_bytes"):
                    import jax
                    if jax.default_backend() != "cpu":
                        from ..ops import crclinear
                        out = crclinear.shared().crc_batch(
                            blocks, backend=backend)
                        self.csum_device_batches += 1
                        return [int(c) for c in out]
            except Exception:
                pass                     # host loop serves
        return [crc32c(b) for b in blocks]

    # -- read barrier ----------------------------------------------------
    def _wait_applied(self, seq: int) -> None:
        """Block until WAL seq ``seq`` is applied, stealing the apply
        work when the background driver doesn't get there first."""
        self._wal_fsync(seq)
        while True:
            with self._qcond:
                if self._applied_seq >= seq or self._db is None:
                    return
            if self._pump_once():
                continue
            with self._qcond:
                if self._applied_seq >= seq or self._db is None:
                    return
                self._qcond.wait(0.05)

    def _barrier(self, coll: str,
                 obj: Optional[GHObject] = None) -> None:
        with self._qcond:
            seq = self._pending_seq_for(coll, obj)
            if seq <= self._applied_seq:
                return
        self._wait_applied(seq)

    def _barrier_all(self) -> None:
        with self._qcond:
            seq = max((p.seq for p in self._pending),
                      default=self._applied_seq)
            if seq <= self._applied_seq:
                return
        self._wait_applied(seq)

    def flush(self) -> None:
        """Drain: every queued transaction applied, every commit
        callback delivered (reference ObjectStore::flush)."""
        self._barrier_all()
        fin = self._finisher
        if fin is not None:
            fin.wait_for_empty()

    # -- reads (commit→apply window correctness) -----------------------
    def exists(self, coll: str, obj: GHObject) -> bool:
        # non-blocking: the admission overlay already knows the answer
        with self._qcond:
            e = self._ov_objs.get((coll, obj))
            w = self._ov_wiped.get(coll)
            if e is not None and (w is None or e[1] >= w):
                return e[0]
            if w is not None:
                return False
        return super().exists(coll, obj)

    def collection_exists(self, coll: str) -> bool:
        with self._qcond:
            st = self._ov_colls.get(coll)
            if st is not None:
                return st[0]
        return super().collection_exists(coll)

    def read(self, coll: str, obj: GHObject, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        self._barrier(coll, obj)
        return super().read(coll, obj, offset, length)

    def stat(self, coll: str, obj: GHObject):
        self._barrier(coll, obj)
        return super().stat(coll, obj)

    def getattr(self, coll: str, obj: GHObject, name: str) -> bytes:
        # hot path: the EC write pipeline reads the hinfo and
        # object-info xattrs before every sub-write, and both are
        # setattr'd by the previous sub-write's transaction — so the
        # admission overlay almost always has the latest value and a
        # full apply barrier here would re-serialize the deferred
        # pipeline
        with self._qcond:
            dirty = self._ov_attr_dirty.get((coll, obj), -1)
            w = self._ov_wiped.get(coll)
            if w is not None and w > dirty:
                dirty = w
            hit = self._ov_attrs.get((coll, obj, name))
            if hit is not None and hit[1] > dirty:
                if hit[0] is _ATTR_DEL:
                    raise KeyError(name)
                return hit[0]
            exists_in_window = False
            if dirty < 0:
                e = self._ov_objs.get((coll, obj))
                exists_in_window = e is not None and e[0]
        if dirty >= 0:
            # identity changed (remove/clone/rename) with no newer
            # pending value: only the applied KV knows the answer
            self._barrier(coll, obj)
            return super().getattr(coll, obj, name)
        # overlay miss, identity stable: the KV value (a point-in-time
        # read under the base lock) is current — no barrier
        try:
            return super().getattr(coll, obj, name)
        except FileNotFoundError:
            if exists_in_window:
                # object created in the pending window, attr never set
                raise KeyError(name)
            raise

    def getattrs(self, coll: str, obj: GHObject) -> Dict[str, bytes]:
        self._barrier(coll, obj)
        return super().getattrs(coll, obj)

    def omap_get(self, coll: str, obj: GHObject) -> Dict[str, bytes]:
        self._barrier(coll, obj)
        return super().omap_get(coll, obj)

    def omap_get_header(self, coll: str, obj: GHObject) -> bytes:
        self._barrier(coll, obj)
        return super().omap_get_header(coll, obj)

    def omap_get_keys(self, coll: str, obj: GHObject,
                      start_after: str = "",
                      max_return: Optional[int] = None) -> List[str]:
        self._barrier(coll, obj)
        return super().omap_get_keys(coll, obj, start_after,
                                     max_return)

    def list_collections(self) -> List[str]:
        self._barrier_all()
        return super().list_collections()

    def collection_list(self, coll: str, start_after: str = "",
                        max_return: Optional[int] = None
                        ) -> List[GHObject]:
        self._barrier_all()
        return super().collection_list(coll, start_after, max_return)

    # -- introspection -------------------------------------------------
    def usage(self) -> Dict:
        if self.path:
            out = super().usage()
        else:
            with self._lock:
                buf = self._dev.getbuffer()
                dev_bytes = buf.nbytes
                buf.release()
                out = {"block_size": BLOCK,
                       "blocks_used": self._alloc.used(),
                       "bytes_used": self._alloc.used() * BLOCK,
                       "dev_bytes": dev_bytes,
                       "compress_logical_bytes":
                           self.compress_logical_bytes,
                       "compress_stored_bytes":
                           self.compress_stored_bytes,
                       "csum_failures": self.csum_failures}
        with self._qcond:
            out["deferred_pending"] = len(self._pending)
        out["wal"] = {
            "records": self.wal_records,
            "bytes": self.wal_bytes,
            "group_syncs": self.wal_group_syncs,
            "group_txns": self.wal_group_txns,
            "durable_seq": self._wal_durable_seq,
            "applied_seq": self._applied_seq,
        }
        out["apply"] = {
            "batches": self.apply_batches,
            "txns": self.apply_txns,
            "errors": self.apply_errors,
            "vectored_flushes": self.vectored_flushes,
            "vectored_blocks": self.vectored_blocks,
            "vectored_runs": self.vectored_runs,
        }
        out["csum"] = {
            "batches": self.csum_batches,
            "blocks": self.csum_blocks,
            "device_batches": self.csum_device_batches,
        }
        return out
