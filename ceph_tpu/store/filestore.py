"""Persistent directory-backed object store.

The framework's durable ObjectStore (the reference's BlueStore seat,
reference src/os/bluestore/BlueStore.cc, with FileStore's
file-per-object layout, reference src/os/filestore/): object byte data
in per-object files under the store root, metadata (existence, xattrs,
omap) in a LogDB key/value store (ceph_tpu/store/kv.py — the RocksDB
seat, as BlueStore keeps metadata in RocksDB), and a write-ahead
transaction journal in the same KV so a transaction's data-file writes
and metadata batch apply atomically across a crash (reference
FileStore's FileJournal; journal entries replay on mount).

Ordering per transaction: validate (reject invalid transactions whole,
see objectstore.check_ops) → journal the encoded transaction with
fsync → apply data-file writes and the metadata batch → fsync touched
data files and directories → durably retire the journal entry.  A
crash anywhere before retirement replays the whole transaction on the
next mount (apply is written to be replay-tolerant).  Metadata reads
during apply go through a read-your-writes view over (KV, pending
batch) so ops see earlier ops of the same transaction.

An OSD restart is resume: mount() replays any journaled-but-unretired
transactions, then collections/objects are exactly as committed
(reference SURVEY §5 checkpoint/resume).
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..utils.finisher import Finisher
from .kv import LogDB, WriteBatch
from .objectstore import (GHObject, ObjectStat, ObjectStore, Transaction,
                          check_ops, xor_into)


def _objkey(obj: GHObject) -> str:
    return f"{obj.oid.encode().hex()}_{obj.shard}"


def _unobjkey(key: str) -> GHObject:
    hexoid, shard = key.rsplit("_", 1)
    return GHObject(bytes.fromhex(hexoid).decode(), int(shard))


class _BatchView:
    """Read-your-writes view over (db, pending WriteBatch): metadata
    reads during apply see earlier ops of the same transaction."""

    def __init__(self, db: LogDB, batch: WriteBatch):
        self.db = db
        self.batch = batch

    def get(self, key: str) -> Optional[bytes]:
        val = self.db.get(key)
        for op, k, v in self.batch.ops:
            if op == "set" and k == key:
                val = v
            elif op == "rm" and k == key:
                val = None
            elif op == "rm_prefix" and key.startswith(k):
                val = None
            elif op == "rm_range" and k <= key < v.decode():
                val = None
        return val

    def iterate(self, prefix: str) -> List[Tuple[str, bytes]]:
        data = dict(self.db.iterate(prefix))
        for op, k, v in self.batch.ops:
            if op == "set":
                if k.startswith(prefix):
                    data[k] = v
            elif op == "rm":
                data.pop(k, None)
            elif op == "rm_prefix":
                for kk in [kk for kk in data if kk.startswith(k)]:
                    del data[kk]
            elif op == "rm_range":
                end = v.decode()
                for kk in [kk for kk in data if k <= kk < end]:
                    del data[kk]
        return sorted(data.items())


class _ApplyCtx:
    """Per-transaction apply state: the metadata batch, its view, and
    the data files/dirs needing fsync before journal retirement."""

    def __init__(self, db: LogDB):
        self.batch = WriteBatch()
        self.view = _BatchView(db, self.batch)
        self.dirty_files: Set[str] = set()
        self.dirty_dirs: Set[str] = set()


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class FileStore(ObjectStore):
    """Data files + LogDB metadata + journaled transactions."""

    medium = "hdd"

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        # filestore_fsync: the per-txn data fsync is the machine-crash
        # durability knob; process restarts replay the WAL either way
        self.fsync = fsync
        self._lock = threading.RLock()
        self._db: Optional[LogDB] = None
        self._finisher: Optional[Finisher] = None
        self._journal_seq = 0

    # -- paths -------------------------------------------------------------
    def _data_dir(self, coll: str) -> str:
        return os.path.join(self.path, "data", coll)

    def _data_path(self, coll: str, obj: GHObject) -> str:
        return os.path.join(self._data_dir(coll), _objkey(obj))

    # -- lifecycle ---------------------------------------------------------
    def mkfs(self) -> None:
        os.makedirs(os.path.join(self.path, "data"), exist_ok=True)
        db = LogDB(os.path.join(self.path, "meta.kv"))
        db.open()
        db.close()

    def mount(self) -> None:
        with self._lock:
            if self._db is not None:
                return
            if not os.path.exists(os.path.join(self.path, "meta.kv")):
                raise IOError(f"{self.path}: not a FileStore (run mkfs)")
            self._db = LogDB(os.path.join(self.path, "meta.kv"))
            self._db.open()
            self._finisher = Finisher("filestore-finisher")
            self._replay_journal()

    def umount(self) -> None:
        with self._lock:
            if self._db is None:
                return
            db, fin = self._db, self._finisher
            self._db = None
            self._finisher = None
        if fin:
            fin.wait_for_empty()
            fin.stop()
        db.close()

    def flush(self) -> None:
        fin = self._finisher
        if fin:
            fin.wait_for_empty()

    def _replay_journal(self) -> None:
        pending = sorted(self._db.get_prefix("J/").items())
        for key, payload in pending:
            txn = Transaction.decode(payload)
            ctx = _ApplyCtx(self._db)
            for op in txn.ops:
                self._apply_op(op, ctx, replay=True)
            self._sync_dirty(ctx)
            ctx.batch.rm(key)
            self._db.submit(ctx.batch, sync=True)
        self._journal_seq = 0

    # -- mutation ----------------------------------------------------------
    def _do_queue_transactions(self, txns: List[Transaction],
                               on_commit: Optional[Callable[[], None]] = None
                               ) -> None:
        with self._lock:
            if self._db is None:
                raise RuntimeError("store not mounted")
            merged = Transaction()
            for txn in txns:
                merged.ops.extend(txn.ops)
            # 1. validate: nothing durable happens for an invalid txn
            check_ops(merged.ops,
                      lambda c: self._db.get(f"C/{c}") is not None,
                      lambda c, o: self._db.get(
                          self._exists_key(c, o)) is not None)
            # 2. journal (WAL): the whole txn durable before any apply;
            #    on an I/O failure past this point the entry stays and
            #    replays on the next mount.  Append and fsync are
            #    stamped separately so the ledger splits WAL write
            #    cost from WAL durability cost.
            self._journal_seq += 1
            jkey = f"J/{self._journal_seq:016d}"
            record = merged.encode()
            self._txn_meta("journal_bytes", len(record))
            self._db.submit(WriteBatch().set(jkey, record))
            self._stamp_txn("journal_append")
            self._db.sync()
            self._stamp_txn("journal_fsync")
            # 3. apply data-file writes + metadata batch
            ctx = _ApplyCtx(self._db)
            for op in merged.ops:
                self._apply_op(op, ctx)
            # 4. data durable before the journal entry is retired
            self._sync_dirty(ctx)
            self._stamp_txn("data_write")
            ctx.batch.rm(jkey)
            self._db.submit(ctx.batch, sync=True)
            self._stamp_txn("kv_commit")
            fin = self._finisher
        for txn in txns:
            for fn in txn.on_applied:
                fn()
        self._stamp_txn("flush")
        callbacks = [fn for txn in txns for fn in txn.on_commit]
        if on_commit is not None:
            callbacks.append(on_commit)
        assert fin is not None
        for fn in callbacks:
            fin.queue(fn)

    def _sync_dirty(self, ctx: _ApplyCtx) -> None:
        if not self.fsync:
            return
        for path in ctx.dirty_files:
            if os.path.exists(path):
                _fsync_path(path)
        for path in ctx.dirty_dirs:
            if os.path.isdir(path):
                _fsync_path(path)

    def _exists_key(self, coll: str, obj: GHObject) -> str:
        return f"E/{coll}/{_objkey(obj)}"

    def _require_coll_view(self, coll: str, ctx: _ApplyCtx) -> None:
        if ctx.view.get(f"C/{coll}") is None:
            raise FileNotFoundError(f"no collection {coll!r}")

    def _ensure_obj(self, coll: str, obj: GHObject,
                    ctx: _ApplyCtx) -> str:
        """Mark existence; return the data file path."""
        self._require_coll_view(coll, ctx)
        ctx.batch.set(self._exists_key(coll, obj), b"")
        path = self._data_path(coll, obj)
        ctx.dirty_files.add(path)
        ctx.dirty_dirs.add(self._data_dir(coll))
        return path

    def _apply_op(self, op, ctx: _ApplyCtx, replay: bool = False) -> None:
        """Apply one op: file I/O immediately, metadata into the batch.
        replay=True tolerates missing sources (the op may have fully or
        partially applied before the crash)."""
        try:
            self._apply_op_inner(op[0], op, ctx)
        except FileNotFoundError:
            if not replay:
                raise

    def _apply_op_inner(self, name, op, ctx: _ApplyCtx) -> None:
        if name == "touch":
            _, coll, obj = op
            path = self._ensure_obj(coll, obj, ctx)
            if not os.path.exists(path):
                open(path, "wb").close()
        elif name == "write":
            _, coll, obj, offset, data = op
            path = self._ensure_obj(coll, obj, ctx)
            with open(path, "ab" if not os.path.exists(path) else "r+b") \
                    as fh:
                size = fh.seek(0, 2)
                if size < offset:
                    fh.write(b"\x00" * (offset - size))
                fh.seek(offset)
                fh.write(data)
        elif name == "xor_write":
            _, coll, obj, offset, data = op
            path = self._ensure_obj(coll, obj, ctx)
            if not os.path.exists(path):
                open(path, "wb").close()
            with open(path, "r+b") as fh:
                size = fh.seek(0, 2)
                end = offset + len(data)
                if size < end:
                    fh.write(b"\x00" * (end - size))
                fh.seek(offset)
                cur = bytearray(fh.read(len(data)))
                xor_into(cur, 0, data)
                fh.seek(offset)
                fh.write(cur)
        elif name == "zero":
            _, coll, obj, offset, length = op
            self._apply_op_inner(
                "write", ("write", coll, obj, offset, b"\x00" * length),
                ctx)
        elif name == "truncate":
            _, coll, obj, size = op
            path = self._ensure_obj(coll, obj, ctx)
            if not os.path.exists(path):
                open(path, "wb").close()
            with open(path, "r+b") as fh:
                cur = fh.seek(0, 2)
                if cur < size:
                    fh.write(b"\x00" * (size - cur))
                else:
                    fh.truncate(size)
        elif name == "remove":
            _, coll, obj = op
            self._require_coll_view(coll, ctx)
            k = _objkey(obj)
            ctx.batch.rm(self._exists_key(coll, obj))
            ctx.batch.rm(f"H/{coll}/{k}")
            ctx.batch.rm_prefix(f"A/{coll}/{k}/")
            ctx.batch.rm_prefix(f"M/{coll}/{k}/")
            try:
                os.unlink(self._data_path(coll, obj))
                ctx.dirty_dirs.add(self._data_dir(coll))
            except FileNotFoundError:
                pass
        elif name == "clone":
            _, coll, src, dst = op
            self._require_coll_view(coll, ctx)
            if ctx.view.get(self._exists_key(coll, src)) is None:
                raise FileNotFoundError(f"no object {src} in {coll!r}")
            sk, dk = _objkey(src), _objkey(dst)
            ctx.batch.set(self._exists_key(coll, dst), b"")
            for pfx in ("A", "M"):
                src_pfx = f"{pfx}/{coll}/{sk}/"
                ctx.batch.rm_prefix(f"{pfx}/{coll}/{dk}/")
                for k, v in ctx.view.iterate(src_pfx):
                    ctx.batch.set(
                        f"{pfx}/{coll}/{dk}/{k[len(src_pfx):]}", v)
            ctx.batch.rm(f"H/{coll}/{dk}")    # dst replaced wholesale
            hdr = ctx.view.get(f"H/{coll}/{sk}")
            if hdr is not None:
                ctx.batch.set(f"H/{coll}/{dk}", hdr)
            spath = self._data_path(coll, src)
            data = b""
            if os.path.exists(spath):
                with open(spath, "rb") as fh:
                    data = fh.read()
            dpath = self._data_path(coll, dst)
            with open(dpath, "wb") as fh:
                fh.write(data)
            ctx.dirty_files.add(dpath)
            ctx.dirty_dirs.add(self._data_dir(coll))
        elif name == "setattr":
            _, coll, obj, attr, value = op
            self._ensure_obj(coll, obj, ctx)
            ctx.batch.set(f"A/{coll}/{_objkey(obj)}/{attr}", value)
        elif name == "rmattr":
            _, coll, obj, attr = op
            ctx.batch.rm(f"A/{coll}/{_objkey(obj)}/{attr}")
        elif name == "omap_setkeys":
            _, coll, obj, kvs = op
            self._ensure_obj(coll, obj, ctx)
            for k, v in kvs.items():
                ctx.batch.set(f"M/{coll}/{_objkey(obj)}/{k}", v)
        elif name == "omap_rmkeys":
            _, coll, obj, keys = op
            for k in keys:
                ctx.batch.rm(f"M/{coll}/{_objkey(obj)}/{k}")
        elif name == "omap_clear":
            _, coll, obj = op
            ctx.batch.rm_prefix(f"M/{coll}/{_objkey(obj)}/")
        elif name == "omap_setheader":
            _, coll, obj, header = op
            self._ensure_obj(coll, obj, ctx)
            ctx.batch.set(f"H/{coll}/{_objkey(obj)}", header)
        elif name == "mkcoll":
            _, coll = op
            ctx.batch.set(f"C/{coll}", b"")
            os.makedirs(self._data_dir(coll), exist_ok=True)
            ctx.dirty_dirs.add(self._data_dir(coll))
            ctx.dirty_dirs.add(os.path.join(self.path, "data"))
        elif name == "rmcoll":
            _, coll = op
            ctx.batch.rm(f"C/{coll}")
            for pfx in ("E", "H", "A", "M"):
                ctx.batch.rm_prefix(f"{pfx}/{coll}/")
            ddir = self._data_dir(coll)
            if os.path.isdir(ddir):
                for f in os.listdir(ddir):
                    os.unlink(os.path.join(ddir, f))
                os.rmdir(ddir)
                ctx.dirty_dirs.add(os.path.join(self.path, "data"))
        elif name == "coll_move_rename":
            _, src_coll, src, dst_coll, dst = op
            self._require_coll_view(dst_coll, ctx)
            if ctx.view.get(self._exists_key(src_coll, src)) is None:
                raise FileNotFoundError(
                    f"no object {src} in {src_coll!r}")
            sk, dk = _objkey(src), _objkey(dst)
            # dst is replaced wholesale, as MemStore's dict assignment does
            for pfx in ("A", "M"):
                ctx.batch.rm_prefix(f"{pfx}/{dst_coll}/{dk}/")
                src_pfx = f"{pfx}/{src_coll}/{sk}/"
                for k, v in ctx.view.iterate(src_pfx):
                    ctx.batch.set(
                        f"{pfx}/{dst_coll}/{dk}/{k[len(src_pfx):]}", v)
                ctx.batch.rm_prefix(src_pfx)
            ctx.batch.rm(f"H/{dst_coll}/{dk}")
            hdr = ctx.view.get(f"H/{src_coll}/{sk}")
            if hdr is not None:
                ctx.batch.set(f"H/{dst_coll}/{dk}", hdr)
                ctx.batch.rm(f"H/{src_coll}/{sk}")
            ctx.batch.rm(self._exists_key(src_coll, src))
            ctx.batch.set(self._exists_key(dst_coll, dst), b"")
            spath = self._data_path(src_coll, src)
            dpath = self._data_path(dst_coll, dst)
            if os.path.exists(spath):
                os.replace(spath, dpath)
                ctx.dirty_files.add(dpath)
                ctx.dirty_dirs.add(self._data_dir(src_coll))
                ctx.dirty_dirs.add(self._data_dir(dst_coll))
            elif os.path.exists(dpath):
                os.unlink(dpath)      # data-less src: drop dst's old data
                ctx.dirty_dirs.add(self._data_dir(dst_coll))
        else:
            raise ValueError(f"unknown op {name!r}")

    # -- reads -------------------------------------------------------------
    def _check_obj(self, coll: str, obj: GHObject) -> None:
        if self._db is None:
            raise RuntimeError("store not mounted")
        if self._db.get(f"C/{coll}") is None:
            raise FileNotFoundError(f"no collection {coll!r}")
        if self._db.get(self._exists_key(coll, obj)) is None:
            raise FileNotFoundError(f"no object {obj} in {coll!r}")

    def read(self, coll: str, obj: GHObject, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        with self._lock:
            self._check_obj(coll, obj)
            path = self._data_path(coll, obj)
            if not os.path.exists(path):
                return b""
            with open(path, "rb") as fh:
                # clamp to EOF: callers pass huge sentinels for
                # "whole object" and fh.read preallocates the buffer
                size = os.fstat(fh.fileno()).st_size
                if length is None or offset + length > size:
                    length = max(0, size - offset)
                fh.seek(offset)
                return fh.read(length)

    def stat(self, coll: str, obj: GHObject) -> ObjectStat:
        with self._lock:
            self._check_obj(coll, obj)
            path = self._data_path(coll, obj)
            size = os.path.getsize(path) if os.path.exists(path) else 0
            return ObjectStat(size=size)

    def exists(self, coll: str, obj: GHObject) -> bool:
        with self._lock:
            if self._db is None:
                raise RuntimeError("store not mounted")
            return self._db.get(self._exists_key(coll, obj)) is not None

    def getattr(self, coll: str, obj: GHObject, name: str) -> bytes:
        with self._lock:
            self._check_obj(coll, obj)
            v = self._db.get(f"A/{coll}/{_objkey(obj)}/{name}")
            if v is None:
                raise KeyError(name)
            return v

    def getattrs(self, coll: str, obj: GHObject) -> Dict[str, bytes]:
        with self._lock:
            self._check_obj(coll, obj)
            pfx = f"A/{coll}/{_objkey(obj)}/"
            return {k[len(pfx):]: v for k, v in self._db.iterate(pfx)}

    def omap_get(self, coll: str, obj: GHObject) -> Dict[str, bytes]:
        with self._lock:
            self._check_obj(coll, obj)
            pfx = f"M/{coll}/{_objkey(obj)}/"
            return {k[len(pfx):]: v for k, v in self._db.iterate(pfx)}

    def omap_get_header(self, coll: str, obj: GHObject) -> bytes:
        with self._lock:
            self._check_obj(coll, obj)
            return self._db.get(f"H/{coll}/{_objkey(obj)}") or b""

    def omap_get_keys(self, coll: str, obj: GHObject,
                      start_after: str = "",
                      max_return: Optional[int] = None) -> List[str]:
        with self._lock:
            self._check_obj(coll, obj)
            pfx = f"M/{coll}/{_objkey(obj)}/"
            keys = [k[len(pfx):] for k, _ in self._db.iterate(pfx)
                    if k[len(pfx):] > start_after]
        return keys if max_return is None else keys[:max_return]

    # -- collections -------------------------------------------------------
    def list_collections(self) -> List[str]:
        with self._lock:
            if self._db is None:
                raise RuntimeError("store not mounted")
            return [k[2:] for k, _ in self._db.iterate("C/")]

    def collection_exists(self, coll: str) -> bool:
        with self._lock:
            if self._db is None:
                raise RuntimeError("store not mounted")
            return self._db.get(f"C/{coll}") is not None

    def collection_list(self, coll: str, start_after: str = "",
                        max_return: Optional[int] = None
                        ) -> List[GHObject]:
        with self._lock:
            if self._db is None:
                raise RuntimeError("store not mounted")
            if self._db.get(f"C/{coll}") is None:
                raise FileNotFoundError(f"no collection {coll!r}")
            pfx = f"E/{coll}/"
            objs = sorted((_unobjkey(k[len(pfx):])
                           for k, _ in self._db.iterate(pfx)),
                          key=lambda o: (o.oid, o.shard))
            objs = [o for o in objs if o.oid > start_after]
        return objs if max_return is None else objs[:max_return]
