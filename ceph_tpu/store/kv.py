"""Key/value store abstraction.

Python-native equivalent of the reference's KeyValueDB seam (reference
src/kv/KeyValueDB.h with RocksDB/LevelDB/MemDB backends): sorted
string keys with bytes values, atomic write batches, prefix-range
iteration.  Backends here: MemDB (dict) and LogDB (single append-only
record log with replay-on-open and size-triggered compaction — the
framework's stand-in for the vendored RocksDB submodule, reference
.gitmodules rocksdb).  Used by FileStore for object metadata and by
the monitor's MonitorDBStore equivalent (reference
mon/MonitorDBStore.h:37).
"""
from __future__ import annotations

import abc
import os
import struct
import threading
from typing import Dict, Iterator, List, Optional, Tuple


class WriteBatch:
    """Atomic batch of sets/deletes (reference KeyValueDB::Transaction)."""

    def __init__(self) -> None:
        self.ops: List[Tuple[str, str, bytes]] = []  # (op, key, value)

    def set(self, key: str, value: bytes) -> "WriteBatch":
        self.ops.append(("set", key, bytes(value))); return self

    def rm(self, key: str) -> "WriteBatch":
        self.ops.append(("rm", key, b"")); return self

    def rm_range(self, start: str, end: str) -> "WriteBatch":
        """Delete keys in [start, end) (reference rm_range_keys)."""
        self.ops.append(("rm_range", start, end.encode())); return self

    def rm_prefix(self, prefix: str) -> "WriteBatch":
        """Delete every key starting with ``prefix`` (reference
        rmkeys_by_prefix) — unlike rm_range there is no upper-bound
        sentinel to outgrow, so non-ASCII key tails are covered."""
        self.ops.append(("rm_prefix", prefix, b"")); return self


class KeyValueDB(abc.ABC):
    @abc.abstractmethod
    def open(self) -> None: ...

    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def submit(self, batch: WriteBatch, sync: bool = False) -> None:
        """Apply atomically; sync=True durably (reference
        submit_transaction[_sync])."""

    @abc.abstractmethod
    def get(self, key: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    def iterate(self, prefix: str = "",
                start: str = "") -> Iterator[Tuple[str, bytes]]:
        """Sorted iteration over keys with the given prefix, starting at
        ``start`` (inclusive) if given."""

    def sync(self) -> None:
        """Make previously submitted records durable (reference
        KeyValueDB::submit_transaction_sync's fsync half).  Splitting
        append from fsync lets the store ledger charge WAL write and
        WAL durability as separate phases.  Default: no-op (MemDB has
        no durability to wait for)."""

    def get_prefix(self, prefix: str) -> Dict[str, bytes]:
        return dict(self.iterate(prefix))


def _snapshot_iterate(data: Dict[str, bytes], prefix: str,
                      start: str) -> Iterator[Tuple[str, bytes]]:
    """Sorted snapshot of the matching keys (caller holds the lock)."""
    keys = sorted(k for k in data if k.startswith(prefix) and k >= start)
    return iter([(k, data[k]) for k in keys])


class MemDB(KeyValueDB):
    """Dict-backed (reference kv/MemDB.cc)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._data: Dict[str, bytes] = {}

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass

    def submit(self, batch: WriteBatch, sync: bool = False) -> None:
        with self._lock:
            _apply_batch(self._data, batch)

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def iterate(self, prefix: str = "",
                start: str = "") -> Iterator[Tuple[str, bytes]]:
        with self._lock:
            return _snapshot_iterate(self._data, prefix, start)


def _apply_batch(data: Dict[str, bytes], batch: WriteBatch) -> None:
    for op, key, value in batch.ops:
        if op == "set":
            data[key] = value
        elif op == "rm":
            data.pop(key, None)
        elif op == "rm_range":
            end = value.decode()
            for k in [k for k in data if key <= k < end]:
                del data[k]
        elif op == "rm_prefix":
            for k in [k for k in data if k.startswith(key)]:
                del data[k]


class LogDB(KeyValueDB):
    """Append-only record log with in-memory index.

    Record framing: u32 length + payload, payload = batch of
    (op u8, key, value) entries; a torn tail record is discarded on
    replay (crash atomicity).  Compacts by rewriting the live set when
    the log exceeds ``compact_factor`` times the live size.
    """

    MAGIC = b"CTKV0001"

    def __init__(self, path: str, compact_factor: int = 4):
        self.path = path
        self.compact_factor = compact_factor
        self._lock = threading.RLock()
        self._data: Dict[str, bytes] = {}
        self._fh = None
        self._log_bytes = 0
        # next log size at which to run the O(keys) live-size scan, so
        # submits stay O(batch) between checks
        self._compact_check_at = 8192

    # -- framing -----------------------------------------------------------
    @staticmethod
    def _encode_batch(batch: WriteBatch) -> bytes:
        parts = [struct.pack("<I", len(batch.ops))]
        for op, key, value in batch.ops:
            kb = key.encode()
            code = {"set": 0, "rm": 1, "rm_range": 2, "rm_prefix": 3}[op]
            parts.append(struct.pack("<BI", code, len(kb)))
            parts.append(kb)
            parts.append(struct.pack("<I", len(value)))
            parts.append(value)
        payload = b"".join(parts)
        return struct.pack("<I", len(payload)) + payload

    @staticmethod
    def _decode_batch(payload: bytes) -> WriteBatch:
        batch = WriteBatch()
        pos = 4
        (count,) = struct.unpack_from("<I", payload, 0)
        for _ in range(count):
            code, klen = struct.unpack_from("<BI", payload, pos)
            pos += 5
            key = payload[pos:pos + klen].decode()
            pos += klen
            (vlen,) = struct.unpack_from("<I", payload, pos)
            pos += 4
            value = payload[pos:pos + vlen]
            pos += vlen
            op = {0: "set", 1: "rm", 2: "rm_range", 3: "rm_prefix"}[code]
            batch.ops.append((op, key, bytes(value)))
        return batch

    # -- lifecycle ---------------------------------------------------------
    def open(self) -> None:
        with self._lock:
            if self._fh is not None:
                return
            exists = os.path.exists(self.path)
            if exists:
                self._replay()
            self._fh = open(self.path, "ab")
            if not exists:
                self._fh.write(self.MAGIC)
                self._fh.flush()
                self._log_bytes = len(self.MAGIC)

    def _replay(self) -> None:
        self._data = {}
        with open(self.path, "rb") as fh:
            magic = fh.read(len(self.MAGIC))
            if len(magic) < len(self.MAGIC) \
                    and self.MAGIC.startswith(magic):
                # crash between file creation and the magic flush on the
                # very first open: an empty/torn-magic log is a fresh log
                with open(self.path, "wb") as wfh:
                    wfh.write(self.MAGIC)
                    wfh.flush()
                    os.fsync(wfh.fileno())
                self._log_bytes = len(self.MAGIC)
                return
            if magic != self.MAGIC:
                raise IOError(f"{self.path}: bad magic")
            good_end = fh.tell()
            while True:
                hdr = fh.read(4)
                if len(hdr) < 4:
                    break
                (length,) = struct.unpack("<I", hdr)
                payload = fh.read(length)
                if len(payload) < length:
                    break               # torn tail record: discard
                _apply_batch(self._data, self._decode_batch(payload))
                good_end = fh.tell()
        self._log_bytes = good_end
        if good_end < os.path.getsize(self.path):
            with open(self.path, "r+b") as fh:
                fh.truncate(good_end)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._fh.close()
                self._fh = None

    # -- access ------------------------------------------------------------
    def submit(self, batch: WriteBatch, sync: bool = False) -> None:
        with self._lock:
            if self._fh is None:
                raise RuntimeError("LogDB not open")
            record = self._encode_batch(batch)
            self._fh.write(record)
            self._fh.flush()
            if sync:
                os.fsync(self._fh.fileno())
            self._log_bytes += len(record)
            _apply_batch(self._data, batch)
            self._maybe_compact()

    def sync(self) -> None:
        with self._lock:
            if self._fh is None:
                raise RuntimeError("LogDB not open")
            os.fsync(self._fh.fileno())

    def _live_bytes(self) -> int:
        return sum(len(k) + len(v) + 13 for k, v in self._data.items())

    def compact(self) -> None:
        """Force a rewrite-to-live compaction now (reference
        KeyValueDB::compact; consumed by mon_compact_on_start).
        Logs already near their live size (< 4 KiB of slack) skip."""
        with self._lock:
            save_at, save_f = self._compact_check_at, self.compact_factor
            self._compact_check_at, self.compact_factor = 0, 0
            try:
                self._maybe_compact()
            finally:
                self._compact_check_at = max(save_at, self._log_bytes)
                self.compact_factor = save_f

    def _maybe_compact(self) -> None:
        if self._log_bytes < self._compact_check_at:
            return
        live = self._live_bytes() + len(self.MAGIC)
        if self._log_bytes <= max(4096, live * self.compact_factor):
            # not worth compacting yet; defer the next scan until the
            # log has grown enough to possibly cross the threshold
            self._compact_check_at = max(
                self._log_bytes * 2, live * self.compact_factor)
            return
        tmp = self.path + ".compact"
        batch = WriteBatch()
        for k in sorted(self._data):
            batch.set(k, self._data[k])
        with open(tmp, "wb") as fh:
            fh.write(self.MAGIC)
            fh.write(self._encode_batch(batch))
            fh.flush()
            os.fsync(fh.fileno())
        self._fh.close()
        os.replace(tmp, self.path)
        self._fh = open(self.path, "ab")
        self._log_bytes = os.path.getsize(self.path)
        self._compact_check_at = max(8192, self._log_bytes * 2)

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def iterate(self, prefix: str = "",
                start: str = "") -> Iterator[Tuple[str, bytes]]:
        with self._lock:
            return _snapshot_iterate(self._data, prefix, start)
