"""In-RAM transactional object store.

Python-native equivalent of the reference's MemStore test double
(reference src/os/memstore/MemStore.cc, ~1.8k LoC): the full
ObjectStore contract with no persistence, used to run OSD logic
without disks (reference src/test/objectstore/store_test.cc runs the
common store suite over it).  Mutations apply synchronously under the
store lock; on_commit callbacks are delivered from a Finisher thread
to preserve the asynchronous commit contract the OSD relies on
(reference MemStore::queue_transactions → finisher.queue).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from ..utils.finisher import Finisher
from .objectstore import (GHObject, ObjectStat, ObjectStore, Transaction,
                          check_ops, xor_into)


class _Object:
    __slots__ = ("data", "xattrs", "omap", "omap_header")

    def __init__(self) -> None:
        self.data = bytearray()
        self.xattrs: Dict[str, bytes] = {}
        self.omap: Dict[str, bytes] = {}
        self.omap_header = b""

    def clone(self) -> "_Object":
        o = _Object()
        o.data = bytearray(self.data)
        o.xattrs = dict(self.xattrs)
        o.omap = dict(self.omap)
        o.omap_header = self.omap_header
        return o


class MemStore(ObjectStore):
    def __init__(self, path: str = "", max_bytes: int = 0) -> None:
        self.path = path          # unused; kept for ObjectStore symmetry
        self.max_bytes = max_bytes   # reference memstore_device_bytes;
        self._data_bytes = 0         # 0 = unlimited
        self._lock = threading.RLock()
        self._colls: Dict[str, Dict[GHObject, _Object]] = {}
        self._finisher: Optional[Finisher] = None
        self._mounted = False

    def _grow(self, delta: int) -> None:
        if delta > 0 and self.max_bytes and \
                self._data_bytes + delta > self.max_bytes:
            raise OSError(28, "memstore full")   # ENOSPC
        self._data_bytes += delta

    # -- lifecycle ---------------------------------------------------------
    def mkfs(self) -> None:
        with self._lock:
            self._colls = {}

    def mount(self) -> None:
        with self._lock:
            if self._mounted:
                return
            self._finisher = Finisher("memstore-finisher")
            self._mounted = True

    def umount(self) -> None:
        with self._lock:
            if not self._mounted:
                return
            self._mounted = False
            fin = self._finisher
            self._finisher = None
        if fin:
            fin.wait_for_empty()
            fin.stop()

    def flush(self) -> None:
        """Drain pending commit callbacks (reference store flush)."""
        fin = self._finisher
        if fin:
            fin.wait_for_empty()

    # -- mutation ----------------------------------------------------------
    def _do_queue_transactions(self, txns: List[Transaction],
                               on_commit: Optional[Callable[[], None]] = None
                               ) -> None:
        with self._lock:
            if not self._mounted:
                raise RuntimeError("store not mounted")
            # reject invalid transactions whole before mutating anything
            check_ops(
                [op for txn in txns for op in txn.ops],
                lambda c: c in self._colls,
                lambda c, o: c in self._colls and o in self._colls[c])
            for txn in txns:
                for op in txn.ops:
                    self._apply_op(op)
            # no journal, no KV: those ledger phases never stamp and
            # fold to zero-width — the whole apply charges here
            self._stamp_txn("data_write")
            fin = self._finisher
        for txn in txns:
            for fn in txn.on_applied:
                fn()
        self._stamp_txn("flush")
        callbacks = [fn for txn in txns for fn in txn.on_commit]
        if on_commit is not None:
            callbacks.append(on_commit)
        assert fin is not None
        for fn in callbacks:
            fin.queue(fn)

    def _coll(self, coll: str) -> Dict[GHObject, _Object]:
        try:
            return self._colls[coll]
        except KeyError:
            raise FileNotFoundError(f"no collection {coll!r}")

    def _obj(self, coll: str, obj: GHObject,
             create: bool = False) -> _Object:
        c = self._coll(coll)
        if obj not in c:
            if not create:
                raise FileNotFoundError(f"no object {obj} in {coll!r}")
            c[obj] = _Object()
        return c[obj]

    def _apply_op(self, op) -> None:
        name = op[0]
        if name == "touch":
            self._obj(op[1], op[2], create=True)
        elif name == "write":
            _, coll, obj, offset, data = op
            o = self._obj(coll, obj, create=True)
            end = offset + len(data)
            if len(o.data) < end:
                self._grow(end - len(o.data))
                o.data.extend(b"\x00" * (end - len(o.data)))
            o.data[offset:end] = data
        elif name == "xor_write":
            _, coll, obj, offset, data = op
            o = self._obj(coll, obj, create=True)
            end = offset + len(data)
            if len(o.data) < end:
                self._grow(end - len(o.data))
                o.data.extend(b"\x00" * (end - len(o.data)))
            xor_into(o.data, offset, data)
        elif name == "zero":
            _, coll, obj, offset, length = op
            o = self._obj(coll, obj, create=True)
            end = offset + length
            if len(o.data) < end:
                self._grow(end - len(o.data))
                o.data.extend(b"\x00" * (end - len(o.data)))
            o.data[offset:end] = b"\x00" * length
        elif name == "truncate":
            _, coll, obj, size = op
            o = self._obj(coll, obj, create=True)
            if len(o.data) > size:
                self._grow(size - len(o.data))
                del o.data[size:]
            else:
                self._grow(size - len(o.data))
                o.data.extend(b"\x00" * (size - len(o.data)))
        elif name == "remove":
            _, coll, obj = op
            gone = self._coll(coll).pop(obj, None)
            if gone is not None:
                self._data_bytes -= len(gone.data)
        elif name == "clone":
            _, coll, src, dst = op
            prev = self._coll(coll).get(dst)
            src_o = self._obj(coll, src)
            self._grow(len(src_o.data)
                       - (len(prev.data) if prev else 0))
            self._coll(coll)[dst] = src_o.clone()
        elif name == "setattr":
            _, coll, obj, attr, value = op
            self._obj(coll, obj, create=True).xattrs[attr] = value
        elif name == "rmattr":
            _, coll, obj, attr = op
            self._obj(coll, obj).xattrs.pop(attr, None)
        elif name == "omap_setkeys":
            _, coll, obj, kvs = op
            self._obj(coll, obj, create=True).omap.update(kvs)
        elif name == "omap_rmkeys":
            _, coll, obj, keys = op
            o = self._obj(coll, obj)
            for k in keys:
                o.omap.pop(k, None)
        elif name == "omap_clear":
            _, coll, obj = op
            self._obj(coll, obj).omap.clear()
        elif name == "omap_setheader":
            _, coll, obj, header = op
            self._obj(coll, obj, create=True).omap_header = header
        elif name == "mkcoll":
            self._colls.setdefault(op[1], {})
        elif name == "rmcoll":
            dropped = self._colls.pop(op[1], None)
            if dropped:
                self._data_bytes -= sum(len(o.data)
                                        for o in dropped.values())
        elif name == "coll_move_rename":
            _, src_coll, src, dst_coll, dst = op
            o = self._coll(src_coll).pop(src)
            self._coll(dst_coll)[dst] = o
        else:
            raise ValueError(f"unknown op {name!r}")

    # -- reads -------------------------------------------------------------
    def read(self, coll: str, obj: GHObject, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        with self._lock:
            o = self._obj(coll, obj)
            if length is None:
                return bytes(o.data[offset:])
            return bytes(o.data[offset:offset + length])

    def stat(self, coll: str, obj: GHObject) -> ObjectStat:
        with self._lock:
            return ObjectStat(size=len(self._obj(coll, obj).data))

    def exists(self, coll: str, obj: GHObject) -> bool:
        with self._lock:
            return coll in self._colls and obj in self._colls[coll]

    def getattr(self, coll: str, obj: GHObject, name: str) -> bytes:
        with self._lock:
            attrs = self._obj(coll, obj).xattrs
            if name not in attrs:
                raise KeyError(name)
            return attrs[name]

    def getattrs(self, coll: str, obj: GHObject) -> Dict[str, bytes]:
        with self._lock:
            return dict(self._obj(coll, obj).xattrs)

    def omap_get(self, coll: str, obj: GHObject) -> Dict[str, bytes]:
        with self._lock:
            return dict(self._obj(coll, obj).omap)

    def omap_get_header(self, coll: str, obj: GHObject) -> bytes:
        with self._lock:
            return self._obj(coll, obj).omap_header

    def omap_get_keys(self, coll: str, obj: GHObject,
                      start_after: str = "",
                      max_return: Optional[int] = None) -> List[str]:
        with self._lock:
            keys = sorted(k for k in self._obj(coll, obj).omap
                          if k > start_after)
        return keys if max_return is None else keys[:max_return]

    # -- collections -------------------------------------------------------
    def list_collections(self) -> List[str]:
        with self._lock:
            return sorted(self._colls)

    def collection_exists(self, coll: str) -> bool:
        with self._lock:
            return coll in self._colls

    def collection_list(self, coll: str, start_after: str = "",
                        max_return: Optional[int] = None
                        ) -> List[GHObject]:
        with self._lock:
            objs = sorted(o for o in self._coll(coll)
                          if o.oid > start_after)
        return objs if max_return is None else objs[:max_return]
