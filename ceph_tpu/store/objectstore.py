"""Transactional local object store interface.

Python-native equivalent of the reference's ObjectStore seam (reference
src/os/ObjectStore.h): named collections (one per PG shard) holding
objects with byte data, xattrs and an omap (sorted key/value map);
all mutations expressed as ordered op lists inside a ``Transaction``
applied atomically by ``queue_transactions`` (reference
os/ObjectStore.h:222), with on_applied / on_commit completion
callbacks registered on the transaction itself (reference
Transaction::register_on_applied / register_on_commit).

Transactions are encodable (ceph_tpu.utils.encoding) because the EC
write path ships them shard-to-shard inside ECSubWrite messages, as
the reference does (reference osd/ECMsgTypes.h ECSubWrite::t).

Implementations: MemStore (ceph_tpu/store/memstore.py, the reference's
test double os/memstore/MemStore.cc) and FileStore
(ceph_tpu/store/filestore.py, persistent directory-backed).
"""
from __future__ import annotations

import abc
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ..utils import faults as faultlib
from ..utils import store_ledger
from ..utils.encoding import Decoder, Encoder

#: thread-local current store-transaction ledger: backends stamp
#: phases through _stamp_txn without any signature change to
#: _do_queue_transactions (apply runs synchronously on the queueing
#: thread in every backend, so thread-local is exact)
_TXN_TLS = threading.local()

# Collection ids are strings: str(SPGid) for PG collections, "meta" for
# the OSD's bookkeeping collection (reference coll_t, osd/osd_types.h).
COLL_META = "meta"


def xor_into(buf: bytearray, offset: int, data) -> None:
    """XOR ``data`` into ``buf[offset:offset+len(data)]`` in place.
    Caller guarantees the region exists.  Wide-int XOR: CPython
    bignum ^ runs word-at-a-time, ~100x a Python byte loop on
    chunk-sized parity deltas."""
    n = len(data)
    end = offset + n
    a = int.from_bytes(buf[offset:end], "little")
    b = int.from_bytes(data, "little")
    buf[offset:end] = (a ^ b).to_bytes(n, "little")


@dataclass(frozen=True, order=True)
class GHObject:
    """Store-level object identity (reference ghobject_t): object name
    plus the EC shard the local copy holds (-1 = whole object /
    replicated, reference shard_id_t::NO_SHARD)."""
    oid: str
    shard: int = -1

    def __str__(self) -> str:
        return self.oid if self.shard < 0 else f"{self.oid}(s{self.shard})"


class Transaction:
    """Ordered mutation list (reference ObjectStore::Transaction).

    Ops are (name, args...) tuples; the op vocabulary is the subset of
    the reference's Transaction::Op codes the OSD data path uses
    (reference os/ObjectStore.h enum: OP_TOUCH..OP_COLL_MOVE_RENAME).
    """

    def __init__(self) -> None:
        self.ops: List[Tuple] = []
        self.on_applied: List[Callable[[], None]] = []
        self.on_commit: List[Callable[[], None]] = []

    def empty(self) -> bool:
        return not self.ops

    # -- completion hooks (reference register_on_applied/:commit) ---------
    def register_on_applied(self, fn: Callable[[], None]) -> None:
        self.on_applied.append(fn)

    def register_on_commit(self, fn: Callable[[], None]) -> None:
        self.on_commit.append(fn)

    def append(self, other: "Transaction") -> None:
        self.ops.extend(other.ops)
        self.on_applied.extend(other.on_applied)
        self.on_commit.extend(other.on_commit)

    # -- object data ops ---------------------------------------------------
    def touch(self, coll: str, obj: GHObject) -> "Transaction":
        self.ops.append(("touch", coll, obj)); return self

    def write(self, coll: str, obj: GHObject, offset: int,
              data: bytes) -> "Transaction":
        # bytes/memoryview/uint8-ndarray payloads ride BY REFERENCE
        # (the EC write path hands over encoded shard views; copying
        # here would undo the zero-copy data path).  Ownership
        # transfers: the caller must not mutate the buffer after
        # queueing.  Mutable bytearrays still snapshot.
        if isinstance(data, bytearray):
            data = bytes(data)  # copycheck: ok - snapshot of a caller-mutable buffer
        self.ops.append(("write", coll, obj, offset, data))
        return self

    def xor_write(self, coll: str, obj: GHObject, offset: int,
                  data: bytes) -> "Transaction":
        """XOR ``data`` into the stored bytes at ``offset`` (zero-extend
        if the object is shorter): the parity-delta RMW carrier.  The
        EC primary ships Δparity = M·Δdata and each parity shard folds
        it in locally — GF(2^8) addition IS xor, so the store never
        needs codec knowledge.  Payload rides by reference like write.
        """
        if isinstance(data, bytearray):
            data = bytes(data)  # copycheck: ok - snapshot of a caller-mutable buffer
        self.ops.append(("xor_write", coll, obj, offset, data))
        return self

    def zero(self, coll: str, obj: GHObject, offset: int,
             length: int) -> "Transaction":
        self.ops.append(("zero", coll, obj, offset, length)); return self

    def truncate(self, coll: str, obj: GHObject,
                 size: int) -> "Transaction":
        self.ops.append(("truncate", coll, obj, size)); return self

    def remove(self, coll: str, obj: GHObject) -> "Transaction":
        self.ops.append(("remove", coll, obj)); return self

    def clone(self, coll: str, src: GHObject,
              dst: GHObject) -> "Transaction":
        self.ops.append(("clone", coll, src, dst)); return self

    # -- xattrs ------------------------------------------------------------
    def setattr(self, coll: str, obj: GHObject, name: str,
                value: bytes) -> "Transaction":
        self.ops.append(("setattr", coll, obj, name, bytes(value)))
        return self

    def setattrs(self, coll: str, obj: GHObject,
                 attrs: Dict[str, bytes]) -> "Transaction":
        for name in sorted(attrs):
            self.setattr(coll, obj, name, attrs[name])
        return self

    def rmattr(self, coll: str, obj: GHObject,
               name: str) -> "Transaction":
        self.ops.append(("rmattr", coll, obj, name)); return self

    # -- omap --------------------------------------------------------------
    def omap_setkeys(self, coll: str, obj: GHObject,
                     kvs: Dict[str, bytes]) -> "Transaction":
        self.ops.append(("omap_setkeys", coll, obj,
                         {k: bytes(v) for k, v in kvs.items()}))
        return self

    def omap_rmkeys(self, coll: str, obj: GHObject,
                    keys: Iterable[str]) -> "Transaction":
        self.ops.append(("omap_rmkeys", coll, obj, list(keys)))
        return self

    def omap_clear(self, coll: str, obj: GHObject) -> "Transaction":
        self.ops.append(("omap_clear", coll, obj)); return self

    def omap_setheader(self, coll: str, obj: GHObject,
                       header: bytes) -> "Transaction":
        self.ops.append(("omap_setheader", coll, obj, bytes(header)))
        return self

    # -- collections -------------------------------------------------------
    def create_collection(self, coll: str) -> "Transaction":
        self.ops.append(("mkcoll", coll)); return self

    def remove_collection(self, coll: str) -> "Transaction":
        self.ops.append(("rmcoll", coll)); return self

    def collection_move_rename(self, src_coll: str, src: GHObject,
                               dst_coll: str,
                               dst: GHObject) -> "Transaction":
        self.ops.append(("coll_move_rename", src_coll, src,
                         dst_coll, dst))
        return self

    # -- wire form (reference Transaction::encode/decode) ------------------
    _OBJ_OPS = {"touch", "remove", "omap_clear"}

    def encode(self) -> bytes:
        return Encoder().struct(1, 1, self._encode_body()).build()

    @classmethod
    def _encode_op(cls, body: Encoder, op: Tuple) -> None:
        name = op[0]
        body.str(name)
        if name in cls._OBJ_OPS:
            _, coll, obj = op
            body.str(coll).str(obj.oid).i32(obj.shard)
        elif name in ("write", "xor_write"):
            _, coll, obj, offset, data = op
            body.str(coll).str(obj.oid).i32(obj.shard)
            body.u64(offset).bytes(data)
        elif name in ("zero",):
            _, coll, obj, offset, length = op
            body.str(coll).str(obj.oid).i32(obj.shard)
            body.u64(offset).u64(length)
        elif name == "truncate":
            _, coll, obj, size = op
            body.str(coll).str(obj.oid).i32(obj.shard).u64(size)
        elif name == "clone":
            _, coll, src, dst = op
            body.str(coll).str(src.oid).i32(src.shard)
            body.str(dst.oid).i32(dst.shard)
        elif name == "setattr":
            _, coll, obj, attr, value = op
            body.str(coll).str(obj.oid).i32(obj.shard)
            body.str(attr).bytes(value)
        elif name == "rmattr":
            _, coll, obj, attr = op
            body.str(coll).str(obj.oid).i32(obj.shard).str(attr)
        elif name == "omap_setkeys":
            _, coll, obj, kvs = op
            body.str(coll).str(obj.oid).i32(obj.shard)
            body.str_bytes_map(kvs)
        elif name == "omap_rmkeys":
            _, coll, obj, keys = op
            body.str(coll).str(obj.oid).i32(obj.shard)
            body.str_list(keys)
        elif name == "omap_setheader":
            _, coll, obj, header = op
            body.str(coll).str(obj.oid).i32(obj.shard).bytes(header)
        elif name in ("mkcoll", "rmcoll"):
            _, coll = op
            body.str(coll)
        elif name == "coll_move_rename":
            _, src_coll, src, dst_coll, dst = op
            body.str(src_coll).str(src.oid).i32(src.shard)
            body.str(dst_coll).str(dst.oid).i32(dst.shard)
        else:
            raise ValueError(f"unencodable op {name!r}")

    def encode_parts(self) -> List:
        """Wire form as a fragment list: small framing fields coalesce,
        large write payloads stay as by-reference views — the messenger
        sends the list as scatter-gather iovecs without ever joining
        them (ECSubWrite's txn never round-trips through one big
        bytes)."""
        body = self._encode_body()
        return Encoder().struct(1, 1, body).build_parts()

    def _encode_body(self) -> Encoder:
        body = Encoder()
        body.u32(len(self.ops))
        for op in self.ops:
            self._encode_op(body, op)
        return body

    @classmethod
    def decode(cls, buf) -> "Transaction":
        if isinstance(buf, (list, tuple)):
            # locally-looped message carrying encode_parts() fragments
            buf = b"".join(buf)
        _, d = Decoder(buf).struct(1)
        t = cls()
        for _ in range(d.u32()):
            name = d.str()
            if name in cls._OBJ_OPS:
                t.ops.append((name, d.str(), GHObject(d.str(), d.i32())))
            elif name in ("write", "xor_write"):
                coll, obj = d.str(), GHObject(d.str(), d.i32())
                t.ops.append((name, coll, obj, d.u64(), d.bytes()))
            elif name == "zero":
                coll, obj = d.str(), GHObject(d.str(), d.i32())
                t.ops.append((name, coll, obj, d.u64(), d.u64()))
            elif name == "truncate":
                coll, obj = d.str(), GHObject(d.str(), d.i32())
                t.ops.append((name, coll, obj, d.u64()))
            elif name == "clone":
                coll, src = d.str(), GHObject(d.str(), d.i32())
                t.ops.append((name, coll, src, GHObject(d.str(), d.i32())))
            elif name == "setattr":
                coll, obj = d.str(), GHObject(d.str(), d.i32())
                t.ops.append((name, coll, obj, d.str(), d.bytes()))
            elif name == "rmattr":
                coll, obj = d.str(), GHObject(d.str(), d.i32())
                t.ops.append((name, coll, obj, d.str()))
            elif name == "omap_setkeys":
                coll, obj = d.str(), GHObject(d.str(), d.i32())
                t.ops.append((name, coll, obj, d.str_bytes_map()))
            elif name == "omap_rmkeys":
                coll, obj = d.str(), GHObject(d.str(), d.i32())
                t.ops.append((name, coll, obj, d.str_list()))
            elif name == "omap_setheader":
                coll, obj = d.str(), GHObject(d.str(), d.i32())
                t.ops.append((name, coll, obj, d.bytes()))
            elif name in ("mkcoll", "rmcoll"):
                t.ops.append((name, d.str()))
            elif name == "coll_move_rename":
                src_coll, src = d.str(), GHObject(d.str(), d.i32())
                t.ops.append((name, src_coll, src, d.str(),
                              GHObject(d.str(), d.i32())))
            else:
                raise ValueError(f"undecodable op {name!r}")
        return t


@dataclass
class ObjectStat:
    """reference struct stat subset returned by ObjectStore::stat."""
    size: int


def check_ops(ops, coll_exists: Callable[[str], bool],
              obj_exists: Callable[[str, GHObject], bool]) -> None:
    """Validate a transaction's ops before any mutation, simulating
    intra-transaction creates/removes over the store's existence
    predicates, so an invalid transaction is rejected whole (the
    atomicity contract; the reference treats an op failure mid-apply
    as fatal store corruption — ceph_abort in
    BlueStore::_txc_add_transaction — so validating up front is the
    recoverable equivalent).  Raises FileNotFoundError on a missing
    source; I/O errors during the subsequent apply are the only
    remaining mid-transaction failures and are fatal.
    """
    colls: Dict[str, bool] = {}          # overlay: name -> exists
    objs: Dict[Tuple[str, GHObject], bool] = {}
    wiped: set = set()                   # colls rmcoll'd in this txn

    def has_coll(coll: str) -> bool:
        if coll in colls:
            return colls[coll]
        return coll_exists(coll)

    def has_obj(coll: str, obj: GHObject) -> bool:
        key = (coll, obj)
        if key in objs:
            return objs[key]
        if coll in wiped:
            return False
        return obj_exists(coll, obj)

    def need_coll(coll):
        if not has_coll(coll):
            raise FileNotFoundError(f"no collection {coll!r}")

    def need_obj(coll, obj):
        need_coll(coll)
        if not has_obj(coll, obj):
            raise FileNotFoundError(f"no object {obj} in {coll!r}")

    creates = {"touch", "write", "xor_write", "zero", "truncate",
               "setattr", "omap_setkeys", "omap_setheader"}
    requires = {"rmattr", "omap_rmkeys", "omap_clear"}
    for op in ops:
        name = op[0]
        if name in creates:
            need_coll(op[1])
            objs[(op[1], op[2])] = True
        elif name in requires:
            need_obj(op[1], op[2])
        elif name == "remove":
            need_coll(op[1])
            objs[(op[1], op[2])] = False
        elif name == "clone":
            _, coll, src, dst = op
            need_obj(coll, src)
            objs[(coll, dst)] = True
        elif name == "mkcoll":
            colls[op[1]] = True
        elif name == "rmcoll":
            colls[op[1]] = False
            wiped.add(op[1])
            for key in [k for k in objs if k[0] == op[1]]:
                del objs[key]
        elif name == "coll_move_rename":
            _, src_coll, src, dst_coll, dst = op
            need_obj(src_coll, src)
            need_coll(dst_coll)
            objs[(src_coll, src)] = False
            objs[(dst_coll, dst)] = True
        else:
            raise ValueError(f"unknown op {name!r}")


class ObjectStore(abc.ABC):
    """Abstract store API (reference os/ObjectStore.h).

    All mutations go through queue_transactions; reads are direct.
    Transactions are applied atomically and in submission order per
    collection (the reference serializes per-collection via op
    sequencers).
    """

    # -- lifecycle ---------------------------------------------------------
    @abc.abstractmethod
    def mount(self) -> None:
        """Load state (reference ObjectStore::mount)."""

    @abc.abstractmethod
    def umount(self) -> None:
        """Flush and release (reference ObjectStore::umount)."""

    @abc.abstractmethod
    def mkfs(self) -> None:
        """Initialize an empty store (reference ObjectStore::mkfs)."""

    # -- observability seams (utils/store_ledger.py) -----------------------
    # ObjectStore subclasses never call super().__init__, so all
    # ledger state is created lazily: any store — including a future
    # BlueStore-class rewrite — inherits the full instrumentation by
    # merely routing mutations through queue_transactions and
    # (optionally) stamping its internal phases via _stamp_txn.

    def _store_accum(self) -> store_ledger.StoreLedgerAccum:
        accum = getattr(self, "_sl_accum", None)
        if accum is None:
            accum = store_ledger.StoreLedgerAccum()
            self._sl_accum = accum
        return accum

    def attach_observability(self, perf_coll=None, recorder=None,
                             stall_threshold_s: float = 0.0
                             ) -> store_ledger.StoreLedgerAccum:
        """Wire the store's ledger into a daemon: register the
        ``store`` perf subsystem in ``perf_coll`` (-> ``ceph_store_*``
        prometheus), flight-record ``store_stall`` events into
        ``recorder`` for phases at/over ``stall_threshold_s``.
        Idempotent, and safe for stores surviving an OSD restart:
        accumulated state is kept, counters rebind into the new
        daemon's collection."""
        accum = self._store_accum()
        if perf_coll is not None:
            accum.bind_perf(perf_coll)
        self._sl_recorder = recorder
        self._sl_stall_s = float(stall_threshold_s)
        return accum

    def _stamp_txn(self, phase: str) -> None:
        """Backend seam: stamp the current transaction's ledger.
        No-op outside queue_transactions (mount-time replay)."""
        led = getattr(_TXN_TLS, "led", None)
        if led is not None:
            led[phase] = time.time()

    def _txn_meta(self, field_name: str, value) -> None:
        """Backend seam: accumulate a meta field (carved phase
        seconds, IO accounting counts) on the current ledger."""
        led = getattr(_TXN_TLS, "led", None)
        if led is not None:
            led[field_name] = led.get(field_name, 0) + value

    def dump_store(self) -> dict:
        """``dump_store`` admin payload: the accumulator dump plus
        backend identity (merge-compatible across backends)."""
        out = self._store_accum().dump()
        out["backend"] = type(self).__name__
        return out

    def store_stall_signals(self) -> dict:
        """Health-check feed: stall count + txn volume."""
        accum = self._store_accum()
        return {"stalls": accum.stalls, "txns": accum.txns}

    def _observe_txn(self, led: Dict[str, float],
                     txns: List["Transaction"]) -> None:
        bytes_written = 0
        op_counts: Dict[str, int] = {}
        fam_of = store_ledger.op_family
        for txn in txns:
            for o in txn.ops:
                fam = fam_of(o[0])
                op_counts[fam] = op_counts.get(fam, 0) + 1
                if o[0] in ("write", "xor_write"):
                    bytes_written += len(o[4])
        led["txns"] = len(txns)
        led["bytes_written"] = bytes_written
        accum = self._store_accum()
        charged = accum.observe(led, op_counts=op_counts)
        stall_s = getattr(self, "_sl_stall_s", 0.0)
        if stall_s > 0:
            for phase, dt in charged:
                if dt >= stall_s:
                    accum.note_stall()
                    rec = getattr(self, "_sl_recorder", None)
                    if rec is not None:
                        rec.note("store_stall", phase=phase,
                                 ms=round(dt * 1e3, 3),
                                 backend=type(self).__name__,
                                 op=led.get("op"))
                        rec.auto_dump("store-phase-stall")

    # -- mutation ----------------------------------------------------------
    def queue_transactions(self, txns: List[Transaction],
                           on_commit: Optional[Callable[[], None]] = None,
                           op: Optional[str] = None) -> None:
        """Apply atomically; deliver per-transaction on_applied inline
        and on_commit (plus the aggregate callback) via the finisher
        (reference os/ObjectStore.h:222).

        Template method: the ``store.apply`` injection point
        (utils/faults.py) gates admission — error mode raises before
        any mutation, stall sleeps in place like a wedged disk,
        corrupt mode bit-flips one queued write payload (planted bit
        rot for the scrub/repair machinery) — then the backend's
        ``_do_queue_transactions`` applies.  ``op`` tags the txn's
        store ledger with the enclosing client op's identity.

        The ledger's ``txn_queued`` t0 lands BEFORE the fault gate so
        an injected store.apply stall is charged into the following
        phase interval — exactly where a real wedged journal/device
        would surface."""
        led: Dict[str, float] = {"txn_queued": time.time()}
        if op is not None:
            led["op"] = op
        prev = getattr(_TXN_TLS, "led", None)
        _TXN_TLS.led = led
        try:
            faultlib.registry().store_apply(txns)
            self._do_queue_transactions(txns, on_commit)
        except BaseException:
            # abort-path ledger hygiene: a txn that raises (check_ops
            # reject, fault-site error, mid-apply I/O error) leaves
            # dangling phase stamps — discard the ledger WHOLE rather
            # than charge a partial waterfall, and count the abort.
            # BaseException: a simulated crash in the torture test
            # must not leak ledger state into the next txn either.
            led.pop("_deferred", None)
            self._store_accum().note_abort()
            raise
        finally:
            _TXN_TLS.led = prev
        if led.pop("_deferred", False):
            # a deferred-apply backend (BlueStore) took ownership: the
            # txn is WAL-durable but not yet applied; the apply driver
            # stamps the remaining phases and calls _finalize_txn when
            # the batch lands, keeping charge-sum == txn wall.
            return
        self._finalize_txn(led, txns)

    def _finalize_txn(self, led: Dict[str, float],
                      txns: List["Transaction"]) -> None:
        """Close a transaction's ledger: final stamp + accumulate.
        Synchronous backends reach here from queue_transactions;
        deferred-apply backends call it from the apply driver."""
        led["apply_done"] = time.time()
        self._observe_txn(led, txns)

    def flush(self) -> None:
        """Block until previously queued transactions are applied and
        their callbacks delivered (reference ObjectStore::flush).
        Synchronous backends have nothing pending; deferred-apply
        backends override."""

    @abc.abstractmethod
    def _do_queue_transactions(self, txns: List[Transaction],
                               on_commit: Optional[Callable[[], None]]
                               = None) -> None:
        """Backend apply (see queue_transactions)."""

    def apply_transaction(self, txn: Transaction) -> None:
        self.queue_transactions([txn])

    # -- reads -------------------------------------------------------------
    @abc.abstractmethod
    def read(self, coll: str, obj: GHObject, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        """Byte extent; length=None reads to EOF.  Raises FileNotFoundError
        for a missing object (maps -ENOENT)."""

    @abc.abstractmethod
    def stat(self, coll: str, obj: GHObject) -> ObjectStat:
        ...

    @abc.abstractmethod
    def exists(self, coll: str, obj: GHObject) -> bool:
        ...

    @abc.abstractmethod
    def getattr(self, coll: str, obj: GHObject, name: str) -> bytes:
        ...

    @abc.abstractmethod
    def getattrs(self, coll: str, obj: GHObject) -> Dict[str, bytes]:
        ...

    @abc.abstractmethod
    def omap_get(self, coll: str, obj: GHObject) -> Dict[str, bytes]:
        ...

    @abc.abstractmethod
    def omap_get_header(self, coll: str, obj: GHObject) -> bytes:
        ...

    @abc.abstractmethod
    def omap_get_keys(self, coll: str, obj: GHObject,
                      start_after: str = "",
                      max_return: Optional[int] = None) -> List[str]:
        """Sorted key range scan (reference omap iterator)."""

    # -- collections -------------------------------------------------------
    @abc.abstractmethod
    def list_collections(self) -> List[str]:
        ...

    @abc.abstractmethod
    def collection_exists(self, coll: str) -> bool:
        ...

    @abc.abstractmethod
    def collection_list(self, coll: str, start_after: str = "",
                        max_return: Optional[int] = None
                        ) -> List[GHObject]:
        """Objects in name order (reference collection_list)."""
