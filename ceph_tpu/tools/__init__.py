"""CLI tooling (reference ``src/ceph.in`` + ``src/tools/``, §2.6).

Each module is runnable as ``python -m ceph_tpu.tools.<name>``:

- ``ceph_cli``          — cluster admin CLI (``ceph``)
- ``rados_cli``         — object CLI + ``bench`` (``rados``)
- ``ec_tool``           — offline encode/decode (``ceph-erasure-code-tool``)
- ``ec_benchmark``      — codec microbench (``ceph_erasure_code_benchmark``)
- ``crushtool``         — CRUSH build/test (``crushtool``)
- ``osdmaptool``        — OSDMap inspection (``osdmaptool``)
- ``objectstore_tool``  — offline store access (``ceph-objectstore-tool``)
- ``vstart``            — standalone dev cluster (``vstart.sh``)
"""
