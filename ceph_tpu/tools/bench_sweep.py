"""EC benchmark sweep across plugins / k/m pairs / techniques.

Reference analog: ``qa/workunits/erasure-code/bench.sh`` (:53-59,
148-170) — loops ``ceph_erasure_code_benchmark`` over isa+jerasure ×
vandermonde+cauchy × a k/m grid and emits data the ``bench.html``
flot viewer plots.  This emits one JSON row per combination (GB/s
derived exactly as bench.sh does: KiB / 2^20 / seconds) and an
optional self-contained HTML bar chart.

    python -m ceph_tpu.tools.bench_sweep --size 1048576 -i 3
    python -m ceph_tpu.tools.bench_sweep --plugins tpu,jerasure \\
        --html sweep.html
"""
from __future__ import annotations

import argparse
import json
import sys
from html import escape
from typing import List

from . import ec_benchmark

DEFAULT_KM = ["2/1", "3/2", "4/2", "6/3", "8/4", "10/4"]


def run_one(plugin: str, k: int, m: int, technique: str, size: int,
            iters: int, workload: str) -> dict:
    params = [f"k={k}", f"m={m}"]
    if technique and plugin == "jerasure":
        params.append(f"technique={technique}")
    ns = argparse.Namespace(
        plugin=plugin, parameter=[",".join(params)], size=size,
        iterations=iters, workload=workload, erasures=1,
        erasures_generation="random", erased=[], verbose=False)
    line = ec_benchmark.run(ns)
    secs, kib = line.split("\t")
    gbps = (int(kib) / (1 << 20)) / float(secs) if float(secs) else 0.0
    return {"plugin": plugin, "k": k, "m": m,
            "technique": technique or "default",
            "workload": workload, "seconds": round(float(secs), 6),
            "kib": int(kib), "gbps": round(gbps, 4)}


def render_html(rows: List[dict]) -> str:
    """Self-contained bar chart (stand-in for the reference's flot
    bench.html viewer)."""
    peak = max((r["gbps"] for r in rows), default=1.0) or 1.0
    bars = []
    for r in rows:
        label = (f"{r['plugin']}/{r['technique']} k={r['k']} "
                 f"m={r['m']} {r['workload']}")
        width = max(1, int(520 * r["gbps"] / peak))
        bars.append(
            f"<div class='row'><span class='lbl'>{escape(label)}"
            f"</span><span class='bar' style='width:{width}px'>"
            f"</span><span class='val'>{r['gbps']:.3f} GB/s"
            f"</span></div>")
    return ("<!doctype html><meta charset='utf-8'>"
            "<title>EC bench sweep</title><style>"
            "body{font:13px monospace;margin:2em}"
            ".row{display:flex;align-items:center;margin:2px 0}"
            ".lbl{width:340px}.bar{background:#4a7;height:12px;"
            "display:inline-block;margin-right:6px}</style>"
            "<h2>Erasure-code encode/decode sweep</h2>"
            + "".join(bars))


def main(argv: List[str] = None) -> int:
    p = argparse.ArgumentParser(prog="bench-sweep",
                                description=__doc__.splitlines()[0])
    p.add_argument("--plugins", default="jerasure,isa,tpu")
    p.add_argument("--km", default=",".join(DEFAULT_KM),
                   help="comma list of k/m pairs")
    p.add_argument("--techniques", default="reed_sol_van,cauchy_good",
                   help="jerasure techniques to sweep")
    p.add_argument("--size", type=int, default=1 << 20)
    p.add_argument("-i", "--iterations", type=int, default=3)
    p.add_argument("--workloads", default="encode,decode")
    p.add_argument("--html", help="also write a bar-chart viewer here")
    ns = p.parse_args(argv)

    rows: List[dict] = []
    for plugin in ns.plugins.split(","):
        techniques = ns.techniques.split(",") if plugin == "jerasure" \
            else [""]
        for tech in techniques:
            for km in ns.km.split(","):
                k, m = (int(x) for x in km.split("/"))
                for workload in ns.workloads.split(","):
                    try:
                        row = run_one(plugin, k, m, tech, ns.size,
                                      ns.iterations, workload)
                    except Exception as e:
                        print(f"# skip {plugin} {tech} {km} "
                              f"{workload}: {e}", file=sys.stderr)
                        continue
                    rows.append(row)
                    print(json.dumps(row))
    if ns.html:
        with open(ns.html, "w") as f:
            f.write(render_html(rows))
        print(f"# wrote {ns.html}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
