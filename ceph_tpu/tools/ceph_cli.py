"""``ceph`` — the cluster admin CLI.

Reference analog: ``src/ceph.in`` + ``src/pybind/ceph_argparse.py``:
free-form argv is matched against the monitor's command table
(``src/mon/MonCommands.h``) and shipped as a JSON dict
(``{"prefix": ..., args...}``) over MonClient; the monitor replies with
(retcode, outs, outbl).  This implementation mirrors the subset of
``MonCommands.h`` the framework's monitor serves (profile management at
``mon/OSDMonitor.cc:10829``, pool create at ``:7216``, osd out/in/down,
status/health/pg-dump) plus daemon-local ``ceph daemon <sock> <cmd>``
(reference admin socket, ``src/common/admin_socket.cc``).

Usage examples (same shapes as the reference):
    ceph -m HOST:PORT status
    ceph osd erasure-code-profile set tpuprof plugin=tpu k=8 m=4
    ceph osd pool create ecpool 8 erasure tpuprof
    ceph osd pool create rpool 8 replicated --size 3
    ceph osd out 2
    ceph pg dump --format json
"""
from __future__ import annotations

import argparse
import sys
from typing import List, Tuple

from .common import connect, print_out

POOL_TYPES = ("replicated", "erasure")


def _build_command(words: List[str], ns: argparse.Namespace
                   ) -> Tuple[dict, List[str]]:
    """argv words -> monitor command dict (reference
    ceph_argparse.validate_command against MonCommands.h)."""
    w = words

    def is_(*prefix: str) -> bool:
        return w[:len(prefix)] == list(prefix)

    def arg(i: int, usage: str) -> str:
        if len(w) <= i:
            raise SystemExit(f"usage: {usage}")
        return w[i]

    if is_("osd", "erasure-code-profile", "set"):
        name = arg(3, "osd erasure-code-profile set <name> [k=v ...] "
                   "[--force]")
        return ({"prefix": "osd erasure-code-profile set", "name": name,
                 "profile": w[4:], "force": ns.force}, [])
    if is_("osd", "erasure-code-profile", "get"):
        return ({"prefix": "osd erasure-code-profile get",
                 "name": arg(3, "osd erasure-code-profile get <name>")}, [])
    if is_("osd", "erasure-code-profile", "ls"):
        return ({"prefix": "osd erasure-code-profile ls"}, [])
    if is_("osd", "erasure-code-profile", "rm"):
        return ({"prefix": "osd erasure-code-profile rm",
                 "name": arg(3, "osd erasure-code-profile rm <name>")}, [])

    if is_("osd", "pool", "create"):
        # osd pool create <pool> [pg_num] [replicated|erasure [profile]]
        if len(w) < 4:
            raise SystemExit("usage: osd pool create <pool> [pg_num] "
                             "[replicated|erasure [profile]]")
        cmd = {"prefix": "osd pool create", "pool": w[3]}
        rest = w[4:]
        if rest and rest[0].isdigit():
            cmd["pg_num"] = int(rest.pop(0))
        if rest and rest[0] in POOL_TYPES:
            cmd["pool_type"] = rest.pop(0)
            if cmd["pool_type"] == "erasure" and rest:
                cmd["erasure_code_profile"] = rest.pop(0)
        if ns.size is not None:
            cmd["size"] = ns.size
        return cmd, rest
    if is_("osd", "pool", "set"):
        if len(w) < 6:
            raise SystemExit("usage: osd pool set <pool> <var> <val>")
        return ({"prefix": "osd pool set", "pool": w[3], "var": w[4],
                 "val": w[5]}, w[6:])
    if is_("osd", "pool", "delete") or is_("osd", "pool", "rm"):
        return ({"prefix": "osd pool delete",
                 "pool": arg(3, "osd pool delete <pool>")}, w[4:])
    if is_("osd", "pool", "ls"):
        return ({"prefix": "osd pool ls"}, w[3:])

    for verb in ("out", "in", "down"):
        if is_("osd", verb):
            ids = [int(x) for x in w[2:]]
            if not ids:
                raise SystemExit(f"usage: osd {verb} <id> [<id>...]")
            return ({"prefix": f"osd {verb}", "ids": ids}, [])
    if is_("osd", "dump"):
        return ({"prefix": "osd dump"}, w[2:])
    if is_("osd", "tree"):
        return ({"prefix": "osd tree"}, w[2:])

    if is_("fs", "set"):
        return ({"prefix": "fs set",
                 "var": arg(2, "fs set <var> <val>"),
                 "val": arg(3, "fs set <var> <val>")}, w[4:])
    if is_("fs", "pin"):
        return ({"prefix": "fs pin",
                 "path": arg(2, "fs pin <path> <rank>"),
                 "rank": arg(3, "fs pin <path> <rank>")}, w[4:])
    if is_("mds", "getmap") or is_("fs", "status"):
        return ({"prefix": "mds getmap"}, w[2:])

    if is_("status") or is_("-s"):
        return ({"prefix": "status"}, w[1:])
    if is_("health"):
        return ({"prefix": "health"}, w[1:])
    if is_("pg", "stat"):
        return ({"prefix": "pg stat"}, w[2:])
    if is_("pg", "dump"):
        return ({"prefix": "pg dump"}, w[2:])
    if is_("pg", "scrub") or is_("pg", "deep-scrub") or is_("pg", "repair"):
        return ({"prefix": f"pg {w[1]}",
                 "pgid": arg(2, f"pg {w[1]} <pgid>")}, w[3:])

    if is_("tell"):
        # handled out-of-band: direct daemon command, not a mon command
        target = arg(1, "tell osd.<id> <command...>")
        rest = w[2:]
        if not rest:
            raise SystemExit("usage: tell osd.<id> <command...>")
        if rest[:2] == ["config", "get"]:
            if len(rest) < 3:
                raise SystemExit("usage: tell <osd> config get <name>")
            return ({"_tell": target, "prefix": "config get",
                     "name": rest[2]}, [])
        if rest[:2] == ["config", "set"]:
            if len(rest) < 4:
                raise SystemExit("usage: tell <osd> config set "
                                 "<name> <value>")
            return ({"_tell": target, "prefix": "config set",
                     "name": rest[2], "value": rest[3]}, [])
        return ({"_tell": target, "prefix": " ".join(rest)}, [])

    if is_("auth", "get-or-create"):
        return ({"prefix": "auth get-or-create",
                 "entity": arg(2, "auth get-or-create <entity> "
                               "[<svc> <caps> ...]"),
                 "caps": w[3:]}, [])
    if is_("auth", "get"):
        return ({"prefix": "auth get",
                 "entity": arg(2, "auth get <entity>")}, [])
    if is_("auth", "ls"):
        return ({"prefix": "auth ls"}, w[2:])
    if is_("auth", "rm") or is_("auth", "del"):
        return ({"prefix": "auth rm",
                 "entity": arg(2, "auth rm <entity>")}, [])
    if is_("auth", "print-key"):
        return ({"prefix": "auth print-key",
                 "entity": arg(2, "auth print-key <entity>")}, [])

    if is_("config", "set"):
        arg(3, "config set <name> <value>")
        return ({"prefix": "config set", "name": w[2], "value": w[3]}, w[4:])
    if is_("config", "get"):
        return ({"prefix": "config get",
                 "name": arg(2, "config get <name>")}, w[3:])

    raise SystemExit(f"unknown command: {' '.join(w)!r}")


def _split_argv(argv: List[str]) -> Tuple[List[str], List[str]]:
    """Pull our own options out of argv wherever they appear, leaving
    the command words (argparse.REMAINDER would swallow options placed
    after the first word, breaking 'ceph pg dump --format json')."""
    takes_value = {"-m", "--mon", "--format", "--size", "--timeout"}
    flags = {"--force"}
    opts: List[str] = []
    words: List[str] = []
    i = 0
    while i < len(argv):
        tok = argv[i]
        key, _, inline = tok.partition("=")
        if key in takes_value:
            opts.append(tok)
            if not inline and i + 1 < len(argv):
                i += 1
                opts.append(argv[i])
        elif key in flags:
            opts.append(tok)
        elif tok == "-s" and not words:
            words.append("status")
        else:
            words.append(tok)
        i += 1
    return opts, words


def _tell(cluster, target: str, cmd: dict, timeout: float
          ) -> Tuple[int, str, dict]:
    """Direct daemon command (reference 'ceph tell osd.N ...' over
    MCommand): resolve the daemon's address from the osdmap, dial it,
    await the reply."""
    import threading

    from ..msg.messages import MCommand, MCommandReply
    from ..msg.messenger import Dispatcher

    if not target.startswith("osd."):
        raise SystemExit(f"tell target {target!r} not supported "
                         f"(osd.<id> only)")
    try:
        osd = int(target.split(".", 1)[1])
    except ValueError:
        raise SystemExit(f"bad tell target {target!r} "
                         f"(want osd.<id>)")
    ret, rs, out = cluster.mon_command({"prefix": "osd dump"}, timeout)
    if ret != 0:
        return ret, rs, out
    info = next((o for o in out.get("osds", []) if o["osd"] == osd),
                None)
    if info is None or not info.get("up") or not info.get("addr"):
        return -2, f"osd.{osd} is not up", {}

    got = threading.Event()
    reply = {}

    class _Collector(Dispatcher):
        def ms_dispatch(self, conn, msg) -> bool:
            if isinstance(msg, MCommandReply):
                reply["msg"] = msg
                got.set()
                return True
            return False

    cluster.msgr.add_dispatcher(_Collector())
    # lossy, like every client->daemon dial: a lossless session would
    # leave the OSD waiting forever for this short-lived CLI process
    # to reconnect
    conn = cluster.msgr.connect_to(tuple(info["addr"]),
                                   lossless=False,
                                   peer_name=f"osd.{osd}")
    conn.send_message(MCommand(tid=1, cmd=cmd))
    if not got.wait(timeout):
        return -110, f"osd.{osd} did not answer", {}
    m = reply["msg"]
    return m.retcode, m.rs, m.out


def main(argv: List[str] = None) -> int:
    p = argparse.ArgumentParser(
        prog="ceph", description=__doc__.splitlines()[0])
    p.add_argument("-m", "--mon", help="monitor host:port "
                   "(default $CEPH_TPU_MON)")
    p.add_argument("--format", choices=("plain", "json"), default="plain")
    p.add_argument("--force", action="store_true")
    p.add_argument("--size", type=int, help="replica count for pool create")
    p.add_argument("--timeout", type=float, default=30.0)
    if argv is None:
        argv = sys.argv[1:]
    opts, words = _split_argv(list(argv))
    ns = p.parse_args(opts)
    ns.words = words
    if not ns.words:
        p.error("no command")
    cmd, leftover = _build_command(ns.words, ns)
    if leftover:
        raise SystemExit(f"trailing arguments: {leftover}")

    with connect(ns.mon) as cluster:
        if "_tell" in cmd:
            retcode, rs, out = _tell(cluster, cmd.pop("_tell"), cmd,
                                     ns.timeout)
        else:
            retcode, rs, out = cluster.mon_command(cmd, ns.timeout)
    print_out(rs, out, ns.format == "json")
    if retcode < 0:
        print(f"Error: {rs} ({retcode})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
