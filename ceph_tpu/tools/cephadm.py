"""cephadm-style deployment: spec-driven cluster bootstrap + service
management.

Python-native equivalent of the reference's orchestration layer
(reference ``src/cephadm/`` + the ``ceph orch`` mgr module) collapsed
to what a single-host (or test-host) deployment needs:

* a **service spec** (JSON) names the daemons to run — mons, osds
  (with store kind + data paths), mgr, rgw, mds — like cephadm's
  service specs;
* ``bootstrap`` brings the cluster up from the spec: mon quorum
  first, then OSDs (creating their data dirs/stores), then the
  service daemons, writing a ``cluster.json`` handle with addresses;
* ``orch ls / ps / apply / daemon stop|start`` manage the running
  set, mirroring the ``ceph orch`` verbs.

Daemons run as threads of this process (the framework's daemons are
in-process objects; the reference runs containers — the management
surface is what's mirrored, not the container runtime).

CLI::

    python -m ceph_tpu.tools.cephadm bootstrap --spec spec.json --shell
    # then, at the orch> prompt: orch ls | orch ps |
    #   daemon stop osd.1 | daemon start osd.1 | orch apply osd 4
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

DEFAULT_SPEC = {
    "mon": {"count": 1},
    "osd": {"count": 3, "store": "mem"},
    "mgr": {"count": 0},
    "rgw": {"count": 0, "pool": "rgw"},
    "mds": {"count": 0, "meta_pool": "fsmeta", "data_pool": "fsdata"},
}


class CephAdm:
    """One deployed cluster under management (reference cephadm shell
    + orchestrator state)."""

    def __init__(self, spec: Optional[dict] = None,
                 data_dir: str = ""):
        self.spec = {**DEFAULT_SPEC, **(spec or {})}
        for k, v in DEFAULT_SPEC.items():
            if isinstance(v, dict):
                self.spec[k] = {**v, **self.spec.get(k, {})}
        self.data_dir = data_dir
        self.cluster = None
        self.services: Dict[str, object] = {}   # name -> daemon obj
        # how to (re)create each service daemon: restartable stop/start
        self._factories: Dict[str, object] = {}

    # -- bootstrap (reference cephadm bootstrap) -----------------------
    def bootstrap(self):
        try:
            return self._bootstrap()
        except Exception:
            # partial bring-up must not leak daemon threads/ports: the
            # caller never receives the handle, so clean up here
            self.shutdown()
            raise

    def _bootstrap(self):
        from ..cluster import Cluster, test_config
        osd_spec = self.spec["osd"]
        self.cluster = Cluster(
            n_osds=osd_spec.get("count", 3),
            n_mons=self.spec["mon"].get("count", 1),
            data_dir=self.data_dir or None,
            store_kind=osd_spec.get("store", "mem"),
            conf=test_config(**self.spec.get("conf", {})))
        self.cluster.__enter__()
        for i in range(osd_spec.get("count", 3)):
            self.cluster.wait_for_osd_up(i, 60)
        if self.spec["mgr"].get("count"):
            from ..mgr.manager import Manager

            def mk_mgr():
                return Manager(self.cluster.client_mon_addrs(),
                               conf=self.cluster.conf).start()
            self._factories["mgr.x"] = mk_mgr
            self.services["mgr.x"] = mk_mgr()
        if self.spec["rgw"].get("count"):
            pool = self.spec["rgw"].get("pool", "rgw")
            self.cluster.create_pool(pool, "replicated",
                                     size=min(2, len(
                                         self.cluster.osds)))
            from ..rgw.server import RGWServer

            def mk_rgw():
                io = self.cluster.rados().open_ioctx(pool)
                return RGWServer(io).start()
            self._factories["rgw.x"] = mk_rgw
            self.services["rgw.x"] = mk_rgw()
        if self.spec["mds"].get("count"):
            meta = self.spec["mds"].get("meta_pool", "fsmeta")
            data = self.spec["mds"].get("data_pool", "fsdata")
            for p in (meta, data):
                self.cluster.create_pool(p, "replicated",
                                         size=min(2, len(
                                             self.cluster.osds)))
            from ..mds import MDSDaemon

            def mk_mds():
                return MDSDaemon(self.cluster.client_mon_addrs(), meta,
                                 data,
                                 conf=self.cluster.conf).start()
            self._factories["mds.a"] = mk_mds
            self.services["mds.a"] = mk_mds()
        return self

    def shutdown(self):
        for name, svc in list(self.services.items()):
            try:
                svc.shutdown()
            except Exception:
                pass
        if self.cluster is not None:
            self.cluster.__exit__(None, None, None)

    # -- orch verbs (reference `ceph orch`) ----------------------------
    def orch_ls(self) -> List[dict]:
        out = [{"service": "mon",
                "running": len([m for m in self.cluster.mons.values()
                                if m is not None])},
               {"service": "osd",
                "running": len([o for o in self.cluster.osds.values()
                                if o is not None])}]
        for kind in ("mgr", "rgw", "mds"):
            known = [s for s in self._factories if s.startswith(kind)]
            if known:
                out.append({"service": kind,
                            "running": len([s for s in known
                                            if s in self.services])})
        return out

    def orch_ps(self) -> List[dict]:
        rows = []
        for r, m in sorted(self.cluster.mons.items()):
            rows.append({"daemon": f"mon.{r}",
                         "status": "running" if m else "stopped",
                         "addr": list(m.my_addr) if m else None})
        for i, o in sorted(self.cluster.osds.items()):
            rows.append({"daemon": f"osd.{i}",
                         "status": "running" if o else "stopped",
                         "addr": list(o.my_addr) if o else None})
        for name in sorted(self._factories):
            svc = self.services.get(name)
            addr = (getattr(svc, "my_addr", None)
                    or getattr(svc, "addr", None)) if svc else None
            rows.append({"daemon": name,
                         "status": "running" if svc else "stopped",
                         "addr": list(addr) if addr else None})
        return rows

    def daemon_stop(self, name: str) -> None:
        kind, _, ident = name.partition(".")
        if kind == "osd":
            self.cluster.kill_osd(int(ident))
        elif kind == "mon":
            self.cluster.kill_mon(int(ident))
        elif name in self.services:
            self.services.pop(name).shutdown()
        else:
            raise KeyError(name)

    def daemon_start(self, name: str) -> None:
        kind, _, ident = name.partition(".")
        if kind == "osd":
            self.cluster.revive_osd(int(ident))
        elif kind == "mon":
            self.cluster.revive_mon(int(ident))
        elif name in self._factories:
            if name not in self.services:
                self.services[name] = self._factories[name]()
        else:
            raise KeyError(name)

    def orch_apply_osd(self, count: int) -> int:
        """Scale the OSD service up (reference `ceph orch apply osd`);
        -> number of new daemons."""
        started = 0
        # declarative: count DEPLOYED daemons (a stopped daemon is
        # still deployed — replacing it would over-provision CRUSH)
        while len(self.cluster.osds) < count:
            new_id = max(self.cluster.osds, default=-1) + 1
            self.cluster.start_osd(new_id)
            self.cluster.wait_for_osd_up(new_id, 60)
            started += 1
        return started


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cephadm",
                                description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("bootstrap")
    b.add_argument("--spec", help="service spec JSON file")
    b.add_argument("--data-dir", default="")
    b.add_argument("--seconds", type=float, default=5.0,
                   help="keep the cluster up this long (demo mode)")
    b.add_argument("--shell", action="store_true",
                   help="interactive orch shell on stdin")
    ns = p.parse_args(argv)
    if ns.cmd == "bootstrap":
        spec = json.loads(open(ns.spec).read()) if ns.spec else {}
        adm = CephAdm(spec, data_dir=ns.data_dir).bootstrap()
        try:
            print(json.dumps({"services": adm.orch_ls(),
                              "daemons": adm.orch_ps()}, indent=1))
            if ns.shell:
                _shell(adm)
            else:
                time.sleep(ns.seconds)
        finally:
            adm.shutdown()
        return 0
    return 2


def _shell(adm: CephAdm, stdin=None) -> None:
    """`ceph orch`-verb REPL over a live deployment."""
    stdin = stdin or sys.stdin
    sys.stdout.write("orch> ")
    sys.stdout.flush()
    for line in stdin:
        words = line.split()
        try:
            if words[:2] == ["orch", "ls"]:
                print(json.dumps(adm.orch_ls()))
            elif words[:2] == ["orch", "ps"]:
                print(json.dumps(adm.orch_ps()))
            elif words[:2] == ["daemon", "stop"]:
                adm.daemon_stop(words[2])
                print("stopped", words[2])
            elif words[:2] == ["daemon", "start"]:
                adm.daemon_start(words[2])
                print("started", words[2])
            elif words[:3][:2] == ["orch", "apply"] and \
                    words[2] == "osd":
                print("started", adm.orch_apply_osd(int(words[3])))
            elif words == ["exit"] or words == ["quit"]:
                return
            elif words:
                print("? orch ls|ps, daemon stop|start <name>, "
                      "orch apply osd <n>, exit")
        except Exception as e:       # keep the shell alive
            print(f"error: {e!r}")
        sys.stdout.write("orch> ")
        sys.stdout.flush()


if __name__ == "__main__":
    sys.exit(main())
