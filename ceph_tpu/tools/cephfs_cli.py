"""``cephfs`` — file-layer CLI (mount-less).

Reference analog: ``cephfs-shell`` (``src/tools/cephfs/``) — drive
the file hierarchy without a kernel mount:

    cephfs -m HOST:PORT --meta-pool fsmeta [--data-pool fsdata] ls /
    cephfs ... mkdir /a/b
    cephfs ... put local.bin /a/b/file.bin
    cephfs ... get /a/b/file.bin out.bin
    cephfs ... mv /a/b/file.bin /a/renamed.bin
    cephfs ... rm /a/renamed.bin ; cephfs ... rmdir /a/b
    cephfs ... stat /a ; cephfs ... tree /
"""
from __future__ import annotations

import argparse
import sys
from typing import List

from .common import connect
from ..fs import FileSystem, FSError


def main(argv: List[str] = None) -> int:
    p = argparse.ArgumentParser(prog="cephfs",
                                description=__doc__.splitlines()[0])
    p.add_argument("-m", "--mon")
    p.add_argument("--meta-pool", required=True)
    p.add_argument("--data-pool", help="defaults to the meta pool")
    sub = p.add_subparsers(dest="op", required=True)
    s = sub.add_parser("ls"); s.add_argument("path", nargs="?",
                                             default="/")
    s = sub.add_parser("mkdir"); s.add_argument("path")
    s = sub.add_parser("put"); s.add_argument("infile")
    s.add_argument("path")
    s = sub.add_parser("get"); s.add_argument("path")
    s.add_argument("outfile")
    s = sub.add_parser("rm"); s.add_argument("path")
    s = sub.add_parser("rmdir"); s.add_argument("path")
    s = sub.add_parser("mv"); s.add_argument("old")
    s.add_argument("new")
    s = sub.add_parser("stat"); s.add_argument("path")
    s = sub.add_parser("tree"); s.add_argument("path", nargs="?",
                                               default="/")
    ns = p.parse_args(argv)

    with connect(ns.mon) as cluster:
        meta = cluster.open_ioctx(ns.meta_pool)
        data = cluster.open_ioctx(ns.data_pool) if ns.data_pool \
            else None
        fs = FileSystem(meta, data)
        try:
            if ns.op == "ls":
                for e in fs.listdir(ns.path):
                    kind = "d" if e["type"] == "dir" else "-"
                    print(f"{kind} {e['name']}")
            elif ns.op == "mkdir":
                fs.mkdir(ns.path)
            elif ns.op == "put":
                with open(ns.infile, "rb") as f:
                    data = f.read()
                fs.write_file(ns.path, data)
                # put is whole-file replacement; write_file alone is
                # pwrite (a smaller upload would keep the old tail)
                fs.truncate(ns.path, len(data))
            elif ns.op == "get":
                with open(ns.outfile, "wb") as f:
                    f.write(fs.read_file(ns.path))
            elif ns.op == "rm":
                fs.unlink(ns.path)
            elif ns.op == "rmdir":
                fs.rmdir(ns.path)
            elif ns.op == "mv":
                fs.rename(ns.old, ns.new)
            elif ns.op == "stat":
                st = fs.stat(ns.path)
                print(f"{ns.path}: {st['type']} ino={st['ino']} "
                      f"size={st['size']} mode={oct(st['st_mode'])}")
            elif ns.op == "tree":
                for path, dirs, files in fs.walk(ns.path):
                    print(path)
                    for d in sorted(dirs):
                        print(f"  {d}/")
                    for f0 in sorted(files):
                        print(f"  {f0}")
        except FSError as e:
            print(f"cephfs: {e}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
