"""Shared plumbing for the CLI tools.

Reference analog: ``src/ceph.in`` and ``src/pybind/ceph_argparse.py``
resolve the monitor address from ``-m``/ceph.conf, open a client handle
and ship JSON command dicts to the monitor.  Here every tool accepts
``-m/--mon host:port`` (default from ``$CEPH_TPU_MON``) and talks the
framework's real wire protocol over loopback/DCN, so the same binary
works against an in-process test cluster or a standalone ``vstart``
cluster in another process.
"""
from __future__ import annotations

import json
import os
import sys
from typing import Optional, Tuple

from ..client.rados import Rados


def parse_mon_addr(spec: Optional[str]) -> Tuple[str, int]:
    spec = spec or os.environ.get("CEPH_TPU_MON", "")
    if not spec:
        raise SystemExit(
            "no monitor address: pass -m host:port or set $CEPH_TPU_MON")
    host, _, port = spec.rpartition(":")
    if not host:
        raise SystemExit(f"bad monitor address {spec!r} (want host:port)")
    return host, int(port)


def connect(mon: Optional[str], timeout: float = 10.0) -> Rados:
    return Rados(parse_mon_addr(mon)).connect(timeout)


def print_out(rs: str, out: dict, as_json: bool, file=None) -> None:
    """Command output: human string + structured payload (reference
    ``ceph`` prints outs to stderr and outbl to stdout).  A closed
    pipe (``| head``) ends output quietly instead of tracebacking."""
    file = file or sys.stdout
    try:
        if as_json or (out and not rs):
            if out:
                json.dump(out, file, indent=2, sort_keys=True,
                          default=str)
                file.write("\n")
            if rs:
                print(rs, file=sys.stderr)
        else:
            if rs:
                print(rs, file=file)
            if out:
                json.dump(out, file, indent=2, sort_keys=True,
                          default=str)
                file.write("\n")
    except BrokenPipeError:
        try:
            devnull = os.open(os.devnull, os.O_WRONLY)
            os.dup2(devnull, file.fileno())
        except OSError:
            pass
