"""``crushtool`` — build, inspect and test CRUSH maps offline.

Reference analog: ``src/tools/crushtool.cc``: ``--build`` synthesises a
hierarchy, ``-d`` decompiles a map, ``-c`` compiles one, ``--test``
runs ``crush_do_rule`` over a range of inputs and reports mappings /
utilization.  Maps are stored as the framework's JSON wire dict
(``crush/wrapper.py to_wire_dict``) instead of the reference's binary
encoding.

    crushtool --build --num-osds 12 -o map.json \
        node straw2 4 rack straw2 0
    crushtool -d map.json
    crushtool --test -i map.json --rule 0 --num-rep 3 \
        --min-x 0 --max-x 1023 --show-mappings
    crushtool --test -i map.json --rule 0 --num-rep 3 --show-utilization
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from typing import List

from ..crush.wrapper import CrushWrapper


def build_hierarchy(num_osds: int, layers: List[List[str]]) -> CrushWrapper:
    """--build: bottom-up layers of (type_name, algorithm, fan_out);
    fan_out 0 = one bucket holding everything (reference
    crushtool.cc --build / CrushCompiler)."""
    crush = CrushWrapper()

    def ensure_type(tname: str) -> None:
        if tname not in crush.types.values():
            crush.types[max(crush.types) + 1] = tname

    items = [(i, f"osd.{i}") for i in range(num_osds)]
    level_items = items
    for depth, (tname, alg, size) in enumerate(layers):
        ensure_type(tname)
        size = int(size)
        buckets = []
        if size <= 0:
            groups = [level_items]
        else:
            groups = [level_items[i:i + size]
                      for i in range(0, len(level_items), size)]
        for bi, group in enumerate(groups):
            bname = f"{tname}{bi}" if size > 0 else tname
            crush.add_bucket(bname, tname, alg=alg)
            for iid, iname in group:
                if depth == 0:
                    crush.insert_item(iid, 1.0, iname, bname)
                else:
                    crush.move_bucket(iname, bname)
            buckets.append((crush.get_bucket(bname).id, bname))
        level_items = buckets
    root_name = level_items[0][1] if len(level_items) == 1 else "root"
    if len(level_items) > 1:
        crush.add_bucket("root", "root")
        for _, bname in level_items:
            crush.move_bucket(bname, "root")
    crush.add_simple_rule("replicated_rule", root_name, "osd",
                          mode="firstn")
    return crush


def cmd_test(crush: CrushWrapper, ns) -> int:
    rule = ns.rule
    reps = ns.num_rep
    n_dev = max((i for i in crush.name_ids.values() if i >= 0),
                default=-1) + 1
    weights = [0x10000] * n_dev
    total = Counter()
    bad = 0
    for x in range(ns.min_x, ns.max_x + 1):
        out = crush.do_rule(rule, x, reps, weights)
        if ns.show_mappings:
            print(f"CRUSH rule {rule} x {x} {out}")
        if len([o for o in out if o is not None]) < reps:
            bad += 1
        total.update(o for o in out if o is not None)
    n_inputs = ns.max_x - ns.min_x + 1
    if ns.show_utilization:
        expect = n_inputs * reps / max(1, len(total))
        for dev in sorted(total):
            print(f"  device {dev}:\tstored : {total[dev]}\t"
                  f"expected : {expect:.2f}")
    if ns.show_bad_mappings or bad:
        print(f"bad mappings: {bad}/{n_inputs}")
    return 0 if bad == 0 else 1


def main(argv: List[str] = None) -> int:
    p = argparse.ArgumentParser(prog="crushtool",
                                description=__doc__.splitlines()[0])
    p.add_argument("--build", action="store_true")
    p.add_argument("--num-osds", type=int, default=0)
    p.add_argument("-o", "--outfn")
    p.add_argument("-i", "--infn")
    p.add_argument("-d", "--decompile")
    p.add_argument("-c", "--compile", dest="compilefn")
    p.add_argument("--test", action="store_true")
    p.add_argument("--rule", type=int, default=0)
    p.add_argument("--num-rep", type=int, default=3)
    p.add_argument("--min-x", type=int, default=0)
    p.add_argument("--max-x", type=int, default=1023)
    p.add_argument("--show-mappings", action="store_true")
    p.add_argument("--show-utilization", action="store_true")
    p.add_argument("--show-bad-mappings", action="store_true")
    p.add_argument("layers", nargs="*",
                   help="--build: repeated <type> <algorithm> <size>")
    ns = p.parse_args(argv)

    if ns.build:
        if ns.num_osds <= 0 or len(ns.layers) % 3:
            raise SystemExit("--build needs --num-osds and "
                             "<type> <alg> <size> triples")
        layers = [ns.layers[i:i + 3] for i in range(0, len(ns.layers), 3)]
        crush = build_hierarchy(ns.num_osds, layers)
        out = json.dumps(crush.to_wire_dict(), indent=2, sort_keys=True)
        if ns.outfn:
            with open(ns.outfn, "w") as f:
                f.write(out + "\n")
        else:
            print(out)
        return 0

    if ns.decompile:
        with open(ns.decompile) as f:
            crush = CrushWrapper.from_wire_dict(json.load(f))
        json.dump(crush.dump(), sys.stdout, indent=2, sort_keys=True)
        print()
        return 0

    if ns.compilefn:
        with open(ns.compilefn) as f:
            crush = CrushWrapper.from_wire_dict(json.load(f))
        out = json.dumps(crush.to_wire_dict(), sort_keys=True)
        if ns.outfn:
            with open(ns.outfn, "w") as f:
                f.write(out + "\n")
        print(f"compiled ok: {len(crush.bucket_names)} buckets, "
              f"{len(crush.map.rules)} rules")
        return 0

    if ns.test:
        if not ns.infn:
            raise SystemExit("--test needs -i <map.json>")
        with open(ns.infn) as f:
            crush = CrushWrapper.from_wire_dict(json.load(f))
        return cmd_test(crush, ns)

    p.error("one of --build/-d/-c/--test required")


if __name__ == "__main__":
    sys.exit(main())
