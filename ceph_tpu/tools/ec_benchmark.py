"""``ceph_erasure_code_benchmark`` — codec micro-benchmark.

Reference analog: ``src/test/erasure-code/ceph_erasure_code_benchmark.cc``
(:156-316).  Same CLI surface and the same two-column output
``<seconds>\t<KiB>`` so the reference's ``qa/workunits/erasure-code/
bench.sh`` GB/s arithmetic (``KiB / 2^20 / seconds``) works unchanged:

    -p/--plugin NAME        codec plugin (jerasure, isa, tpu, lrc, ...)
    -P/--parameter k=v      profile parameter, repeatable
    -S/--size BYTES         total bytes per iteration (default 1 MiB)
    -i/--iterations N       iterations (default 1)
    -w/--workload encode|decode
    -e/--erasures N         erasure count for decode (default 1)
    --erasures-generation random|exhaustive
    --erased i              explicit erased chunk, repeatable
    -v/--verbose

Workloads mirror the reference: ``encode`` times repeated
``encode(all, buffer)``; ``decode`` pre-encodes once, then times
``decode`` over chunk subsets with N chunks erased (random draws per
iteration, or every C(k+m, N) pattern when exhaustive).
"""
from __future__ import annotations

import argparse
import itertools
import random
import sys
import time
from typing import List

from .ec_tool import parse_profile
from ..ec import registry as ecreg


def run(ns) -> str:
    prof = {}
    for item in ns.parameter:
        prof.update(parse_profile(item))
    plugin = ns.plugin
    ec = ecreg.instance().factory(plugin, prof)
    k = ec.get_data_chunk_count()
    m = ec.get_coding_chunk_count()
    want = set(range(k + m))
    data = random.Random(42).randbytes(ns.size)

    if ns.workload == "encode":
        total_kib = 0
        t0 = time.perf_counter()
        for _ in range(ns.iterations):
            ec.encode(want, data)
            total_kib += len(data) // 1024
        dt = time.perf_counter() - t0
        return f"{dt:.6f}\t{total_kib}"

    # decode workload
    chunks = ec.encode(want, data)
    chunk_ids = sorted(chunks)
    if ns.erased:
        patterns = [tuple(ns.erased)]
    elif ns.erasures_generation == "exhaustive":
        patterns = list(itertools.combinations(chunk_ids, ns.erasures))
        if not patterns:
            raise SystemExit(f"--erasures {ns.erasures} exceeds "
                             f"chunk count {len(chunk_ids)}")
    else:
        rng = random.Random(7)
        patterns = [tuple(rng.sample(chunk_ids, ns.erasures))
                    for _ in range(ns.iterations)]
    want_read = set(range(k))
    total_kib = 0
    t0 = time.perf_counter()
    for it in range(ns.iterations):
        pattern = patterns[it % len(patterns)]
        avail = {i: c for i, c in chunks.items() if i not in pattern}
        need = ec.minimum_to_decode(want_read, set(avail))
        ec.decode(want_read, {i: avail[i] for i in need})
        total_kib += len(data) // 1024
    dt = time.perf_counter() - t0
    if ns.verbose:
        print(f"# patterns={len(patterns)} first={patterns[0]}",
              file=sys.stderr)
    return f"{dt:.6f}\t{total_kib}"


def main(argv: List[str] = None) -> int:
    p = argparse.ArgumentParser(prog="ceph_erasure_code_benchmark",
                                description=__doc__.splitlines()[0])
    p.add_argument("-p", "--plugin", default="jerasure")
    p.add_argument("-P", "--parameter", action="append", default=[])
    p.add_argument("-S", "--size", type=int, default=1 << 20)
    p.add_argument("-i", "--iterations", type=int, default=1)
    p.add_argument("-w", "--workload", choices=("encode", "decode"),
                   default="encode")
    p.add_argument("-e", "--erasures", type=int, default=1)
    p.add_argument("--erasures-generation", default="random",
                   choices=("random", "exhaustive"))
    p.add_argument("--erased", type=int, action="append", default=[])
    p.add_argument("-v", "--verbose", action="store_true")
    ns = p.parse_args(argv)
    print(run(ns))
    return 0


if __name__ == "__main__":
    sys.exit(main())
