"""Erasure-code bit-exactness corpus: create + verify.

Reference analog: the ``ceph-erasure-code-corpus`` submodule +
``qa/workunits/erasure-code/encode-decode-non-regression.sh`` (:19-40)
and ``src/test/erasure-code/ceph_erasure_code_non_regression.cc``:
chunks encoded by released versions are stored; every build re-encodes
the same payload and compares byte-for-byte, then decodes every 1- and
2-erasure pattern and compares the recovered chunks — codec output may
never silently change across versions, or mixed-version clusters would
corrupt each other's objects.

    python -m ceph_tpu.tools.ec_non_regression --base corpus --create
    python -m ceph_tpu.tools.ec_non_regression --base corpus --check
"""
from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
from typing import Dict, List, Tuple

from ..ec import registry as ecreg

# the corpus matrix (reference corpus stores per-version directories
# of plugin/parameter combinations)
CONFIGS: List[Tuple[str, Dict[str, str]]] = [
    ("jerasure", {"k": "2", "m": "1",
                  "technique": "reed_sol_van"}),
    ("jerasure", {"k": "8", "m": "4",
                  "technique": "reed_sol_van"}),
    ("jerasure", {"k": "4", "m": "2",
                  "technique": "cauchy_good"}),
    ("jerasure", {"k": "5", "m": "3",
                  "technique": "liberation"}),
    ("isa", {"k": "4", "m": "2"}),
    ("tpu", {"k": "8", "m": "4"}),
    ("shec", {"k": "4", "m": "3", "c": "2"}),
    ("clay", {"k": "4", "m": "2"}),
    ("lrc", {"mapping": "__DD__DD",
             "layers": json.dumps([["_cDD_cDD", ""],
                                   ["cDDD____", ""],
                                   ["____cDDD", ""]])}),
]

PAYLOAD_SIZE = 31 * 1024 + 17          # deliberately unaligned


def payload() -> bytes:
    """Deterministic unaligned payload (reference uses a fixed random
    file committed to the corpus)."""
    out = bytearray()
    x = 0x12345678
    while len(out) < PAYLOAD_SIZE:
        x = (1103515245 * x + 12345) & 0x7FFFFFFF
        out.append(x & 0xFF)
    return bytes(out[:PAYLOAD_SIZE])


def config_dir(base: str, plugin: str, profile: Dict[str, str]) -> str:
    tag = plugin + "".join(
        f"_{k}={profile[k]}" for k in sorted(profile)
        if k not in ("mapping", "layers"))
    if "layers" in profile:
        tag += "_layered"
    return os.path.join(base, tag)


def _codec(plugin: str, profile: Dict[str, str]):
    return ecreg.instance().factory(plugin, dict(profile))


def create(base: str) -> int:
    data = payload()
    for plugin, profile in CONFIGS:
        ec = _codec(plugin, profile)
        n = ec.get_chunk_count()
        chunks = ec.encode(set(range(n)), data)
        d = config_dir(base, plugin, profile)
        os.makedirs(d, exist_ok=True)
        for i, buf in chunks.items():
            with open(os.path.join(d, f"chunk.{i}"), "wb") as f:
                f.write(buf)
        with open(os.path.join(d, "manifest.json"), "w") as f:
            json.dump({"plugin": plugin, "profile": profile,
                       "payload_size": PAYLOAD_SIZE,
                       "chunk_count": n}, f, indent=2, sort_keys=True)
        print(f"created {d}: {n} chunks of "
              f"{len(next(iter(chunks.values())))} bytes")
    return 0


def check(base: str, verbose: bool = False) -> int:
    data = payload()
    failures = 0
    for plugin, profile in CONFIGS:
        d = config_dir(base, plugin, profile)
        manifest_path = os.path.join(d, "manifest.json")
        if not os.path.exists(manifest_path):
            print(f"MISSING corpus dir {d}", file=sys.stderr)
            failures += 1
            continue
        ec = _codec(plugin, profile)
        n = ec.get_chunk_count()
        stored = {}
        for i in range(n):
            with open(os.path.join(d, f"chunk.{i}"), "rb") as f:
                stored[i] = f.read()
        # 1) encode must reproduce the stored chunks bit-exactly
        fresh = ec.encode(set(range(n)), data)
        for i in range(n):
            if bytes(fresh[i]) != stored[i]:
                print(f"FAIL {d}: encode chunk {i} diverged",
                      file=sys.stderr)
                failures += 1
        # 2) decode of every 1- and 2-erasure pattern must recover the
        # stored bytes (reference erasure sweep)
        want = set(range(n))
        patterns = list(itertools.combinations(range(n), 1))
        if n - ec.get_data_chunk_count() >= 2:
            patterns += list(itertools.combinations(range(n), 2))
        for pattern in patterns:
            avail = {i: stored[i] for i in range(n)
                     if i not in pattern}
            try:
                need = ec.minimum_to_decode(set(pattern), set(avail))
            except IOError:
                # non-MDS codes (LRC locality configs, SHEC) declare
                # some erasure patterns unrecoverable — the reference
                # sweep likewise skips what minimum_to_decode rejects
                continue
            try:
                dec = ec.decode(set(pattern),
                                {i: avail[i] for i in need})
            except Exception as e:
                print(f"FAIL {d}: decode {pattern} raised {e!r}",
                      file=sys.stderr)
                failures += 1
                continue
            for i in pattern:
                if bytes(dec[i]) != stored[i]:
                    print(f"FAIL {d}: decode {pattern} chunk {i} "
                          f"diverged", file=sys.stderr)
                    failures += 1
        if verbose:
            print(f"ok {d} ({len(patterns)} erasure patterns)")
    if failures:
        print(f"{failures} non-regression failures", file=sys.stderr)
        return 1
    print(f"corpus ok: {len(CONFIGS)} configs bit-exact")
    return 0


def main(argv: List[str] = None) -> int:
    p = argparse.ArgumentParser(
        prog="ec-non-regression", description=__doc__.splitlines()[0])
    p.add_argument("--base", default="corpus")
    p.add_argument("--create", action="store_true")
    p.add_argument("--check", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true")
    ns = p.parse_args(argv)
    if ns.create:
        return create(ns.base)
    return check(ns.base, ns.verbose)


if __name__ == "__main__":
    sys.exit(main())
